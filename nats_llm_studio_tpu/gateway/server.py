"""OpenAI-compatible HTTP/SSE front door over the NATS serving bus.

``python -m nats_llm_studio_tpu gateway`` binds a plain asyncio HTTP/1.1
server (no web framework — the container ships none) and translates:

    POST /v1/chat/completions   -> ClusterRouter.request_chat[_stream]
    GET  /v1/models             -> {prefix}.list_models
    GET  /healthz               -> gateway + cluster-membership liveness
    GET  /metrics               -> Prometheus exposition (HTTP-edge view)

so any unmodified OpenAI client (``openai`` SDK, curl, LangChain) can talk
to a worker cluster without importing this package. Streaming responses are
Server-Sent Events framed exactly like api.openai.com: one ``data: {chunk}``
per delta, a final chunk carrying ``finish_reason``, then ``data: [DONE]``,
with ``Connection: close`` delimiting the body.

The gateway stays honest about the bus underneath it:

* every request rides the steered router, so excluded-worker retry hops and
  prefix-cache locality work exactly as for native NATS clients;
* the caller's ``X-Deadline-Ms``/``X-Trace-Id`` headers pass through (and
  are minted when absent), so budgets and traces span the HTTP hop;
* a spent retry budget surfaces as a structured ``503`` with ``Retry-After``
  (:class:`~..serve.router.RouterExhausted`), never a bare string;
* a client that disconnects mid-stream tears the whole chain down: the SSE
  writer aborts, the router stream closes, the transport publishes the
  consumer-gone cancel, and the worker frees its batcher slot.

``response_format`` is validated structurally HERE, before any bus traffic:
a garbled value costs one JSON parse, not a worker round-trip.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Any

from ..obs import (
    LogHistogram,
    PromRenderer,
    Span,
    new_span_id,
    new_trace_id,
    span_context_value,
)
from ..serve.constrain import validate_response_format
from ..serve.qos import (
    ANON_TENANT,
    ApiKeySpec,
    TenantUsage,
    TokenBucket,
    cap_tenant_rows,
    format_priority_header,
    parse_api_keys,
)
from ..serve.router import ClusterRouter, RouterExhausted
from ..transport import ConnectionClosedError, NatsClient, RetryPolicy
from ..transport import protocol as p
from ..transport.envelope import error_is_retryable, shed_cause_of

log = logging.getLogger(__name__)

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 10 * 1024 * 1024

_REASONS = {
    200: "OK",
    400: "Bad Request",
    401: "Unauthorized",
    404: "Not Found",
    405: "Method Not Allowed",
    408: "Request Timeout",
    411: "Length Required",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

# OpenAI chat params the gateway forwards to the engine; everything else in
# the request body is ignored (SDKs send fields this backend has no use
# for — dropping them beats failing them)
_FORWARDED_FIELDS = (
    "model",
    "messages",
    "max_tokens",
    "temperature",
    "top_p",
    "top_k",
    "seed",
    "stop",
    "n",
    "logprobs",
    "top_logprobs",
    "response_format",
)


class BadRequest(ValueError):
    """Client-side payload error: rendered as HTTP 400 before any bus hop."""


def translate_chat_payload(body: Any) -> tuple[dict, bool]:
    """OpenAI ``/v1/chat/completions`` body -> (chat envelope, stream?).

    Structural validation only — semantic limits (n vs slot count, schema
    compilability against the tokenizer) belong to the serving worker.
    Unknown fields are dropped; a missing ``max_tokens`` defers to the
    engine default. Raises :class:`BadRequest` with a client-facing message.
    """
    if not isinstance(body, dict):
        raise BadRequest("request body must be a JSON object")
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise BadRequest("'model' must be a non-empty string")
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise BadRequest("'messages' must be a non-empty array")
    for i, m in enumerate(messages):
        if not isinstance(m, dict) or not isinstance(m.get("role"), str):
            raise BadRequest(f"messages[{i}] must be an object with a 'role'")
    # a garbled response_format must never reach the batcher: validate the
    # structure here (the worker re-validates and also compiles the schema)
    try:
        validate_response_format(body.get("response_format"))
    except ValueError as e:
        raise BadRequest(str(e)) from e
    for name in ("max_tokens", "max_completion_tokens", "n", "top_logprobs"):
        v = body.get(name)
        if v is not None and (isinstance(v, bool) or not isinstance(v, int)):
            raise BadRequest(f"'{name}' must be an integer")
    for name in ("temperature", "top_p"):
        v = body.get(name)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, (int, float))
        ):
            raise BadRequest(f"'{name}' must be a number")
    payload = {k: body[k] for k in _FORWARDED_FIELDS if body.get(k) is not None}
    if "max_tokens" not in payload and body.get("max_completion_tokens") is not None:
        payload["max_tokens"] = body["max_completion_tokens"]
    stream = bool(body.get("stream"))
    return payload, stream


def _error_body(message: str, etype: str, code: str | None = None) -> dict:
    return {
        "error": {
            "message": message,
            "type": etype,
            "param": None,
            "code": code,
        }
    }


def _status_for_error(err: str) -> tuple[int, str, str | None]:
    """Map a worker error-envelope string to (status, type, code)."""
    low = err.lower()
    if "model not found" in low:
        return 404, "invalid_request_error", "model_not_found"
    if "invalid " in low:
        return 400, "invalid_request_error", None
    if "deadline exceeded" in low:
        return 504, "timeout_error", "deadline_exceeded"
    # cause-aware sheds (transport/envelope.py SHED_CAUSES): quota and
    # fair_share are the CALLER's budget — 429, because retrying the same
    # request elsewhere cannot help; the remaining causes are worker-local
    # pressure and fall through to the generic retryable 503 below
    cause = shed_cause_of(err)
    if cause in ("quota", "fair_share"):
        return 429, "rate_limit_error", cause
    if error_is_retryable(err):
        return 503, "overloaded_error", "worker_unavailable"
    return 500, "api_error", None


def _envelope_error_response(err: str) -> tuple[int, dict, dict | None]:
    """(status, OpenAI error body, extra headers) for a worker error
    envelope — the body carries the machine-readable shed cause when the
    error text embeds one, so clients can branch on quota-vs-pressure
    without parsing prose."""
    status, etype, code = _status_for_error(err)
    body = _error_body(err, etype, code)
    cause = shed_cause_of(err)
    if cause:
        body["error"]["cause"] = cause
    extra = {"Retry-After": "1"} if status in (429, 503) else None
    return status, body, extra


class Gateway:
    """One HTTP front door. Owns (or borrows) a :class:`ClusterRouter`.

    ``port=0`` binds an ephemeral port (tests); the bound port is available
    as ``self.port`` after :meth:`start`.
    """

    def __init__(
        self,
        nc: NatsClient,
        *,
        prefix: str = "lmstudio",
        host: str = "127.0.0.1",
        port: int = 8080,
        max_conn: int = 256,
        chat_timeout_s: float = 120.0,
        retry: RetryPolicy | None = None,
        router: ClusterRouter | None = None,
        stale_after_s: float = 5.0,
        prefix_head_chars: int = 256,
        obs_spans: bool | None = None,
        ident: str = "gateway",
        api_keys: str = "",
        tenant_topk: int = 8,
    ):
        self.nc = nc
        self.prefix = prefix
        self.host = host
        self.port = port
        self.chat_timeout_s = chat_timeout_s
        self.retry = retry or RetryPolicy(max_attempts=3, retry_on_timeout=True)
        self._own_router = router is None
        self.router = router or ClusterRouter(
            nc,
            prefix=prefix,
            stale_after_s=stale_after_s,
            prefix_head_chars=prefix_head_chars,
        )
        if obs_spans is None:
            obs_spans = os.environ.get(
                "OBS_SPANS", "1"
            ).strip().lower() not in ("0", "false", "off")
        self.obs_spans = obs_spans
        self.ident = ident  # worker_id stamped on this gateway's spans
        # cluster advert cadence (0 disables): the aggregator scrapes every
        # advert member's directed metrics.prom subject, so advertising is
        # what folds lmstudio_gateway_* into the cluster exposition. The
        # role marks the advert metrics-only — the router must never route
        # a chat at the gateway (serve/router.py filters role "gateway").
        self.advert_interval_s = float(
            os.environ.get("GATEWAY_ADVERT_INTERVAL_S", "1.0") or 0
        )
        self._advert_seq = 0
        self._advert_task: asyncio.Task | None = None
        self._metrics_sub = None
        self._sem = asyncio.Semaphore(max(1, max_conn))
        self._server: asyncio.base_events.Server | None = None
        self.requests_total = 0
        self.streams_total = 0
        self.client_disconnects = 0
        self.retry_hops_total = 0  # extra attempts behind served replies
        self.sse_open = 0  # SSE streams currently being written
        self._responses_by_status: dict[int, int] = {}
        # TTFT as the HTTP client experiences it: request-line read to
        # first response byte (full reply for non-streaming, SSE preamble
        # for streams) — the edge-side counterpart of the workers'
        # lmstudio_ttft_ms, including routing, retries, and queueing
        self._ttft_ms = LogHistogram()
        # multi-tenant QoS front door (serve/qos.py): the API_KEYS table
        # maps bearer keys to (tenant, priority class, weight, rate,
        # monthly quota). Empty = auth off, everyone is the anonymous
        # standard tenant — exactly the pre-QoS behavior. parse_api_keys
        # raises on a malformed spec: fail at boot, not at first request.
        self.api_keys = parse_api_keys(api_keys)
        self.tenant_topk = int(tenant_topk)
        self._buckets: dict[str, TokenBucket] = {
            k: TokenBucket(s.rps) for k, s in self.api_keys.items() if s.rps > 0
        }
        self._usage = TenantUsage()
        self._tenant_requests: dict[str, int] = {}
        # 401/429 refusals by tenant ("unknown" for bad/missing keys)
        self._tenant_rejected: dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "Gateway":
        if self._own_router:
            await self.router.start()
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        # directed scrape surface (same shape as the workers'): the fleet
        # aggregator requests {prefix}.worker.<id>.metrics.prom for every
        # advert member, so this sub + the advert loop below are all it
        # takes for the HTTP-edge families to join the cluster exposition
        self._metrics_sub = await self.nc.subscribe(
            f"{self.prefix}.worker.{self.ident}.metrics.prom",
            cb=self._on_metrics_prom,
        )
        if self.advert_interval_s > 0:
            self._advert_task = asyncio.ensure_future(self._advert_loop())
        log.info("gateway on http://%s:%d -> %s.*", self.host, self.port, self.prefix)
        return self

    async def stop(self) -> None:
        if self._advert_task is not None:
            self._advert_task.cancel()
            self._advert_task = None
        if self._metrics_sub is not None:
            try:
                await self._metrics_sub.unsubscribe()
            except (ConnectionError, ValueError):
                pass
            self._metrics_sub = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._own_router:
            await self.router.stop()

    async def _on_metrics_prom(self, msg) -> None:
        """Directed metrics.prom — raw Prometheus text, exactly like the
        workers' subject (scrapers want the body, not a JSON envelope)."""
        if msg.reply:
            try:
                await self.nc.publish(msg.reply, self.render_prometheus().encode())
            except (ConnectionError, ValueError):
                pass

    def build_advert(self) -> dict:
        """Minimal membership advert: identity + role "gateway". Serves no
        chat (the router filters the role out of its candidates); exists so
        the aggregator discovers and scrapes this process like a worker."""
        return {
            "worker_id": self.ident,
            "role": "gateway",
            "queue_depth": 0,
            "brownout": 0,
            "hbm_headroom": 1.0,
            "models": [],
            "draining": False,
            "heads": [],
            "seq": self._advert_seq,
        }

    async def _advert_loop(self) -> None:
        try:
            while True:
                self._advert_seq += 1
                try:
                    await self.nc.publish(
                        f"{self.prefix}.cluster.adverts",
                        json.dumps(self.build_advert(),
                                   separators=(",", ":")).encode(),
                    )
                except (ConnectionError, ValueError):
                    pass  # reconnect in flight; the next tick re-advertises
                await asyncio.sleep(self.advert_interval_s)
        except asyncio.CancelledError:
            return

    # -- HTTP plumbing -------------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            if self._sem.locked():
                await self._respond(
                    writer, 503,
                    _error_body("gateway connection limit reached",
                                "overloaded_error", "gateway_overloaded"),
                    extra={"Retry-After": "1"},
                )
                return
            async with self._sem:
                await self._handle_one(reader, writer)
        except (ConnectionResetError, BrokenPipeError):
            self.client_disconnects += 1
        except Exception:  # noqa: BLE001 — one bad conn must not kill the server
            log.exception("gateway: connection handler failed")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _handle_one(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self.requests_total += 1
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return  # client went away before sending a request
        except asyncio.LimitOverrunError:
            await self._respond(
                writer, 413, _error_body("headers too large", "invalid_request_error")
            )
            return
        if len(head) > MAX_HEADER_BYTES:
            await self._respond(
                writer, 413, _error_body("headers too large", "invalid_request_error")
            )
            return
        try:
            request_line, headers = _parse_head(head)
            method, target = request_line
        except ValueError:
            await self._respond(
                writer, 400, _error_body("malformed HTTP request", "invalid_request_error")
            )
            return
        path = target.split("?", 1)[0]

        if method == "GET" and path == "/healthz":
            await self._respond(writer, 200, {
                "status": "ok",
                "cluster_members": len(self.router.members()),
                "requests_total": self.requests_total,
            })
            return
        if method == "GET" and path == "/metrics":
            await self._respond_text(writer, 200, self.render_prometheus())
            return
        if method == "GET" and path == "/v1/models":
            # key validity is enforced (the model list is tenant surface),
            # but listing consumes no rate-bucket tokens or quota
            _, auth_err = self._resolve_key(headers)
            if auth_err is not None:
                await self._respond(writer, auth_err[0], auth_err[1])
                return
            await self._get_models(writer)
            return
        if path == "/v1/chat/completions":
            if method != "POST":
                await self._respond(
                    writer, 405,
                    _error_body("use POST", "invalid_request_error"),
                    extra={"Allow": "POST"},
                )
                return
            spec, auth_err = self._resolve_key(headers)
            if auth_err is not None:
                await self._respond(writer, auth_err[0], auth_err[1])
                return
            admit_err = self._admit(spec)
            if admit_err is not None:
                await self._respond(
                    writer, admit_err[0], admit_err[1], extra=admit_err[2]
                )
                return
            body = await self._read_body(reader, writer, headers)
            if body is None:
                return
            await self._chat(reader, writer, headers, body, spec)
            return
        await self._respond(
            writer, 404,
            _error_body(f"no route for {method} {path}", "invalid_request_error"),
        )

    async def _read_body(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        headers: dict[str, str],
    ) -> bytes | None:
        """POST body via Content-Length (chunked uploads are refused — no
        client this gateway targets sends them for JSON)."""
        if "chunked" in headers.get("transfer-encoding", "").lower():
            await self._respond(
                writer, 411,
                _error_body("chunked request bodies are not supported; "
                            "send Content-Length", "invalid_request_error"),
            )
            return None
        try:
            length = int(headers.get("content-length", "0"))
        except ValueError:
            length = -1
        if length < 0:
            await self._respond(
                writer, 400, _error_body("bad Content-Length", "invalid_request_error")
            )
            return None
        if length > MAX_BODY_BYTES:
            await self._respond(
                writer, 413, _error_body("request body too large", "invalid_request_error")
            )
            return None
        try:
            return await reader.readexactly(length)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return None

    async def _respond(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        body: dict,
        extra: dict[str, str] | None = None,
    ) -> int:
        raw = json.dumps(body, separators=(",", ":")).encode()
        await self._write_response(
            writer, status, "application/json", raw, extra
        )
        return status

    async def _respond_text(
        self, writer: asyncio.StreamWriter, status: int, text: str
    ) -> int:
        await self._write_response(
            writer, status, "text/plain; version=0.0.4; charset=utf-8",
            text.encode(),
        )
        return status

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        content_type: str,
        raw: bytes,
        extra: dict[str, str] | None = None,
    ) -> None:
        self._responses_by_status[status] = (
            self._responses_by_status.get(status, 0) + 1
        )
        lines = [
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(raw)}",
            "Connection: close",
        ]
        for k, v in (extra or {}).items():
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + raw)
        await writer.drain()

    def render_prometheus(self) -> str:
        """HTTP-edge metrics: statuses, streams, retry hops behind served
        replies, and TTFT as the *client* saw it (routing + retries
        included) — the complement of the workers' engine-side families."""
        r = PromRenderer(default_labels={"gateway": self.ident})
        r.counter("lmstudio_gateway_requests_total", self.requests_total,
                  help="HTTP requests accepted (any route)")
        for status in sorted(self._responses_by_status):
            r.counter("lmstudio_gateway_responses_total",
                      self._responses_by_status[status],
                      labels={"status": str(status)},
                      help="HTTP responses by status code")
        r.counter("lmstudio_gateway_streams_total", self.streams_total,
                  help="SSE chat streams started")
        r.gauge("lmstudio_gateway_sse_open", self.sse_open,
                help="SSE streams currently being written")
        r.counter("lmstudio_gateway_client_disconnects_total",
                  self.client_disconnects,
                  help="clients gone before their response completed")
        r.counter("lmstudio_gateway_retry_hops_total", self.retry_hops_total,
                  help="extra routed attempts behind served chat replies")
        r.histogram("lmstudio_gateway_ttft_ms", self._ttft_ms.snapshot(),
                    help="request-line read to first response byte, ms")
        # per-tenant edge families under the same top-K + "other" cardinality
        # cap as the workers' lmstudio_tenant_* families (serve/qos.py)
        for tenant, v in sorted(cap_tenant_rows(
            self._tenant_requests, self.tenant_topk
        ).items()):
            r.counter("lmstudio_gateway_tenant_requests_total", v,
                      labels={"tenant": tenant},
                      help="chat requests accepted past auth, by tenant")
        for tenant, v in sorted(cap_tenant_rows(
            self._tenant_rejected, self.tenant_topk
        ).items()):
            r.counter("lmstudio_gateway_tenant_rejected_total", v,
                      labels={"tenant": tenant},
                      help="401/429 refusals (bad key, rate limit, monthly "
                           "quota), by tenant; 'unknown' = unauthenticated")
        usage_rows = {
            t: row["tokens"] for t, row in self._usage.snapshot().items()
        }
        for tenant, v in sorted(cap_tenant_rows(
            usage_rows, self.tenant_topk
        ).items()):
            r.counter("lmstudio_gateway_tenant_tokens_total", v,
                      labels={"tenant": tenant},
                      help="completion tokens charged this month, by tenant")
        return r.render()

    # -- multi-tenant QoS front door -----------------------------------------

    def _resolve_key(
        self, http_headers: dict[str, str]
    ) -> tuple[ApiKeySpec | None, tuple[int, dict] | None]:
        """Authenticate the request: (key spec, None) on success, (None,
        (status, body)) on refusal. With no API_KEYS configured every
        caller passes as the anonymous standard tenant (spec None)."""
        if not self.api_keys:
            return None, None
        auth = http_headers.get("authorization", "")
        scheme, _, key = auth.partition(" ")
        key = key.strip()
        if not auth or scheme.lower() != "bearer" or not key:
            self._tenant_rejected["unknown"] = (
                self._tenant_rejected.get("unknown", 0) + 1
            )
            return None, (401, _error_body(
                "missing API key: pass 'Authorization: Bearer <key>'",
                "authentication_error", "invalid_api_key",
            ))
        spec = self.api_keys.get(key)
        if spec is None:
            self._tenant_rejected["unknown"] = (
                self._tenant_rejected.get("unknown", 0) + 1
            )
            return None, (401, _error_body(
                "invalid API key", "authentication_error", "invalid_api_key",
            ))
        return spec, None

    def _admit(
        self, spec: ApiKeySpec | None
    ) -> tuple[int, dict, dict[str, str]] | None:
        """Rate-limit + monthly-quota gate for an authenticated chat:
        None = admitted, else (status, body, extra headers) for the 429."""
        if spec is None:
            return None
        bucket = self._buckets.get(spec.key)
        if bucket is not None and not bucket.take():
            self._tenant_rejected[spec.tenant] = (
                self._tenant_rejected.get(spec.tenant, 0) + 1
            )
            retry_after = max(1, int(bucket.retry_after_s() + 0.999))
            body = _error_body(
                f"rate limit exceeded for tenant {spec.tenant}: "
                f"{spec.rps:g} requests/s (shed_cause=quota)",
                "rate_limit_error", "rate_limit_exceeded",
            )
            body["error"]["cause"] = "quota"
            return 429, body, {"Retry-After": str(retry_after)}
        if spec.monthly_tokens > 0 and self._usage.over_quota(
            spec.tenant, spec.monthly_tokens
        ):
            self._tenant_rejected[spec.tenant] = (
                self._tenant_rejected.get(spec.tenant, 0) + 1
            )
            body = _error_body(
                f"monthly token quota exhausted for tenant {spec.tenant}: "
                f"{self._usage.tokens_used(spec.tenant)} of "
                f"{spec.monthly_tokens} tokens used (shed_cause=quota)",
                "rate_limit_error", "insufficient_quota",
            )
            body["error"]["cause"] = "quota"
            # a monthly quota resets at the month boundary, not in seconds;
            # 3600 keeps well-behaved clients from hammering the refusal
            return 429, body, {"Retry-After": "3600"}
        return None

    def _charge_usage(self, spec: ApiKeySpec | None, response: dict) -> None:
        """Book a served chat's completion tokens against the tenant's
        month (anonymous traffic is tracked too — it shows in /metrics)."""
        usage = response.get("usage") or {}
        tokens = usage.get("completion_tokens") or 0
        tenant = spec.tenant if spec is not None else ANON_TENANT
        try:
            self._usage.charge(tenant, int(tokens))
        except (TypeError, ValueError):
            self._usage.charge(tenant, 0)

    # -- routes --------------------------------------------------------------

    async def _get_models(self, writer: asyncio.StreamWriter) -> None:
        try:
            msg = await self.nc.request(
                f"{self.prefix}.list_models", b"{}", timeout=30.0
            )
            env = json.loads(msg.payload or b"{}")
        except (asyncio.TimeoutError, ConnectionClosedError, ValueError) as e:
            await self._respond(
                writer, 503,
                _error_body(f"no worker answered list_models: {e}",
                            "overloaded_error", "worker_unavailable"),
                extra={"Retry-After": "1"},
            )
            return
        if not env.get("ok"):
            status, etype, code = _status_for_error(str(env.get("error", "")))
            await self._respond(
                writer, status, _error_body(str(env.get("error")), etype, code)
            )
            return
        listing = (env.get("data") or {}).get("models") or {"object": "list", "data": []}
        await self._respond(writer, 200, listing)

    def _bus_headers(
        self, http_headers: dict[str, str], spec: ApiKeySpec | None = None
    ) -> dict[str, str]:
        """NATS headers for this HTTP request: trace id and deadline budget
        pass through from the client when stamped, minted otherwise. An
        authenticated key stamps the resolved tenant + priority class (with
        its fair-share weight override) — NEVER the client's own claim, so
        an HTTP caller cannot spoof premium through the gateway."""
        out = {p.TRACE_HEADER: http_headers.get(
            p.TRACE_HEADER.lower(), new_trace_id()
        )}
        deadline = http_headers.get(p.DEADLINE_HEADER.lower())
        if deadline:
            out[p.DEADLINE_HEADER] = deadline
        if spec is not None:
            out[p.TENANT_HEADER] = spec.tenant
            out[p.PRIORITY_HEADER] = format_priority_header(
                spec.priority, spec.weight
            )
        return out

    async def _chat(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        http_headers: dict[str, str],
        raw_body: bytes,
        spec: ApiKeySpec | None = None,
    ) -> None:
        try:
            body = json.loads(raw_body or b"null")
        except ValueError:
            await self._respond(
                writer, 400, _error_body("request body is not valid JSON",
                                         "invalid_request_error")
            )
            return
        try:
            payload, stream = translate_chat_payload(body)
        except BadRequest as e:
            await self._respond(
                writer, 400, _error_body(str(e), "invalid_request_error")
            )
            return
        tenant = spec.tenant if spec is not None else ANON_TENANT
        self._tenant_requests[tenant] = self._tenant_requests.get(tenant, 0) + 1
        payload["stream"] = stream
        bus_headers = self._bus_headers(http_headers, spec)
        # the gateway span is the root of the cross-process trace: its id
        # rides the Traceparent header so every router attempt (and, through
        # it, every worker hop) parents under this request
        trace_id = bus_headers[p.TRACE_HEADER]
        root_span_id = new_span_id()
        bus_headers[p.TRACEPARENT_HEADER] = span_context_value(
            trace_id, root_span_id
        )
        span_t0 = time.time()
        t0 = time.monotonic()
        status = 0  # 0 = client gone before any response byte
        try:
            if stream:
                status = await self._chat_stream(
                    reader, writer, payload, bus_headers, t0, spec
                )
            else:
                status = await self._chat_once(
                    writer, payload, bus_headers, t0, spec
                )
        finally:
            self._emit_span(Span(
                trace_id=trace_id, span_id=root_span_id,
                stage="gateway.request", worker_id=self.ident,
                t0=span_t0, t1=time.time(),
                attrs={"model": payload.get("model", ""),
                       "stream": stream, "status": status},
            ).to_dict())

    def _emit_span(self, span: dict) -> None:
        """Fire-and-forget publish of the gateway root span; never fatal
        (and never blocking the HTTP response path)."""
        if not self.obs_spans:
            return

        async def _pub() -> None:
            try:
                await self.nc.publish(
                    f"{self.prefix}.obs.spans",
                    json.dumps({"spans": [span]}, separators=(",", ":")).encode(),
                )
            except (ConnectionError, ValueError):
                pass

        asyncio.ensure_future(_pub())

    def _count_retry_hops(self, response: dict) -> None:
        """Served replies report the winning attempt number in their trace
        stats; anything past the first attempt was a retry hop."""
        trace = (response.get("stats") or {}).get("trace") or {}
        attempt = trace.get("attempt")
        if isinstance(attempt, int) and attempt > 1:
            self.retry_hops_total += attempt - 1

    async def _chat_once(
        self,
        writer: asyncio.StreamWriter,
        payload: dict,
        bus_headers: dict[str, str],
        t0: float,
        spec: ApiKeySpec | None = None,
    ) -> int:
        try:
            msg = await self.router.request_chat(
                payload,
                timeout=self.chat_timeout_s,
                headers=bus_headers,
                retry=self.retry,
                raise_on_exhausted=True,
            )
            env = json.loads(msg.payload or b"{}")
        except RouterExhausted as e:
            return await self._respond_exhausted(writer, e)
        except (asyncio.TimeoutError, ConnectionClosedError) as e:
            return await self._respond(
                writer, 503,
                _error_body(f"no worker answered: {e}", "overloaded_error",
                            "worker_unavailable"),
                extra={"Retry-After": "1"},
            )
        except ValueError:
            return await self._respond(
                writer, 500, _error_body("worker reply was not JSON", "api_error")
            )
        if not env.get("ok"):
            status, body, extra = _envelope_error_response(
                str(env.get("error", ""))
            )
            return await self._respond(writer, status, body, extra=extra)
        response = (env.get("data") or {}).get("response") or {}
        response.setdefault("id", f"chatcmpl-{bus_headers[p.TRACE_HEADER]}")
        response.setdefault("created", int(time.time()))
        self._count_retry_hops(response)
        self._charge_usage(spec, response)
        self._ttft_ms.record((time.monotonic() - t0) * 1000.0)
        return await self._respond(writer, 200, response)

    async def _respond_exhausted(
        self, writer: asyncio.StreamWriter, e: RouterExhausted
    ) -> int:
        retry_after = max(1, int(e.retry_after_s + 0.999))
        body = _error_body(e.detail(), "overloaded_error", "worker_unavailable")
        body["error"]["retry_after_s"] = retry_after
        if e.worker_id:
            body["error"]["last_worker"] = e.worker_id
        return await self._respond(
            writer, 503, body, extra={"Retry-After": str(retry_after)}
        )

    # -- SSE streaming -------------------------------------------------------

    async def _chat_stream(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        payload: dict,
        bus_headers: dict[str, str],
        t0: float,
        spec: ApiKeySpec | None = None,
    ) -> int:
        self.streams_total += 1
        chat_id = f"chatcmpl-{bus_headers[p.TRACE_HEADER]}"
        created = int(time.time())
        agen = self.router.request_chat_stream(
            payload,
            timeout=self.chat_timeout_s,
            headers=bus_headers,
            retry=self.retry,
            raise_on_exhausted=True,
        )
        # any bytes (or EOF) from the client after the request mean it is
        # gone — SSE clients never write. Racing the watcher against each
        # bus message makes a mid-stream disconnect cancel the slot within
        # one chunk instead of at socket-buffer pressure.
        eof_task = asyncio.ensure_future(reader.read(1))
        preamble_sent = False
        disconnected = False
        try:
            while True:
                step = asyncio.ensure_future(agen.__anext__())
                await asyncio.wait({step, eof_task}, return_when=asyncio.FIRST_COMPLETED)
                if eof_task.done() and not step.done():
                    step.cancel()
                    try:
                        await step
                    except BaseException:  # noqa: BLE001 — cancelled anext
                        pass
                    disconnected = True
                    break
                try:
                    msg = await step
                except StopAsyncIteration:
                    break
                except RouterExhausted as e:
                    if not preamble_sent:
                        return await self._respond_exhausted(writer, e)
                    raise
                except (asyncio.TimeoutError, ConnectionClosedError) as e:
                    if not preamble_sent:
                        return await self._respond(
                            writer, 503,
                            _error_body(f"no worker answered: {e}",
                                        "overloaded_error", "worker_unavailable"),
                            extra={"Retry-After": "1"},
                        )
                    raise
                terminal = bool(msg.headers and "Nats-Stream-Done" in msg.headers)
                try:
                    env = json.loads(msg.payload or b"{}")
                except ValueError:
                    env = {}
                if terminal:
                    if not env.get("ok"):
                        err = str(env.get("error", "stream failed"))
                        if not preamble_sent:
                            status, body, extra = _envelope_error_response(err)
                            return await self._respond(
                                writer, status, body, extra=extra,
                            )
                        # headers are gone: surface the error in-band, the
                        # way api.openai.com does mid-stream (the cause
                        # token, if any, rides inside the message text)
                        await self._sse(writer, {"error": _error_body(
                            err, *_status_for_error(err)[1:])["error"]})
                        break
                    response = (env.get("data") or {}).get("response") or {}
                    self._count_retry_hops(response)
                    self._charge_usage(spec, response)
                    if not preamble_sent:
                        await self._sse_start(writer, t0)
                        preamble_sent = True
                    for choice in response.get("choices") or [{}]:
                        fin = {
                            "id": chat_id,
                            "object": "chat.completion.chunk",
                            "created": created,
                            "model": payload.get("model", ""),
                            "choices": [{
                                "index": choice.get("index", 0),
                                "delta": {},
                                "finish_reason": choice.get("finish_reason", "stop"),
                            }],
                        }
                        if response.get("usage"):
                            fin["usage"] = response["usage"]
                        await self._sse(writer, fin)
                    break
                chunk = (env.get("data") or {}).get("chunk")
                if not isinstance(chunk, dict):
                    continue
                chunk.setdefault("id", chat_id)
                chunk.setdefault("created", created)
                if not preamble_sent:
                    await self._sse_start(writer, t0)
                    preamble_sent = True
                await self._sse(writer, chunk)
            if preamble_sent and not disconnected:
                writer.write(b"data: [DONE]\n\n")
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            disconnected = True
        finally:
            eof_task.cancel()
            try:
                await eof_task
            except BaseException:  # noqa: BLE001
                pass
            # closing the router stream propagates consumer-gone down the
            # transport: the worker sees <inbox>.cancel and frees the slot
            await agen.aclose()
            if preamble_sent:
                self.sse_open -= 1
            if disconnected:
                self.client_disconnects += 1
        if disconnected and not preamble_sent:
            return 499  # client closed before any response byte (nginx idiom)
        return 200 if preamble_sent else 0

    async def _sse_start(self, writer: asyncio.StreamWriter, t0: float) -> None:
        """First SSE byte: the stream is now a committed 200 — count it,
        open the gauge, and record client-perceived TTFT."""
        await self._sse_preamble(writer)
        self._responses_by_status[200] = self._responses_by_status.get(200, 0) + 1
        self.sse_open += 1
        self._ttft_ms.record((time.monotonic() - t0) * 1000.0)

    @staticmethod
    async def _sse_preamble(writer: asyncio.StreamWriter) -> None:
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n"
            b"\r\n"
        )
        await writer.drain()

    @staticmethod
    async def _sse(writer: asyncio.StreamWriter, obj: dict) -> None:
        writer.write(b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n")
        await writer.drain()


def _parse_head(head: bytes) -> tuple[tuple[str, str], dict[str, str]]:
    """(method, target), lower-cased header dict — or ValueError."""
    lines = head.decode("latin-1").split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
        raise ValueError(f"bad request line: {lines[0]!r}")
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, sep, v = line.partition(":")
        if not sep:
            raise ValueError(f"bad header line: {line!r}")
        headers[k.strip().lower()] = v.strip()
    return (parts[0], parts[1]), headers

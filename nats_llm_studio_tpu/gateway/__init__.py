from .server import Gateway, translate_chat_payload

__all__ = ["Gateway", "translate_chat_payload"]

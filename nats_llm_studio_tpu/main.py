"""Worker process entrypoint.

The reference is a library with no ``main()`` — its README tells embedders to
wire config/connect/subscribe themselves (SURVEY.md §1 "critical structural
fact"). This CLI is that wiring, made first-class:

    python -m nats_llm_studio_tpu serve            # worker against NATS_URL
    python -m nats_llm_studio_tpu serve --embedded-broker [--port 4222]
    python -m nats_llm_studio_tpu broker --port 4222 [--store-dir ./nats_data]
    python -m nats_llm_studio_tpu route                # standalone cluster router
    python -m nats_llm_studio_tpu gateway [--port 8080]  # OpenAI-compatible HTTP front door
    python -m nats_llm_studio_tpu obs                  # fleet metrics/trace aggregator
    python -m nats_llm_studio_tpu autoscale            # elastic worker autoscaler
    python -m nats_llm_studio_tpu publish <model.gguf> <publisher>/<name>
    python -m nats_llm_studio_tpu chat <model_id> "prompt..."

Env contract (reference README.md:489-494, minus the LM Studio URL):
NATS_URL, LMSTUDIO_MODELS_DIR, NATS_QUEUE_GROUP, plus MESH_SHAPE (legacy
alias TPU_MESH; default "auto" = all local devices on tp),
JAX_COMPILE_CACHE_DIR, MAX_BATCH_SLOTS, MAX_SEQ_LEN. Multi-host meshes
initialize through ``jax.distributed`` when JAX_COORDINATOR_ADDRESS is set.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys

from .config import WorkerConfig

log = logging.getLogger("nats_llm_studio_tpu")


def _maybe_init_distributed() -> None:
    """Join a multi-host DCN mesh when coordinator env vars are present
    (SURVEY.md §5 distributed-backend: jax.distributed + PJRT over DCN)."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(os.environ.get("JAX_NUM_PROCESSES", "1")),
        process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
    )
    log.info("joined distributed mesh: %d devices", len(jax.devices()))


async def _run_serve(args: argparse.Namespace) -> None:
    from .serve import Worker
    from .serve.registry import LocalRegistry
    from .store import JetStreamStoreModule, ModelStore
    from .transport import EmbeddedBroker, connect
    from .transport import faults
    from .transport.jetstream import ObjectStore

    cfg = WorkerConfig()
    # process-wide JAX knobs (persistent compile cache) must land before
    # the first compile — i.e. before mesh build and any engine load
    cfg.configure_jax()
    # deterministic chaos harness (transport/faults.py): only active when
    # CHAOS_SPEC is set — zero-cost otherwise
    plan = faults.plan_from_env()
    if plan is not None:
        faults.install(plan)
    broker = None
    if args.embedded_broker:
        broker = await EmbeddedBroker(port=args.port).start()
        JetStreamStoreModule(broker, store_dir=args.store_dir).install()
        cfg.nats_url = broker.url
        log.info("embedded broker on %s", broker.url)

    _maybe_init_distributed()
    from .parallel import serving_mesh

    mesh = serving_mesh(cfg.mesh_shape)
    if mesh is not None:
        log.info("mesh: %s", dict(mesh.shape))
    else:
        log.info("mesh: none (single device or MESH_SHAPE=off)")

    nc = await connect(cfg.nats_url, name="store-client")
    schemes = tuple(s for s in cfg.url_pull_schemes.split(",") if s)
    store = ModelStore(cfg.models_dir, objstore=ObjectStore(nc), bucket=cfg.bucket,
                       url_schemes=schemes, max_url_pull_bytes=cfg.max_url_pull_bytes)
    registry = LocalRegistry(
        store, mesh=mesh, max_seq_len=cfg.max_seq_len, max_batch_slots=cfg.max_batch_slots,
        quant=cfg.quant_mode, kv_quant=cfg.kv_quant_mode,
        wquant_group=cfg.wquant_group,
        admit_queue_limit=cfg.admit_queue_limit, admit_max_age_ms=cfg.admit_max_age_ms,
        prefix_cache_blocks=cfg.prefix_cache_blocks,
        spec_decode_k=cfg.spec_decode_k, spec_max_active=cfg.spec_max_active,
        brownout=cfg.brownout,
        kv_paged=cfg.kv_paged, kv_block_tokens=cfg.kv_block_tokens,
        kv_pool_blocks=cfg.kv_pool_blocks,
        kv_host_pool_bytes=cfg.kv_host_pool_bytes,
        restart_backoff_s=cfg.engine_restart_backoff_s,
        restart_backoff_max_s=cfg.engine_restart_backoff_max_s,
        max_restarts=cfg.engine_max_restarts,
        restart_window_s=cfg.engine_restart_window_s,
        obs_recorder=cfg.obs_recorder,
        obs_recorder_interval_ms=cfg.obs_recorder_interval_ms,
        obs_dump_dir=cfg.obs_dump_dir,
        worker_id=cfg.worker_id,
        qos_quantum_tokens=cfg.qos_quantum_tokens,
        qos_preempt=cfg.qos_preempt,
    )
    worker = Worker(cfg, registry)
    await worker.start()
    log.info("worker serving %s.* on %s (role: %s, models: %s)",
             cfg.subject_prefix, cfg.nats_url, cfg.worker_role or "monolithic",
             cfg.models_dir)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    log.info("draining...")
    await worker.drain()
    await nc.close()
    if broker is not None:
        await broker.stop()


async def _run_broker(args: argparse.Namespace) -> None:
    from .store import JetStreamStoreModule
    from .transport import EmbeddedBroker

    broker = await EmbeddedBroker(port=args.port).start()
    JetStreamStoreModule(broker, store_dir=args.store_dir).install()
    log.info("broker on %s (store: %s)", broker.url, args.store_dir or "memory")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await broker.stop()


async def _run_route(args: argparse.Namespace) -> None:
    """Standalone cluster router (serve/router.py): subscribes to worker
    adverts and forwards ``{prefix}.route.chat_model`` requests to the best
    live worker. Clients that import this package should prefer the
    in-process ClusterRouter; this process serves everyone else."""
    from .serve.router import RouterProcess
    from .transport import RetryPolicy, connect

    cfg = WorkerConfig()
    nc = await connect(cfg.nats_url, name="tpu-router")
    proc = RouterProcess(
        nc,
        prefix=cfg.subject_prefix,
        stale_after_s=cfg.router_stale_after_s,
        prefix_head_chars=cfg.router_prefix_head_chars,
        chat_timeout_s=cfg.chat_timeout_s,
        retry=RetryPolicy(max_attempts=args.max_attempts, retry_on_timeout=True),
    )
    await proc.start()
    scaler = None
    if cfg.obs_autoscale:
        # OBS_AUTOSCALE=1 embeds the elastic control loop in the router
        # process (serve/autoscaler.py); it shares the connection
        from .serve import Autoscaler

        scaler = Autoscaler.from_config(nc, cfg)
    agg = None
    if cfg.obs_aggregator:
        # OBS_AGGREGATOR=1 embeds the fleet collector in the router process
        # (one fewer process for small clusters); it shares the connection
        from .obs import Aggregator

        agg = Aggregator(
            nc,
            prefix=cfg.subject_prefix,
            scrape_interval_s=cfg.obs_scrape_interval_s,
            stale_after_s=cfg.router_stale_after_s,
            slo_ttft_p95_ms=cfg.slo_ttft_p95_ms,
            slo_window_s=cfg.slo_window_s,
            slo_served_ratio=cfg.slo_served_ratio,
            slo_shed_ratio=cfg.slo_shed_ratio,
            # a co-tenant autoscaler's families ride the cluster exposition
            extra_expositions=(
                [scaler.render_prometheus] if scaler is not None else None
            ),
        )
        await agg.start()
    if scaler is not None:
        await scaler.start()
    log.info("router on %s (prefix %s%s%s)", cfg.nats_url, cfg.subject_prefix,
             ", embedded aggregator" if agg is not None else "",
             ", embedded autoscaler" if scaler is not None else "")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if scaler is not None:
        await scaler.stop()
    if agg is not None:
        await agg.stop()
    await proc.stop()
    await nc.close()


async def _run_obs(args: argparse.Namespace) -> None:
    """Standalone fleet observability collector (obs/aggregator.py): ingests
    cluster adverts and span batches, scrapes every live worker's directed
    metrics.prom subject, serves the merged cluster exposition on
    ``{prefix}.cluster.metrics.prom`` and assembled traces on
    ``{prefix}.debug.trace.<trace_id>``, and emits slo_burn events."""
    from .obs import Aggregator
    from .transport import connect

    cfg = WorkerConfig()
    nc = await connect(cfg.nats_url, name="tpu-obs")
    scaler = None
    if cfg.obs_autoscale:
        from .serve import Autoscaler

        scaler = Autoscaler.from_config(nc, cfg)
    agg = Aggregator(
        nc,
        prefix=cfg.subject_prefix,
        scrape_interval_s=cfg.obs_scrape_interval_s,
        stale_after_s=cfg.router_stale_after_s,
        slo_ttft_p95_ms=cfg.slo_ttft_p95_ms,
        slo_window_s=cfg.slo_window_s,
        slo_served_ratio=cfg.slo_served_ratio,
        slo_shed_ratio=cfg.slo_shed_ratio,
        extra_expositions=(
            [scaler.render_prometheus] if scaler is not None else None
        ),
    )
    await agg.start()
    if scaler is not None:
        await scaler.start()
    log.info("aggregator on %s (prefix %s, scrape %.1fs%s)",
             cfg.nats_url, cfg.subject_prefix, cfg.obs_scrape_interval_s,
             ", embedded autoscaler" if scaler is not None else "")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if scaler is not None:
        await scaler.stop()
    await agg.stop()
    await nc.close()


async def _run_autoscale(args: argparse.Namespace) -> None:
    """Standalone elastic autoscaler (serve/autoscaler.py): watches worker
    adverts and slo_burn events, spawns/drains local worker subprocesses
    within [AUTOSCALE_MIN, AUTOSCALE_MAX], and serves its decision counters
    on ``{prefix}.autoscale.metrics.prom``. OBS_AGGREGATOR=1 co-hosts the
    fleet collector so one process is a complete control plane."""
    from .serve import Autoscaler
    from .transport import connect

    cfg = WorkerConfig()
    nc = await connect(cfg.nats_url, name="tpu-autoscaler")
    scaler = Autoscaler.from_config(nc, cfg)
    agg = None
    if cfg.obs_aggregator:
        from .obs import Aggregator

        agg = Aggregator(
            nc,
            prefix=cfg.subject_prefix,
            scrape_interval_s=cfg.obs_scrape_interval_s,
            stale_after_s=cfg.router_stale_after_s,
            slo_ttft_p95_ms=cfg.slo_ttft_p95_ms,
            slo_window_s=cfg.slo_window_s,
            slo_served_ratio=cfg.slo_served_ratio,
            slo_shed_ratio=cfg.slo_shed_ratio,
            extra_expositions=[scaler.render_prometheus],
        )
        await agg.start()
    await scaler.start()
    log.info("autoscaler on %s (prefix %s, bounds [%d, %d]%s)",
             cfg.nats_url, cfg.subject_prefix, scaler.min_workers,
             scaler.max_workers,
             ", embedded aggregator" if agg is not None else "")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await scaler.stop()
    if agg is not None:
        await agg.stop()
    await nc.close()


async def _run_gateway(args: argparse.Namespace) -> None:
    """OpenAI-compatible HTTP/SSE front door (gateway/server.py): serves
    /v1/chat/completions, /v1/models, and /healthz over the steered cluster
    router, so unmodified OpenAI clients reach the worker cluster."""
    from .gateway import Gateway
    from .transport import RetryPolicy, connect

    cfg = WorkerConfig()
    nc = await connect(cfg.nats_url, name="tpu-gateway")
    gw = Gateway(
        nc,
        prefix=cfg.subject_prefix,
        host=args.host or cfg.gateway_host,
        port=cfg.gateway_port if args.port is None else args.port,
        max_conn=cfg.gateway_max_conn,
        chat_timeout_s=cfg.chat_timeout_s,
        retry=RetryPolicy(max_attempts=args.max_attempts, retry_on_timeout=True),
        stale_after_s=cfg.router_stale_after_s,
        prefix_head_chars=cfg.router_prefix_head_chars,
        api_keys=cfg.api_keys,
        tenant_topk=cfg.qos_tenant_topk,
    )
    await gw.start()
    log.info("gateway on http://%s:%d (bus %s, prefix %s)",
             gw.host, gw.port, cfg.nats_url, cfg.subject_prefix)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await gw.stop()
    await nc.close()


async def _run_publish(args: argparse.Namespace) -> None:
    from .store import ModelStore
    from .transport import connect
    from .transport.jetstream import ObjectStore

    cfg = WorkerConfig()
    nc = await connect(cfg.nats_url)
    store = ModelStore(cfg.models_dir, objstore=ObjectStore(nc), bucket=cfg.bucket)
    store.import_file(args.gguf, args.model_id)
    obj = await store.publish_model(args.model_id)
    print(f"published {obj} to bucket {cfg.bucket!r}")
    await nc.close()


async def _run_chat(args: argparse.Namespace) -> None:
    from .transport import connect

    cfg = WorkerConfig()
    nc = await connect(cfg.nats_url)
    payload = {
        "model": args.model_id,
        "messages": [{"role": "user", "content": args.prompt}],
        "max_tokens": args.max_tokens,
        "temperature": args.temperature,
        "stream": args.stream,
    }
    body = json.dumps(payload).encode()
    subject = cfg.subject("chat_model")
    if args.stream:
        async for msg in nc.request_stream(subject, body, timeout=cfg.chat_timeout_s):
            r = json.loads(msg.payload)
            if (msg.headers or {}).get("Nats-Stream-Done"):
                if not r.get("ok"):
                    print(f"\nerror: {r.get('error')}", file=sys.stderr)
                print()
                break
            delta = r["data"]["chunk"]["choices"][0]["delta"].get("content", "")
            print(delta, end="", flush=True)
    else:
        msg = await nc.request(subject, body, timeout=cfg.chat_timeout_s)
        r = json.loads(msg.payload)
        if not r.get("ok"):
            print(f"error: {r.get('error')}", file=sys.stderr)
            sys.exit(1)
        print(r["data"]["response"]["choices"][0]["message"]["content"])
    await nc.close()


def main(argv: list[str] | None = None) -> None:
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    p = argparse.ArgumentParser(prog="nats-llm-studio-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve", help="run a TPU worker")
    sp.add_argument("--embedded-broker", action="store_true")
    sp.add_argument("--port", type=int, default=4222)
    sp.add_argument("--store-dir", default=None)

    bp = sub.add_parser("broker", help="run the embedded NATS broker + object store")
    bp.add_argument("--port", type=int, default=4222)
    bp.add_argument("--store-dir", default="./nats_data")

    rp = sub.add_parser("route", help="run a standalone cluster router")
    rp.add_argument("--max-attempts", type=int, default=3)

    sub.add_parser("obs", help="run the fleet metrics/trace aggregator")

    sub.add_parser("autoscale", help="run the elastic worker autoscaler")

    gw = sub.add_parser("gateway", help="run the OpenAI-compatible HTTP gateway")
    gw.add_argument("--host", default=None)
    gw.add_argument("--port", type=int, default=None)
    gw.add_argument("--max-attempts", type=int, default=3)

    pp = sub.add_parser("publish", help="import a GGUF and upload it to the bucket")
    pp.add_argument("gguf")
    pp.add_argument("model_id")

    cp = sub.add_parser("chat", help="send a chat request over NATS")
    cp.add_argument("model_id")
    cp.add_argument("prompt")
    cp.add_argument("--max-tokens", type=int, default=256)
    cp.add_argument("--temperature", type=float, default=0.8)
    cp.add_argument("--stream", action="store_true")

    args = p.parse_args(argv)
    runner = {
        "serve": _run_serve,
        "broker": _run_broker,
        "route": _run_route,
        "gateway": _run_gateway,
        "obs": _run_obs,
        "autoscale": _run_autoscale,
        "publish": _run_publish,
        "chat": _run_chat,
    }[args.cmd]
    asyncio.run(runner(args))


if __name__ == "__main__":
    main()

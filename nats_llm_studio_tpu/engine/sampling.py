"""Token sampling: temperature / top-k / top-p, fully vectorized per row.

Per-request parameters are arrays of shape [B] so one jitted decode step can
serve a continuously-batched set of requests with different sampling settings
(SURVEY.md §7: the batcher is on the critical perf path).

Sort-free design: a full-vocab ``sort``+``argsort`` costs several ms per
decode step on TPU (measured ~7 ms at V=49k — comparable to reading all the
model weights). Instead:

* greedy and unrestricted temperature sampling use ``argmax`` /
  Gumbel-max over the full vocab — exact, no sort;
* top-k / top-p restricted rows draw from the top ``CANDIDATES`` logits
  (``lax.top_k``, cheap at fixed small k). top-k above the cap and top-p
  nuclei wider than the cap are truncated to the cap — for peaked LLM
  distributions the mass beyond the top 64 is negligible, and serving
  engines routinely apply the same candidate cap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

CANDIDATES = 64  # static candidate cap for restricted (top-k/top-p) rows
_NEG_INF = jnp.float32(-jnp.inf)


def _pick(logits, gumbel, temperature, top_k, top_p) -> jax.Array:
    """Shared sort-free selection. gumbel: [B, V] standard Gumbel noise."""
    b, v = logits.shape
    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]

    greedy = jnp.argmax(logits, axis=-1)
    # exact unrestricted sampling: argmax(logits/T + G) ~ softmax(logits/T)
    full_pick = jnp.argmax(logits / safe_t + gumbel, axis=-1)

    c = min(CANDIDATES, v)
    cand, cand_idx = jax.lax.top_k(logits, c)  # sorted desc [B, C]
    ranks = jnp.arange(c)[None, :]
    k_eff = jnp.where(top_k <= 0, c, jnp.minimum(top_k, c))[:, None]
    keep = ranks < k_eff
    # top-p over the candidate softmax; always keep the first token that
    # crosses p (so the nucleus is never empty)
    probs = jax.nn.softmax(cand / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_p[:, None]
    g_cand = jnp.take_along_axis(gumbel, cand_idx, axis=-1)
    masked = jnp.where(keep, cand / safe_t, _NEG_INF)
    drawn = jnp.argmax(masked + g_cand, axis=-1)
    cand_pick = jnp.take_along_axis(cand_idx, drawn[:, None], axis=-1)[:, 0]

    restricted = ((top_k > 0) & (top_k < v)) | (top_p < 1.0)
    pick = jnp.where(restricted, cand_pick, full_pick)
    return jnp.where(temperature <= 0.0, greedy, pick).astype(jnp.int32)


def sample(
    logits: jax.Array,  # [B, V] f32
    key: jax.Array,
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,  # 0 = disabled
    top_p: jax.Array | float = 1.0,
) -> jax.Array:
    """Returns sampled token ids [B] int32. temperature <= 0 means greedy
    (per row). top-k and top-p are per-row arrays, not static."""
    gumbel = jax.random.gumbel(key, logits.shape, jnp.float32)
    return _pick(logits, gumbel, temperature, top_k, top_p)


def sample_rows(
    logits: jax.Array,  # [B, V] f32
    seeds: jax.Array,  # [B] int32 — per-row PRNG seed
    steps: jax.Array,  # [B] int32 — per-row step counter
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,
    top_p: jax.Array | float = 1.0,
) -> jax.Array:
    """Per-row deterministic sampling: row i's randomness depends only on
    (seeds[i], steps[i]), never on batch composition — a request replayed
    with the same seed reproduces its completion regardless of what else is
    running in the continuous batch."""

    def row_gumbel(seed, step):
        k = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.gumbel(k, (logits.shape[1],), jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, steps)
    return _pick(logits, gumbel, temperature, top_k, top_p)

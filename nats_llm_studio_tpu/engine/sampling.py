"""Token sampling: temperature / top-k / top-p, fully vectorized per row.

Per-request parameters are arrays of shape [B] so one jitted decode step can
serve a continuously-batched set of requests with different sampling settings
(SURVEY.md §7: the batcher is on the critical perf path).

Sort-free design: a full-vocab ``sort``+``argsort`` costs several ms per
decode step on TPU (measured ~7 ms at V=49k — comparable to reading all the
model weights). Instead:

* greedy and unrestricted temperature sampling use ``argmax`` /
  Gumbel-max over the full vocab — exact, no sort;
* top-k / top-p restricted rows draw from the top ``CANDIDATES`` logits
  (``lax.top_k``, cheap at fixed small k). top-k above the cap and top-p
  nuclei wider than the cap are truncated to the cap — for peaked LLM
  distributions the mass beyond the top 64 is negligible, and serving
  engines routinely apply the same candidate cap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

CANDIDATES = 64  # static candidate cap for restricted (top-k/top-p) rows
_NEG_INF = jnp.float32(-jnp.inf)


def _pick(logits, gumbel, temperature, top_k, top_p, mask=None) -> jax.Array:
    """Shared sort-free selection. gumbel: [B, V] standard Gumbel noise.

    ``mask`` (optional [B, V] bool) bans tokens BEFORE truncation: banned
    logits drop to -inf, so greedy argmax, full Gumbel-max, and the top-k /
    top-p candidate set all operate on the already-constrained distribution
    (constrained decoding stays distribution-exact over the allowed set).
    ``mask=None`` takes the pre-existing code path untouched — unconstrained
    sampling is bit-identical with or without this feature compiled in."""
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
    b, v = logits.shape
    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]

    greedy = jnp.argmax(logits, axis=-1)
    # exact unrestricted sampling: argmax(logits/T + G) ~ softmax(logits/T)
    full_pick = jnp.argmax(logits / safe_t + gumbel, axis=-1)

    c = min(CANDIDATES, v)
    cand, cand_idx = jax.lax.top_k(logits, c)  # sorted desc [B, C]
    ranks = jnp.arange(c)[None, :]
    k_eff = jnp.where(top_k <= 0, c, jnp.minimum(top_k, c))[:, None]
    keep = ranks < k_eff
    # top-p over the candidate softmax; always keep the first token that
    # crosses p (so the nucleus is never empty)
    probs = jax.nn.softmax(cand / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_p[:, None]
    g_cand = jnp.take_along_axis(gumbel, cand_idx, axis=-1)
    masked = jnp.where(keep, cand / safe_t, _NEG_INF)
    drawn = jnp.argmax(masked + g_cand, axis=-1)
    cand_pick = jnp.take_along_axis(cand_idx, drawn[:, None], axis=-1)[:, 0]

    restricted = ((top_k > 0) & (top_k < v)) | (top_p < 1.0)
    pick = jnp.where(restricted, cand_pick, full_pick)
    return jnp.where(temperature <= 0.0, greedy, pick).astype(jnp.int32)


def sample(
    logits: jax.Array,  # [B, V] f32
    key: jax.Array,
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,  # 0 = disabled
    top_p: jax.Array | float = 1.0,
    mask: jax.Array | None = None,  # [B, V] bool — False bans the token
) -> jax.Array:
    """Returns sampled token ids [B] int32. temperature <= 0 means greedy
    (per row). top-k and top-p are per-row arrays, not static."""
    gumbel = jax.random.gumbel(key, logits.shape, jnp.float32)
    return _pick(logits, gumbel, temperature, top_k, top_p, mask=mask)


def sample_rows(
    logits: jax.Array,  # [B, V] f32
    seeds: jax.Array,  # [B] int32 — per-row PRNG seed
    steps: jax.Array,  # [B] int32 — per-row step counter
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,
    top_p: jax.Array | float = 1.0,
    mask: jax.Array | None = None,  # [B, V] bool — False bans the token
) -> jax.Array:
    """Per-row deterministic sampling: row i's randomness depends only on
    (seeds[i], steps[i]), never on batch composition — a request replayed
    with the same seed reproduces its completion regardless of what else is
    running in the continuous batch."""

    def row_gumbel(seed, step):
        k = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.gumbel(k, (logits.shape[1],), jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, steps)
    return _pick(logits, gumbel, temperature, top_k, top_p, mask=mask)


# ---------------------------------------------------------------------------
# speculative decoding: rejection-sampling acceptance (serve/spec.py design)
# ---------------------------------------------------------------------------


def _log_weights(logits, temperature, top_k, top_p, mask=None) -> jax.Array:
    """Full-vocab log-weights ``w`` with softmax(w) equal to the
    distribution ``_pick`` draws from for temperature > 0 rows — same
    CANDIDATES cap, same top-k/top-p truncation rules, token for token.
    Non-selectable tokens sit at -inf. Greedy rows (temperature <= 0) are
    the caller's job: their "distribution" is a point mass at argmax.

    ``mask`` bans tokens before truncation, mirroring ``_pick`` — so spec
    acceptance against a constrained sampler stays distribution-exact.
    ``mask=None`` is the pre-existing code path, bit for bit."""
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
    b, v = logits.shape
    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]

    c = min(CANDIDATES, v)
    cand, cand_idx = jax.lax.top_k(logits, c)
    ranks = jnp.arange(c)[None, :]
    k_eff = jnp.where(top_k <= 0, c, jnp.minimum(top_k, c))[:, None]
    keep = ranks < k_eff
    probs = jax.nn.softmax(cand / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_p[:, None]
    # scatter the kept candidates back onto the full vocab axis
    rows = jnp.arange(b)[:, None]
    masked = jnp.full((b, v), _NEG_INF).at[rows, cand_idx].set(
        jnp.where(keep, cand / safe_t, _NEG_INF)
    )
    restricted = (((top_k > 0) & (top_k < v)) | (top_p < 1.0))[:, None]
    return jnp.where(restricted, masked, logits / safe_t)


def spec_accept_rows(
    logits: jax.Array,  # [B, T, V] f32 — verify logits, T = k + 1
    drafts: jax.Array,  # [B, k] int32 — proposed draft tokens
    draft_len: jax.Array,  # [B] int32 — valid drafts per row (0..k)
    seeds: jax.Array,  # [B] int32 — per-row PRNG seed (sample_rows contract)
    steps: jax.Array,  # [B] int32 — per-row step counter at verify position 0
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,
    top_p: jax.Array | float = 1.0,
    mask: jax.Array | None = None,  # [B, T, V] bool — per-position bans
) -> tuple[jax.Array, jax.Array]:
    """Rejection-sampling acceptance for prompt-lookup drafts.

    Position j's model distribution is ``p_j`` = what the plain sampler
    would draw from (``_log_weights``; point mass at argmax for greedy
    rows). The draft proposal is DETERMINISTIC (a point mass at d_j), so
    the Leviathan et al. rule collapses to: accept d_j with probability
    p_j(d_j); on the first rejection, resample from p_j with d_j removed
    and renormalized (the residual (p - min(p, q))+ for a point-mass q);
    when every valid draft is accepted, the bonus token is a PLAIN sample
    from the last position. Each emitted token is therefore distributed
    exactly as the plain sampler's — greedy rows degenerate to "accept
    while the draft equals argmax", which makes greedy output bit-identical
    to non-speculative decoding.

    Randomness: position j consumes the (seeds, steps + j) stream, split
    into an acceptance uniform (fold_in 0) and a residual/bonus Gumbel
    (fold_in 1) — independent per position, independent of batch
    composition. Callers advance the step counter by T per verify.

    Returns ``(tokens [B, T], n_emit [B])``: row b's emitted tokens are
    ``tokens[b, :n_emit[b]]`` (accepted drafts then the resampled/bonus
    token); positions past n_emit hold zeros and carry no meaning.
    """
    if mask is not None:
        # ban before anything downstream: _log_weights truncation, greedy
        # argmax, and residual resampling then all see the constrained
        # distribution (identical to masking inside the plain sampler)
        logits = jnp.where(mask, logits, _NEG_INF)
    b, t, v = logits.shape
    kd = t - 1
    temp_b = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))

    def pos_streams(seed, step):
        def one(j):
            kj = jax.random.fold_in(jax.random.PRNGKey(seed), step + j)
            u = jax.random.uniform(jax.random.fold_in(kj, 0))
            g = jax.random.gumbel(jax.random.fold_in(kj, 1), (v,), jnp.float32)
            return u, g

        return jax.vmap(one)(jnp.arange(t, dtype=jnp.int32))

    u, gumbel = jax.vmap(pos_streams)(seeds, steps)  # [B,T], [B,T,V]
    w = jax.vmap(
        _log_weights, in_axes=(1, None, None, None), out_axes=1
    )(logits, temp_b, top_k, top_p)  # [B, T, V]
    p = jax.nn.softmax(w, axis=-1)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, T]

    # acceptance over the kd draft positions
    p_draft = jnp.take_along_axis(p[:, :kd], drafts[..., None], axis=-1)[..., 0]
    is_greedy = (temp_b <= 0.0)[:, None]
    ok = jnp.where(is_greedy, drafts == greedy_tok[:, :kd], u[:, :kd] < p_draft)
    ok &= jnp.arange(kd, dtype=jnp.int32)[None, :] < draft_len[:, None]
    # accepted = length of the all-accepted prefix
    a = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)  # [B] in 0..kd

    # the one extra token, from position a: a rejection resamples the
    # residual (draft token masked out); full acceptance samples plainly
    w_a = jnp.take_along_axis(w, a[:, None, None], axis=1)[:, 0]  # [B, V]
    g_a = jnp.take_along_axis(gumbel, a[:, None, None], axis=1)[:, 0]
    greedy_a = jnp.take_along_axis(greedy_tok, a[:, None], axis=1)[:, 0]
    d_a = jnp.take_along_axis(
        drafts, jnp.minimum(a, kd - 1)[:, None], axis=1
    )[:, 0]
    rejected = a < draft_len
    w_res = jnp.where(
        rejected[:, None] & (jnp.arange(v)[None, :] == d_a[:, None]),
        _NEG_INF,
        w_a,
    )
    pick = jnp.argmax(w_res + g_a, axis=-1)
    extra = jnp.where(temp_b <= 0.0, greedy_a, pick).astype(jnp.int32)

    j = jnp.arange(t, dtype=jnp.int32)[None, :]
    drafts_pad = jnp.pad(drafts, ((0, 0), (0, 1)))
    out = jnp.where(j < a[:, None], drafts_pad, 0)
    out = jnp.where(j == a[:, None], extra[:, None], out).astype(jnp.int32)
    return out, (a + 1).astype(jnp.int32)

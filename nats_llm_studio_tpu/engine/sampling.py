"""Token sampling: temperature / top-k / top-p, fully vectorized per row.

Per-request parameters are arrays of shape [B] so one jitted decode step can
serve a continuously-batched set of requests with different sampling settings
(SURVEY.md §7: the batcher is on the critical perf path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample(
    logits: jax.Array,  # [B, V] f32
    key: jax.Array,
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,  # 0 = disabled
    top_p: jax.Array | float = 1.0,
) -> jax.Array:
    """Returns sampled token ids [B] int32. temperature <= 0 means greedy
    (per row). One sort of the vocab per call; masks are rank-based so top-k
    and top-p are per-row arrays, not static."""
    b, v = logits.shape
    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))

    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # desc
    sorted_idx = jnp.argsort(logits, axis=-1)[:, ::-1]
    ranks = jnp.arange(v)[None, :]

    k_eff = jnp.where(top_k <= 0, v, top_k)[:, None]
    keep = ranks < k_eff

    # top-p over the sorted softmax; always keep the first token that crosses p
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    probs = jax.nn.softmax(sorted_logits / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_p[:, None]

    masked = jnp.where(keep, sorted_logits, -jnp.inf)
    drawn = jax.random.categorical(key, masked / safe_t, axis=-1)  # index into sorted order
    sampled = jnp.take_along_axis(sorted_idx, drawn[:, None], axis=-1)[:, 0]
    greedy = sorted_idx[:, 0]
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)

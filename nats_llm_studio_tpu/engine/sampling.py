"""Token sampling: temperature / top-k / top-p, fully vectorized per row.

Per-request parameters are arrays of shape [B] so one jitted decode step can
serve a continuously-batched set of requests with different sampling settings
(SURVEY.md §7: the batcher is on the critical perf path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _masked_scaled(logits, temperature, top_k, top_p):
    """Shared top-k/top-p masking. Returns (masked/temp logits in sorted
    order, sorted_idx, temperature)."""
    b, v = logits.shape
    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))

    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # desc
    sorted_idx = jnp.argsort(logits, axis=-1)[:, ::-1]
    ranks = jnp.arange(v)[None, :]

    k_eff = jnp.where(top_k <= 0, v, top_k)[:, None]
    keep = ranks < k_eff

    # top-p over the sorted softmax; always keep the first token that crosses p
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    probs = jax.nn.softmax(sorted_logits / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_p[:, None]

    masked = jnp.where(keep, sorted_logits, -jnp.inf) / safe_t
    return masked, sorted_idx, temperature


def _pick(masked, sorted_idx, temperature, gumbel) -> jax.Array:
    drawn = jnp.argmax(masked + gumbel, axis=-1)
    sampled = jnp.take_along_axis(sorted_idx, drawn[:, None], axis=-1)[:, 0]
    greedy = sorted_idx[:, 0]
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def sample(
    logits: jax.Array,  # [B, V] f32
    key: jax.Array,
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,  # 0 = disabled
    top_p: jax.Array | float = 1.0,
) -> jax.Array:
    """Returns sampled token ids [B] int32. temperature <= 0 means greedy
    (per row). One sort of the vocab per call; masks are rank-based so top-k
    and top-p are per-row arrays, not static."""
    masked, sorted_idx, temperature = _masked_scaled(logits, temperature, top_k, top_p)
    gumbel = jax.random.gumbel(key, masked.shape, jnp.float32)
    return _pick(masked, sorted_idx, temperature, gumbel)


def sample_rows(
    logits: jax.Array,  # [B, V] f32
    seeds: jax.Array,  # [B] int32 — per-row PRNG seed
    steps: jax.Array,  # [B] int32 — per-row step counter
    temperature: jax.Array | float = 0.8,
    top_k: jax.Array | int = 0,
    top_p: jax.Array | float = 1.0,
) -> jax.Array:
    """Per-row deterministic sampling: row i's randomness depends only on
    (seeds[i], steps[i]), never on batch composition — a request replayed
    with the same seed reproduces its completion regardless of what else is
    running in the continuous batch."""
    masked, sorted_idx, temperature = _masked_scaled(logits, temperature, top_k, top_p)

    def row_gumbel(seed, step):
        k = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.gumbel(k, (logits.shape[1],), jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, steps)
    return _pick(masked, sorted_idx, temperature, gumbel)

"""Single-stream autoregressive generation: the REFERENCE decode loop.

Two decode implementations exist on purpose and serve different roles:

* ``serve.batcher.ContinuousBatcher`` is the serving path — fixed-width
  batched slots, ring cache, burst decode, chunked prefill. Every
  throughput/latency trick lives there.
* ``Generator`` (this module) is the deliberately simple positional loop —
  one stream, per-position cache writes, token-at-a-time. The batcher's
  correctness tests hold the batcher to Generator's greedy output exactly
  (tests/test_batcher.py), the way the quant layer is held to scalar
  from-spec decoders: an independent implementation that a shared bug
  cannot hide behind. It is also the zero-setup library API for scripts.

Shared pieces (SamplingParams, bucket policy) are defined here and imported
by the batcher, so the two paths cannot drift on request semantics.

Prompt lengths are padded to a small set of bucket shapes so XLA compiles a
handful of prefill programs instead of one per length; ``warmup()``
pre-compiles them ahead of traffic (SURVEY.md §7 hard part #1).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.llama import forward, make_cache
from .sampling import sample, sample_rows, spec_accept_rows


def default_buckets(max_seq: int, start: int = 32) -> list[int]:
    """Powers of two from ``start`` up to max_seq (always includes max_seq)."""
    out = []
    b = start
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return out


@dataclass
class GenStats:
    prompt_tokens: int = 0
    completion_tokens: int = 0
    ttft_s: float = 0.0
    total_s: float = 0.0

    @property
    def decode_tok_s(self) -> float:
        decode_time = self.total_s - self.ttft_s
        n = max(self.completion_tokens - 1, 0)
        return n / decode_time if decode_time > 0 else 0.0


@dataclass
class SamplingParams:
    temperature: float = 0.8
    top_k: int = 0
    top_p: float = 1.0
    max_tokens: int = 256
    seed: int | None = None
    stop_ids: frozenset[int] = field(default_factory=frozenset)


class Generator:
    """Owns jitted prefill/decode for one loaded model.

    Single-stream ``generate()`` here; the continuous batcher in serve/ drives
    the same ``decode_step`` at a fixed batch width.
    """

    def __init__(
        self,
        params,
        cfg: ModelConfig,
        max_seq_len: int | None = None,
        buckets: list[int] | None = None,
    ):
        self.params = params
        self.cfg = cfg
        self.max_seq = min(max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
        self.buckets = buckets or default_buckets(self.max_seq)

        fwd = partial(forward, cfg=cfg)

        @partial(jax.jit, donate_argnums=(2, 3))
        def prefill_fn(params, tokens, k_cache, v_cache, start_pos):
            logits, k_cache, v_cache = fwd(params, tokens=tokens, k_cache=k_cache,
                                           v_cache=v_cache, start_pos=start_pos)
            return logits, k_cache, v_cache

        @partial(jax.jit, donate_argnums=(2, 3))
        def decode_fn(params, token, k_cache, v_cache, pos, key, temperature, top_k, top_p):
            logits, k_cache, v_cache = fwd(params, tokens=token, k_cache=k_cache,
                                           v_cache=v_cache, start_pos=pos)
            next_tok = sample(logits[:, -1, :], key, temperature, top_k, top_p)
            return next_tok, k_cache, v_cache

        @partial(jax.jit, donate_argnums=(2, 3))
        def decode_rows_fn(params, token, k_cache, v_cache, pos, seed, step,
                           temperature, top_k, top_p):
            """Decode step on the (seed, step) counter streams the batcher
            uses (sampling.sample_rows) — the speculative reference loop
            must consume the SAME rng streams as the serving path to be
            token-comparable at temperature > 0."""
            logits, k_cache, v_cache = fwd(params, tokens=token, k_cache=k_cache,
                                           v_cache=v_cache, start_pos=pos)
            next_tok = sample_rows(logits[:, -1, :], seed, step, temperature,
                                   top_k, top_p)
            return next_tok, k_cache, v_cache

        @partial(jax.jit, donate_argnums=(2, 3))
        def spec_verify_fn(params, toks_in, k_cache, v_cache, pos, drafts,
                           dlen, seed, step, temperature, top_k, top_p):
            """Reference verify: one width-(k+1) forward through the
            positional cache-write path + the rejection-sampling acceptance
            rule — the single-stream mirror of the batcher's program."""
            logits, k_cache, v_cache = fwd(params, tokens=toks_in, k_cache=k_cache,
                                           v_cache=v_cache, start_pos=pos)
            out, n_emit = spec_accept_rows(logits, drafts, dlen, seed, step,
                                           temperature, top_k, top_p)
            return out, n_emit, k_cache, v_cache

        self._prefill = prefill_fn
        self._decode = decode_fn
        self._decode_rows = decode_rows_fn
        self._spec_verify = spec_verify_fn

    # -- shape management ----------------------------------------------------

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds max_seq_len {self.max_seq}")

    def warmup(self, batch: int = 1, buckets: list[int] | None = None) -> float:
        """AOT-compile prefill buckets + the decode step. Returns seconds."""
        t0 = time.perf_counter()
        for b in buckets or self.buckets:
            k, v = make_cache(self.cfg, batch, self.max_seq)
            tokens = jnp.zeros((batch, b), jnp.int32)
            logits, k, v = self._prefill(self.params, tokens, k, v, jnp.zeros((batch,), jnp.int32))
            tok = jnp.zeros((batch, 1), jnp.int32)
            nxt, k, v = self._decode(
                self.params, tok, k, v,
                jnp.full((batch,), b, jnp.int32), jax.random.PRNGKey(0),
                jnp.ones((batch,)), jnp.zeros((batch,), jnp.int32), jnp.ones((batch,)),
            )
            # block EVERY bucket's prefill and its decode output inside the
            # loop: one block on the final prefill's logits let the other
            # buckets' compiles (and all decode executions) finish after
            # the timer, so the returned compile-seconds undercounted
            jax.block_until_ready((logits, nxt))
        return time.perf_counter() - t0

    # -- generation ----------------------------------------------------------

    def generate(
        self, prompt_ids: list[int], sp: SamplingParams | None = None, trace=None
    ) -> Iterator[tuple[int, GenStats]]:
        """Yield (token_id, running_stats) until a stop id or max_tokens.

        The final yielded stats carry total timing; ttft is measured at the
        first yielded token. ``trace`` is an optional ``obs.Trace`` stamped at
        prefill / first-token / decode-done (first-write-wins, so a caller that
        already marked a stage keeps its own timestamp).
        """
        sp = sp or SamplingParams()
        n = len(prompt_ids)
        if n == 0:
            return
        if n >= self.max_seq:
            raise ValueError(f"prompt of {n} tokens >= max_seq_len {self.max_seq}")
        bucket = self.bucket_for(n)
        stats = GenStats(prompt_tokens=n)
        t_start = time.perf_counter()
        if trace is not None:
            trace.mark("admit")

        tokens = jnp.asarray([prompt_ids + [0] * (bucket - n)], jnp.int32)
        k_cache, v_cache = make_cache(self.cfg, 1, self.max_seq)
        logits, k_cache, v_cache = self._prefill(
            self.params, tokens, k_cache, v_cache, jnp.zeros((1,), jnp.int32)
        )
        if trace is not None:
            jax.block_until_ready(logits)
            trace.mark("prefill")
        key = jax.random.PRNGKey(sp.seed if sp.seed is not None else time.monotonic_ns() % 2**31)
        key, sub = jax.random.split(key)
        temp = jnp.full((1,), sp.temperature, jnp.float32)
        tk = jnp.full((1,), sp.top_k, jnp.int32)
        tp = jnp.full((1,), sp.top_p, jnp.float32)
        next_tok = sample(logits[:, n - 1, :], sub, temp, tk, tp)

        pos = n
        max_new = min(sp.max_tokens, self.max_seq - n)
        for i in range(max_new):
            # one-step lookahead: dispatch decode step i+1 BEFORE blocking
            # on step i's host readback (int() below), so the device
            # computes the next token while the previous one crosses the
            # wire — the readback round trip no longer serializes between
            # steps. Token i+1 is always sampled from the (i+1)-th key
            # split, exactly as the sequential loop did, so outputs are
            # unchanged; one speculative step is wasted on early stop
            # (next_tok is not donated, so the dispatch is harmless).
            cur = next_tok
            if i < max_new - 1:
                key, sub = jax.random.split(key)
                next_tok, k_cache, v_cache = self._decode(
                    self.params,
                    cur[:, None],
                    k_cache,
                    v_cache,
                    jnp.full((1,), pos, jnp.int32),
                    sub,
                    temp,
                    tk,
                    tp,
                )
                pos += 1
            tok_id = int(cur[0])
            if i == 0:
                stats.ttft_s = time.perf_counter() - t_start
                if trace is not None:
                    trace.mark("first_token")
            if tok_id in sp.stop_ids:
                break
            stats.completion_tokens += 1
            stats.total_s = time.perf_counter() - t_start
            yield tok_id, stats
        stats.total_s = time.perf_counter() - t_start
        if trace is not None:
            trace.mark("decode_done")

    def generate_speculative(
        self,
        prompt_ids: list[int],
        sp: SamplingParams | None = None,
        spec_k: int = 6,
        max_ngram: int = 3,
        min_ngram: int = 1,
    ) -> Iterator[tuple[int, GenStats]]:
        """REFERENCE prompt-lookup speculative loop (single stream).

        Same proposal source (serve.spec.NGramIndex), same acceptance rule
        (sampling.spec_accept_rows) and the same per-(seed, step) rng
        streams as the speculative batcher: first token at step 0, a
        verify consumes steps [s, s + k] and advances by k + 1, a plain
        fallback step consumes one. Greedy output is bit-identical to
        ``generate()``; a single-request speculative batcher with
        ``decode_burst=1`` and the same seed/k/ngram settings is
        token-identical at ANY temperature (with a wider burst the two
        re-propose at different points, so temperature > 0 streams align
        only in distribution). The batcher's equivalence tests hold it to
        this loop."""
        from ..serve.spec import NGramIndex  # deferred: serve imports engine

        sp = sp or SamplingParams()
        n = len(prompt_ids)
        if n == 0:
            return
        if n >= self.max_seq:
            raise ValueError(f"prompt of {n} tokens >= max_seq_len {self.max_seq}")
        bucket = self.bucket_for(n)
        stats = GenStats(prompt_tokens=n)
        t_start = time.perf_counter()

        tokens = jnp.asarray([prompt_ids + [0] * (bucket - n)], jnp.int32)
        k_cache, v_cache = make_cache(self.cfg, 1, self.max_seq)
        logits, k_cache, v_cache = self._prefill(
            self.params, tokens, k_cache, v_cache, jnp.zeros((1,), jnp.int32)
        )
        seed = sp.seed if sp.seed is not None else time.monotonic_ns() % 2**31
        seed_a = jnp.full((1,), seed, jnp.int32)
        temp = jnp.full((1,), sp.temperature, jnp.float32)
        tk = jnp.full((1,), sp.top_k, jnp.int32)
        tp = jnp.full((1,), sp.top_p, jnp.float32)
        first = sample_rows(
            logits[:, n - 1, :], seed_a, jnp.zeros((1,), jnp.int32), temp, tk, tp
        )

        index = NGramIndex(list(prompt_ids), max_ngram, min_ngram)
        index.append(int(first[0]))
        pos = n  # the carry token (index tail) is sequence index pos
        step = 1  # rng step counter; the first token consumed step 0
        max_new = min(sp.max_tokens, self.max_seq - n)
        emitted = 0
        queue = [int(first[0])]  # sampled, not yet yielded
        done = False
        while not done:
            while queue:
                tok_id = queue.pop(0)
                if emitted == 0:
                    stats.ttft_s = time.perf_counter() - t_start
                if tok_id in sp.stop_ids:
                    done = True
                    break
                emitted += 1
                stats.completion_tokens += 1
                stats.total_s = time.perf_counter() - t_start
                yield tok_id, stats
                if emitted >= max_new:
                    done = True
                    break
            if done:
                break
            carry = jnp.asarray([[index.hist[-1]]], jnp.int32)
            drafts = (
                index.propose(spec_k)
                if pos + spec_k + 1 < self.max_seq  # mirror the batcher guard
                else []
            )
            if drafts:
                pad = list(drafts) + [0] * (spec_k - len(drafts))
                out, n_emit, k_cache, v_cache = self._spec_verify(
                    self.params,
                    jnp.concatenate([carry, jnp.asarray([pad], jnp.int32)], axis=1),
                    k_cache, v_cache,
                    jnp.full((1,), pos, jnp.int32),
                    jnp.asarray([pad], jnp.int32),
                    jnp.asarray([len(drafts)], jnp.int32),
                    seed_a, jnp.full((1,), step, jnp.int32), temp, tk, tp,
                )
                ne = int(n_emit[0])
                news = [int(x) for x in out[0, :ne]]
                step += spec_k + 1
                pos += ne
            else:
                nxt, k_cache, v_cache = self._decode_rows(
                    self.params, carry, k_cache, v_cache,
                    jnp.full((1,), pos, jnp.int32),
                    seed_a, jnp.full((1,), step, jnp.int32), temp, tk, tp,
                )
                news = [int(nxt[0])]
                step += 1
                pos += 1
            index.extend(news)
            queue.extend(news)
        stats.total_s = time.perf_counter() - t_start

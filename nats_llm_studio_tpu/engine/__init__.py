"""Inference engine: jitted prefill/decode, sampling, generation loop.

Replaces the reference's external llama.cpp hot loop
(/root/reference/README.md:6, SURVEY.md §3.1: "THE hot loop, entirely outside
the repo") with an in-process JAX decode loop on TPU.
"""

from .generator import Generator
from .sampling import sample

__all__ = ["Generator", "sample"]

"""Block (de)quantization for GGUF tensor storage types.

Vectorized NumPy implementations of the public GGML block formats. Dequant is
the load-path hot loop (GGUF blob -> bf16 shards on the TPU mesh); quantizers
exist for fixture generation, checkpoint conversion, and roundtrip tests.
Quantizers produce valid encodings with straightforward scale selection
(per-(sub)block min/max or abs-max); they do not replicate llama.cpp's
error-minimising search, which only affects quantisation quality, not format.

The reference framework never touches these bytes — GGUF files are opaque to
it (/root/reference/nats_llm_studio.go:120-131 manipulates them only as
directories on disk).
"""

from __future__ import annotations

import numpy as np

from .constants import BLOCK_LAYOUT, GGMLType

_PLAIN_DTYPES: dict[GGMLType, np.dtype] = {
    GGMLType.F32: np.dtype("<f4"),
    GGMLType.F16: np.dtype("<f2"),
    GGMLType.F64: np.dtype("<f8"),
    GGMLType.I8: np.dtype("<i1"),
    GGMLType.I16: np.dtype("<i2"),
    GGMLType.I32: np.dtype("<i4"),
    GGMLType.I64: np.dtype("<i8"),
}


def type_block_size(t: GGMLType) -> int:
    """Elements per storage block."""
    return BLOCK_LAYOUT[t][0]


def type_size(t: GGMLType, n_elements: int) -> int:
    """Bytes needed to store ``n_elements`` of type ``t``."""
    block_elems, block_bytes = BLOCK_LAYOUT[t]
    if n_elements % block_elems != 0:
        raise ValueError(f"{n_elements} elements not divisible by {t.name} block of {block_elems}")
    return n_elements // block_elems * block_bytes


def _f16(raw: np.ndarray) -> np.ndarray:
    """View 2-byte columns as little-endian float16 -> float32."""
    return np.ascontiguousarray(raw).view("<f2").astype(np.float32)


def _blocks(data: bytes | np.ndarray, t: GGMLType, n_elements: int) -> np.ndarray:
    block_elems, block_bytes = BLOCK_LAYOUT[t]
    n_blocks = n_elements // block_elems
    arr = np.frombuffer(data, dtype=np.uint8, count=n_blocks * block_bytes)
    return arr.reshape(n_blocks, block_bytes)


# ---------------------------------------------------------------------------
# dequantization
# ---------------------------------------------------------------------------


def dequantize(data: bytes | np.ndarray, t: GGMLType, n_elements: int) -> np.ndarray:
    """Decode ``n_elements`` of storage type ``t`` to a flat float32 array
    (plain integer types decode to their own dtype)."""
    if t in _PLAIN_DTYPES:
        dt = _PLAIN_DTYPES[t]
        out = np.frombuffer(data, dtype=dt, count=n_elements)
        return out.astype(np.float32) if dt.kind == "f" and dt.itemsize != 4 else np.asarray(out)
    if t == GGMLType.BF16:
        u16 = np.frombuffer(data, dtype="<u2", count=n_elements).astype(np.uint32)
        return (u16 << 16).view(np.float32)
    fn = _DEQUANT.get(t)
    if fn is None:
        raise NotImplementedError(f"dequantize: {t.name} not supported")
    if n_elements >= 4096:  # ctypes call overhead isn't worth it for tiny tensors
        from ..native import dequantize_native

        out = dequantize_native(data, int(t), n_elements)
        if out is not None:
            return out
    return fn(_blocks(data, t, n_elements)).reshape(-1)


def _deq_q4_0(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])  # (N,1)->(N,) after view; keep 2-d via reshape
    d = d.reshape(-1, 1)
    qs = b[:, 2:18]
    lo = (qs & 0x0F).astype(np.int8) - 8
    hi = (qs >> 4).astype(np.int8) - 8
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return d * q


def _deq_q4_1(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2]).reshape(-1, 1)
    m = _f16(b[:, 2:4]).reshape(-1, 1)
    qs = b[:, 4:20]
    q = np.concatenate([qs & 0x0F, qs >> 4], axis=1).astype(np.float32)
    return d * q + m


def _deq_q5_0(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2]).reshape(-1, 1)
    qh = b[:, 2:6].copy().view("<u4").reshape(-1, 1)  # (N,1) uint32
    qs = b[:, 6:22]
    j = np.arange(16)
    hi_bit_lo = ((qh >> j) & 1).astype(np.uint8) << 4  # (N,16)
    hi_bit_hi = ((qh >> (j + 16)) & 1).astype(np.uint8) << 4
    x0 = ((qs & 0x0F) | hi_bit_lo).astype(np.int16) - 16
    x1 = ((qs >> 4) | hi_bit_hi).astype(np.int16) - 16
    return d * np.concatenate([x0, x1], axis=1).astype(np.float32)


def _deq_q5_1(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2]).reshape(-1, 1)
    m = _f16(b[:, 2:4]).reshape(-1, 1)
    qh = b[:, 4:8].copy().view("<u4").reshape(-1, 1)
    qs = b[:, 8:24]
    j = np.arange(16)
    hi_bit_lo = ((qh >> j) & 1).astype(np.uint8) << 4
    hi_bit_hi = ((qh >> (j + 16)) & 1).astype(np.uint8) << 4
    x0 = (qs & 0x0F) | hi_bit_lo
    x1 = (qs >> 4) | hi_bit_hi
    return d * np.concatenate([x0, x1], axis=1).astype(np.float32) + m


def _deq_q8_0(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2]).reshape(-1, 1)
    q = b[:, 2:34].view(np.int8).astype(np.float32)
    return d * q


def _deq_q8_k(b: np.ndarray) -> np.ndarray:
    d = b[:, 0:4].copy().view("<f4").reshape(-1, 1)
    q = b[:, 4:260].view(np.int8).astype(np.float32)
    return d * q


def _kquant_scales(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unpack the 12-byte packed 6-bit (scale, min) pairs of Q4_K/Q5_K.

    Returns (sc, m), each (N, 8) uint8 in [0, 63].
    """
    s = scales.astype(np.uint8)
    sc = np.empty(s.shape[:1] + (8,), dtype=np.uint8)
    m = np.empty_like(sc)
    sc[:, :4] = s[:, 0:4] & 63
    m[:, :4] = s[:, 4:8] & 63
    sc[:, 4:] = (s[:, 8:12] & 0x0F) | ((s[:, 0:4] >> 6) << 4)
    m[:, 4:] = (s[:, 8:12] >> 4) | ((s[:, 4:8] >> 6) << 4)
    return sc, m


def _deq_q4_k(b: np.ndarray) -> np.ndarray:
    n = b.shape[0]
    d = _f16(b[:, 0:2]).reshape(n, 1, 1)
    dmin = _f16(b[:, 2:4]).reshape(n, 1, 1)
    sc, m = _kquant_scales(b[:, 4:16])
    qs = b[:, 16:144].reshape(n, 4, 32)
    lo = qs & 0x0F
    hi = qs >> 4
    # chunk c covers sub-blocks 2c (low nibbles) and 2c+1 (high nibbles)
    q = np.stack([lo, hi], axis=2).reshape(n, 8, 32).astype(np.float32)
    y = d * sc.astype(np.float32)[:, :, None] * q - dmin * m.astype(np.float32)[:, :, None]
    return y.reshape(n, 256)


def _deq_q5_k(b: np.ndarray) -> np.ndarray:
    n = b.shape[0]
    d = _f16(b[:, 0:2]).reshape(n, 1, 1)
    dmin = _f16(b[:, 2:4]).reshape(n, 1, 1)
    sc, m = _kquant_scales(b[:, 4:16])
    qh = b[:, 16:48]  # (n, 32)
    qs = b[:, 48:176].reshape(n, 4, 32)
    shifts = (np.arange(8)).reshape(1, 8, 1)  # sub-block j uses qh bit j
    hbit = ((qh[:, None, :] >> shifts) & 1).astype(np.uint8) << 4  # (n,8,32)
    lo = qs & 0x0F
    hi = qs >> 4
    q4 = np.stack([lo, hi], axis=2).reshape(n, 8, 32)
    q = (q4 | hbit).astype(np.float32)
    y = d * sc.astype(np.float32)[:, :, None] * q - dmin * m.astype(np.float32)[:, :, None]
    return y.reshape(n, 256)


def _deq_q6_k(b: np.ndarray) -> np.ndarray:
    n = b.shape[0]
    ql = b[:, 0:128].reshape(n, 2, 2, 32)  # (half, byte-group, 32)
    qh = b[:, 128:192].reshape(n, 2, 32)
    scales = b[:, 192:208].view(np.int8).reshape(n, 2, 8)
    d = _f16(b[:, 208:210]).reshape(n, 1, 1, 1)
    parts = np.empty((n, 2, 4, 32), dtype=np.int16)
    parts[:, :, 0] = (ql[:, :, 0] & 0x0F) | ((qh & 3) << 4)
    parts[:, :, 1] = (ql[:, :, 1] & 0x0F) | (((qh >> 2) & 3) << 4)
    parts[:, :, 2] = (ql[:, :, 0] >> 4) | (((qh >> 4) & 3) << 4)
    parts[:, :, 3] = (ql[:, :, 1] >> 4) | (((qh >> 6) & 3) << 4)
    q = parts.astype(np.float32) - 32.0
    # scale index for part p, lane l within a half: (l // 16) + 2p
    idx = (np.arange(32) // 16)[None, :] + 2 * np.arange(4)[:, None]  # (4, 32)
    sc = scales.astype(np.float32)[:, :, idx]  # (n, 2, 4, 32)
    return (d * sc * q).reshape(n, 256)


_DEQUANT = {
    GGMLType.Q4_0: _deq_q4_0,
    GGMLType.Q4_1: _deq_q4_1,
    GGMLType.Q5_0: _deq_q5_0,
    GGMLType.Q5_1: _deq_q5_1,
    GGMLType.Q8_0: _deq_q8_0,
    GGMLType.Q8_K: _deq_q8_k,
    GGMLType.Q4_K: _deq_q4_k,
    GGMLType.Q5_K: _deq_q5_k,
    GGMLType.Q6_K: _deq_q6_k,
}


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------


def quantize(x: np.ndarray, t: GGMLType) -> bytes:
    """Encode an array as storage type ``t``. Flattens row-major."""
    if t in _PLAIN_DTYPES:
        # encode straight from the source dtype: a float32 round-trip would
        # silently corrupt I32/I64 values above 2**24
        arr = np.ascontiguousarray(np.asarray(x).reshape(-1))
        return np.ascontiguousarray(arr.astype(_PLAIN_DTYPES[t])).tobytes()
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if t == GGMLType.BF16:
        u = x.view(np.uint32)
        # round-to-nearest-even on the dropped 16 bits; NaN passes through as
        # the canonical quiet NaN (the +0x7FFF carry would otherwise turn
        # some NaN encodings into +/-Inf)
        rounded = ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype("<u2")
        rounded = np.where(np.isnan(x), np.uint16(0x7FC0), rounded).astype("<u2")
        return rounded.tobytes()
    fn = _QUANT.get(t)
    if fn is None:
        raise NotImplementedError(f"quantize: {t.name} not supported")
    block_elems, _ = BLOCK_LAYOUT[t]
    if x.size % block_elems != 0:
        raise ValueError(f"size {x.size} not divisible by {t.name} block of {block_elems}")
    return fn(x.reshape(-1, block_elems)).tobytes()


def _safe_div(num: np.ndarray, den: np.ndarray) -> np.ndarray:
    return np.divide(num, den, out=np.zeros_like(num), where=den != 0)


def _q_q8_0(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    amax = np.abs(x).max(axis=1, keepdims=True)
    d = amax / 127.0
    q = np.clip(np.rint(_safe_div(x, d)), -127, 127).astype(np.int8)
    out = np.empty((n, 34), dtype=np.uint8)
    out[:, 0:2] = d.astype("<f2").view(np.uint8)
    out[:, 2:34] = q.view(np.uint8)
    return out


def _signed_absmax(x: np.ndarray) -> np.ndarray:
    """Per-row value with the largest magnitude, sign preserved. (N,1)"""
    idx = np.abs(x).argmax(axis=1)
    return x[np.arange(x.shape[0]), idx].reshape(-1, 1)


def _q_q4_0(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    d = _signed_absmax(x) / -8.0
    q = np.clip(np.rint(_safe_div(x, d)) + 8, 0, 15).astype(np.uint8)
    out = np.empty((n, 18), dtype=np.uint8)
    out[:, 0:2] = d.astype("<f2").view(np.uint8)
    out[:, 2:18] = q[:, :16] | (q[:, 16:] << 4)
    return out


def _q_q4_1(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    mn = x.min(axis=1, keepdims=True)
    mx = x.max(axis=1, keepdims=True)
    d = (mx - mn) / 15.0
    q = np.clip(np.rint(_safe_div(x - mn, d)), 0, 15).astype(np.uint8)
    out = np.empty((n, 20), dtype=np.uint8)
    out[:, 0:2] = d.astype("<f2").view(np.uint8)
    out[:, 2:4] = mn.astype("<f2").view(np.uint8)
    out[:, 4:20] = q[:, :16] | (q[:, 16:] << 4)
    return out


def _q_q5_0(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    d = _signed_absmax(x) / -16.0
    q = np.clip(np.rint(_safe_div(x, d)) + 16, 0, 31).astype(np.uint32)
    lo, hi = q[:, :16], q[:, 16:]
    j = np.arange(16)
    qh = ((lo >> 4 & 1) << j).sum(axis=1) | ((hi >> 4 & 1) << (j + 16)).sum(axis=1)
    out = np.empty((n, 22), dtype=np.uint8)
    out[:, 0:2] = d.astype("<f2").view(np.uint8)
    out[:, 2:6] = qh.astype("<u4").view(np.uint8).reshape(n, 4)
    out[:, 6:22] = ((lo & 0x0F) | ((hi & 0x0F) << 4)).astype(np.uint8)
    return out


def _q_q5_1(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    mn = x.min(axis=1, keepdims=True)
    mx = x.max(axis=1, keepdims=True)
    d = (mx - mn) / 31.0
    q = np.clip(np.rint(_safe_div(x - mn, d)), 0, 31).astype(np.uint32)
    lo, hi = q[:, :16], q[:, 16:]
    j = np.arange(16)
    qh = ((lo >> 4 & 1) << j).sum(axis=1) | ((hi >> 4 & 1) << (j + 16)).sum(axis=1)
    out = np.empty((n, 24), dtype=np.uint8)
    out[:, 0:2] = d.astype("<f2").view(np.uint8)
    out[:, 2:4] = mn.astype("<f2").view(np.uint8)
    out[:, 4:8] = qh.astype("<u4").view(np.uint8).reshape(n, 4)
    out[:, 8:24] = ((lo & 0x0F) | ((hi & 0x0F) << 4)).astype(np.uint8)
    return out


def _q_q8_k(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    amax = np.abs(x).max(axis=1, keepdims=True)
    d = amax / 127.0
    q = np.clip(np.rint(_safe_div(x, d)), -127, 127).astype(np.int8)
    bsums = q.reshape(n, 16, 16).sum(axis=2).astype("<i2")
    out = np.empty((n, 292), dtype=np.uint8)
    out[:, 0:4] = d.astype("<f4").view(np.uint8)
    out[:, 4:260] = q.view(np.uint8)
    out[:, 260:292] = bsums.view(np.uint8).reshape(n, 32)
    return out


def _pack_kquant_scales(sc: np.ndarray, m: np.ndarray) -> np.ndarray:
    """Pack 8 (scale, min) 6-bit pairs into the 12-byte Q4_K/Q5_K layout."""
    n = sc.shape[0]
    out = np.zeros((n, 12), dtype=np.uint8)
    out[:, 0:4] = (sc[:, :4] & 63) | ((sc[:, 4:] >> 4) << 6)
    out[:, 4:8] = (m[:, :4] & 63) | ((m[:, 4:] >> 4) << 6)
    out[:, 8:12] = (sc[:, 4:] & 0x0F) | ((m[:, 4:] & 0x0F) << 4)
    return out


def _kquant_affine_params(x: np.ndarray, qmax: float) -> tuple[np.ndarray, ...]:
    """Per-sub-block affine params for Q4_K/Q5_K: x ~ d*sc*q - dmin*m."""
    sub = x.reshape(x.shape[0], 8, 32)
    mn = sub.min(axis=2)
    mx = sub.max(axis=2)
    # the representable offset -dmin*m is <= 0, so for sub-blocks with a
    # positive minimum the q range itself must span from 0 (not mn) up to mx
    scales = (mx - np.minimum(mn, 0.0)) / qmax  # per-sub-block real scale, >= 0
    mins = np.maximum(0.0, -mn)
    d = scales.max(axis=1, keepdims=True) / 63.0
    dmin = mins.max(axis=1, keepdims=True) / 63.0
    sc = np.clip(np.rint(_safe_div(scales, d)), 0, 63).astype(np.uint8)
    m = np.clip(np.rint(_safe_div(mins, dmin)), 0, 63).astype(np.uint8)
    # quantize with the 6-bit-rounded params actually stored
    d16 = d.astype("<f2")
    dmin16 = dmin.astype("<f2")
    eff_scale = d16.astype(np.float32) * sc  # (n, 8)
    eff_min = dmin16.astype(np.float32) * m
    q = np.clip(np.rint(_safe_div(sub + eff_min[:, :, None], eff_scale[:, :, None])), 0, qmax)
    return d16, dmin16, sc, m, q.astype(np.uint8)


def _q_q4_k(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    d16, dmin16, sc, m, q = _kquant_affine_params(x, 15.0)
    out = np.empty((n, 144), dtype=np.uint8)
    out[:, 0:2] = d16.view(np.uint8)
    out[:, 2:4] = dmin16.view(np.uint8)
    out[:, 4:16] = _pack_kquant_scales(sc, m)
    pairs = q.reshape(n, 4, 2, 32)  # chunk c: sub 2c -> low nibble, 2c+1 -> high
    out[:, 16:144] = (pairs[:, :, 0] | (pairs[:, :, 1] << 4)).reshape(n, 128)
    return out


def _q_q5_k(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    d16, dmin16, sc, m, q = _kquant_affine_params(x, 31.0)
    out = np.empty((n, 176), dtype=np.uint8)
    out[:, 0:2] = d16.view(np.uint8)
    out[:, 2:4] = dmin16.view(np.uint8)
    out[:, 4:16] = _pack_kquant_scales(sc, m)
    hbits = (q >> 4) & 1  # (n, 8, 32)
    shifts = np.arange(8).reshape(1, 8, 1)
    out[:, 16:48] = (hbits.astype(np.uint8) << shifts).sum(axis=1, dtype=np.uint8)
    low4 = (q & 0x0F).reshape(n, 4, 2, 32)
    out[:, 48:176] = (low4[:, :, 0] | (low4[:, :, 1] << 4)).reshape(n, 128)
    return out


def _q_q6_k(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    sub = x.reshape(n, 16, 16)
    amax = np.abs(sub).max(axis=2)
    a = amax / 31.0  # per-sub-block effective scale
    d = a.max(axis=1, keepdims=True) / 127.0
    d16 = d.astype("<f2")
    sc = np.clip(np.rint(_safe_div(a, d16.astype(np.float32))), -128, 127).astype(np.int8)
    eff = d16.astype(np.float32) * sc  # (n, 16)
    q = np.clip(np.rint(_safe_div(sub, eff[:, :, None])) + 32, 0, 63).astype(np.uint8)
    # scatter into the (half, part, lane) layout used by dequant
    q = q.reshape(n, 16, 16)
    y = np.empty((n, 2, 4, 32), dtype=np.uint8)  # part p holds elems [p*32, p*32+32) of a half
    for h in range(2):
        half = q[:, 8 * h : 8 * h + 8].reshape(n, 128)
        y[:, h] = half.reshape(n, 4, 32)
    ql = np.empty((n, 2, 2, 32), dtype=np.uint8)
    ql[:, :, 0] = (y[:, :, 0] & 0x0F) | ((y[:, :, 2] & 0x0F) << 4)
    ql[:, :, 1] = (y[:, :, 1] & 0x0F) | ((y[:, :, 3] & 0x0F) << 4)
    qh = (
        (y[:, :, 0] >> 4)
        | ((y[:, :, 1] >> 4) << 2)
        | ((y[:, :, 2] >> 4) << 4)
        | ((y[:, :, 3] >> 4) << 6)
    )
    out = np.empty((n, 210), dtype=np.uint8)
    out[:, 0:128] = ql.reshape(n, 128)
    out[:, 128:192] = qh.reshape(n, 64)
    out[:, 192:208] = sc.view(np.uint8)
    out[:, 208:210] = d16.view(np.uint8)
    return out


_QUANT = {
    GGMLType.Q8_0: _q_q8_0,
    GGMLType.Q4_0: _q_q4_0,
    GGMLType.Q4_1: _q_q4_1,
    GGMLType.Q5_0: _q_q5_0,
    GGMLType.Q5_1: _q_q5_1,
    GGMLType.Q8_K: _q_q8_k,
    GGMLType.Q4_K: _q_q4_k,
    GGMLType.Q5_K: _q_q5_k,
    GGMLType.Q6_K: _q_q6_k,
}

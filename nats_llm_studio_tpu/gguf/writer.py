"""GGUF v3 writer.

Used for test fixtures (SURVEY.md §4.1: "tiny hand-built GGUF fixtures"),
for converting HF/safetensors checkpoints into the Object Store distribution
format, and for re-quantizing models.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Any

import numpy as np

from .constants import (
    GGUF_DEFAULT_ALIGNMENT,
    GGUF_MAGIC,
    GGUF_VERSION,
    KEY_ALIGNMENT,
    SCALAR_FMT as _SCALAR_FMT,
    GGMLType,
    GGUFValueType,
)
from .quants import quantize, type_size


def _guess_vtype(v: Any) -> GGUFValueType:
    if isinstance(v, bool):
        return GGUFValueType.BOOL
    if isinstance(v, int):
        return GGUFValueType.INT64 if v < 0 else GGUFValueType.UINT32 if v < 2**32 else GGUFValueType.UINT64
    if isinstance(v, float):
        return GGUFValueType.FLOAT32
    if isinstance(v, str):
        return GGUFValueType.STRING
    if isinstance(v, (list, tuple, np.ndarray)):
        return GGUFValueType.ARRAY
    raise TypeError(f"cannot infer GGUF value type for {type(v)}")


class GGUFWriter:
    def __init__(self, path: str | Path, alignment: int = GGUF_DEFAULT_ALIGNMENT):
        self.path = Path(path)
        self.alignment = alignment
        self._kv: list[tuple[str, GGUFValueType, Any, GGUFValueType | None]] = []
        self._tensors: list[tuple[str, tuple[int, ...], GGMLType, bytes]] = []
        self.add(KEY_ALIGNMENT, alignment, GGUFValueType.UINT32)

    def add(self, key: str, value: Any, vtype: GGUFValueType | None = None, elem_type: GGUFValueType | None = None) -> None:
        vtype = vtype if vtype is not None else _guess_vtype(value)
        self._kv.append((key, vtype, value, elem_type))

    def add_dict(self, kv: dict[str, Any]) -> None:
        for k, v in kv.items():
            self.add(k, v)

    def add_tensor(self, name: str, array: np.ndarray, ggml_type: GGMLType | None = None) -> None:
        """Queue a tensor; float arrays are encoded as ``ggml_type``
        (default F32). Logical row-major shape is preserved (reader reverses
        GGUF's dim order back)."""
        if ggml_type is None:
            ggml_type = GGMLType.F32
        data = quantize(np.asarray(array), ggml_type)
        assert len(data) == type_size(ggml_type, int(np.asarray(array).size))
        self._tensors.append((name, tuple(np.asarray(array).shape), ggml_type, data))

    # -- serialization ------------------------------------------------------

    def _w_string(self, out: list[bytes], s: str) -> None:
        b = s.encode("utf-8")
        out.append(struct.pack("<Q", len(b)))
        out.append(b)

    def _w_value(self, out: list[bytes], vtype: GGUFValueType, v: Any, elem_type: GGUFValueType | None) -> None:
        if vtype == GGUFValueType.BOOL:
            out.append(struct.pack("<B", 1 if v else 0))
        elif vtype == GGUFValueType.STRING:
            self._w_string(out, v)
        elif vtype == GGUFValueType.ARRAY:
            seq = v.tolist() if isinstance(v, np.ndarray) else list(v)
            et = elem_type
            if et is None:
                et = _guess_vtype(seq[0]) if seq else GGUFValueType.INT32
                if et == GGUFValueType.UINT64:
                    et = GGUFValueType.INT64
                if all(type(x) is int for x in seq) and seq:
                    et = GGUFValueType.INT32 if all(-(2**31) <= x < 2**31 for x in seq) else GGUFValueType.INT64
            out.append(struct.pack("<I", int(et)))
            out.append(struct.pack("<Q", len(seq)))
            for x in seq:
                self._w_value(out, et, x, None)
        else:
            out.append(struct.pack(_SCALAR_FMT[vtype], v))

    def write(self) -> Path:
        out: list[bytes] = [
            struct.pack("<IIQQ", GGUF_MAGIC, GGUF_VERSION, len(self._tensors), len(self._kv))
        ]
        for key, vtype, v, et in self._kv:
            self._w_string(out, key)
            out.append(struct.pack("<I", int(vtype)))
            self._w_value(out, vtype, v, et)

        # tensor index: dims stored reversed (ne[0] = contiguous axis)
        rel = 0
        for name, shape, ttype, data in self._tensors:
            self._w_string(out, name)
            dims = tuple(reversed(shape)) if shape else (1,)
            out.append(struct.pack("<I", len(dims)))
            for d in dims:
                out.append(struct.pack("<Q", d))
            out.append(struct.pack("<I", int(ttype)))
            out.append(struct.pack("<Q", rel))
            rel += len(data)
            rel = (rel + self.alignment - 1) // self.alignment * self.alignment

        header = b"".join(out)
        pad = (-len(header)) % self.alignment
        with open(self.path, "wb") as f:
            f.write(header)
            f.write(b"\x00" * pad)
            written = 0
            for _, _, _, data in self._tensors:
                f.write(data)
                written += len(data)
                tail = (-written) % self.alignment
                f.write(b"\x00" * tail)
                written += tail
        return self.path

"""GGUF v3 wire-format constants (public GGML/GGUF specification)."""

from __future__ import annotations

import enum

GGUF_MAGIC = 0x46554747  # b"GGUF" little-endian
GGUF_VERSION = 3
GGUF_DEFAULT_ALIGNMENT = 32

# Standard metadata keys this framework reads/writes.
KEY_ARCHITECTURE = "general.architecture"
KEY_NAME = "general.name"
KEY_ALIGNMENT = "general.alignment"
KEY_QUANT_VERSION = "general.quantization_version"
KEY_FILE_TYPE = "general.file_type"

KEY_TOKENIZER_MODEL = "tokenizer.ggml.model"
KEY_TOKENIZER_PRE = "tokenizer.ggml.pre"
KEY_TOKENIZER_TOKENS = "tokenizer.ggml.tokens"
KEY_TOKENIZER_SCORES = "tokenizer.ggml.scores"
KEY_TOKENIZER_TYPES = "tokenizer.ggml.token_type"
KEY_TOKENIZER_MERGES = "tokenizer.ggml.merges"
KEY_TOKENIZER_BOS = "tokenizer.ggml.bos_token_id"
KEY_TOKENIZER_EOS = "tokenizer.ggml.eos_token_id"
KEY_TOKENIZER_ADD_BOS = "tokenizer.ggml.add_bos_token"
KEY_TOKENIZER_ADD_EOS = "tokenizer.ggml.add_eos_token"
KEY_CHAT_TEMPLATE = "tokenizer.chat_template"


class GGUFValueType(enum.IntEnum):
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    UINT32 = 4
    INT32 = 5
    FLOAT32 = 6
    BOOL = 7
    STRING = 8
    ARRAY = 9
    UINT64 = 10
    INT64 = 11
    FLOAT64 = 12


# struct format per scalar metadata value type (wire encoding, little-endian)
SCALAR_FMT: dict[GGUFValueType, str] = {
    GGUFValueType.UINT8: "<B",
    GGUFValueType.INT8: "<b",
    GGUFValueType.UINT16: "<H",
    GGUFValueType.INT16: "<h",
    GGUFValueType.UINT32: "<I",
    GGUFValueType.INT32: "<i",
    GGUFValueType.FLOAT32: "<f",
    GGUFValueType.UINT64: "<Q",
    GGUFValueType.INT64: "<q",
    GGUFValueType.FLOAT64: "<d",
}


class GGMLType(enum.IntEnum):
    """Tensor storage types (ggml type ids)."""

    F32 = 0
    F16 = 1
    Q4_0 = 2
    Q4_1 = 3
    Q5_0 = 6
    Q5_1 = 7
    Q8_0 = 8
    Q8_1 = 9
    Q2_K = 10
    Q3_K = 11
    Q4_K = 12
    Q5_K = 13
    Q6_K = 14
    Q8_K = 15
    I8 = 24
    I16 = 25
    I32 = 26
    I64 = 27
    F64 = 28
    BF16 = 30


class TokenType(enum.IntEnum):
    """tokenizer.ggml.token_type values."""

    NORMAL = 1
    UNKNOWN = 2
    CONTROL = 3
    USER_DEFINED = 4
    UNUSED = 5
    BYTE = 6


# (elements per block, bytes per block) for each storage type.
BLOCK_LAYOUT: dict[GGMLType, tuple[int, int]] = {
    GGMLType.F32: (1, 4),
    GGMLType.F16: (1, 2),
    GGMLType.BF16: (1, 2),
    GGMLType.F64: (1, 8),
    GGMLType.I8: (1, 1),
    GGMLType.I16: (1, 2),
    GGMLType.I32: (1, 4),
    GGMLType.I64: (1, 8),
    GGMLType.Q4_0: (32, 18),
    GGMLType.Q4_1: (32, 20),
    GGMLType.Q5_0: (32, 22),
    GGMLType.Q5_1: (32, 24),
    GGMLType.Q8_0: (32, 34),
    GGMLType.Q2_K: (256, 84),
    GGMLType.Q3_K: (256, 110),
    GGMLType.Q4_K: (256, 144),
    GGMLType.Q5_K: (256, 176),
    GGMLType.Q6_K: (256, 210),
    GGMLType.Q8_K: (256, 292),
}

"""mmap-backed GGUF v3 reader.

Parses the header, metadata KV section, and tensor index; tensor bytes stay on
disk (memory-mapped) until a caller dequantizes them, so a 40 GB 70B file can
be loaded shard-by-shard onto the device mesh without materialising the whole
model in host RAM (SURVEY.md §7 hard part #2).
"""

from __future__ import annotations

import mmap
import re
import struct
from dataclasses import dataclass
from pathlib import Path
from typing import Any, BinaryIO

import numpy as np

from .constants import (
    GGUF_DEFAULT_ALIGNMENT,
    GGUF_MAGIC,
    KEY_ALIGNMENT,
    SCALAR_FMT as _SCALAR_FMT,
    GGMLType,
    GGUFValueType,
)
from .quants import dequantize, type_size


class GGUFFormatError(ValueError):
    pass


@dataclass
class GGUFTensor:
    """One entry of the tensor index.

    ``shape`` is in logical (row-major, numpy) order — GGUF stores dims
    reversed (ne[0] is the fastest-varying / contiguous axis), and this reader
    undoes that so ``shape == dequantized.shape``.
    """

    name: str
    shape: tuple[int, ...]
    ggml_type: GGMLType
    offset: int  # absolute file offset of the first byte
    _buf: memoryview

    @property
    def n_elements(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def n_bytes(self) -> int:
        return type_size(self.ggml_type, self.n_elements)

    def raw(self) -> memoryview:
        return self._buf[self.offset : self.offset + self.n_bytes]

    def to_numpy(self, dtype: np.dtype | str | None = None) -> np.ndarray:
        """Dequantize to a dense array of ``self.shape``."""
        arr = dequantize(np.frombuffer(self.raw(), dtype=np.uint8), self.ggml_type, self.n_elements)
        arr = arr.reshape(self.shape)
        return arr.astype(dtype) if dtype is not None else arr


class _Cursor:
    def __init__(self, buf: memoryview):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> memoryview:
        if self.pos + n > len(self.buf):
            raise GGUFFormatError("truncated GGUF file")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def scalar(self, fmt: str) -> Any:
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, self.take(size))[0]

    def string(self) -> str:
        n = self.scalar("<Q")
        if n > len(self.buf):
            raise GGUFFormatError("string length exceeds file size")
        return bytes(self.take(n)).decode("utf-8", errors="replace")

    def value(self, vtype: GGUFValueType) -> Any:
        if vtype == GGUFValueType.BOOL:
            return bool(self.scalar("<B"))
        if vtype == GGUFValueType.STRING:
            return self.string()
        if vtype == GGUFValueType.ARRAY:
            etype = GGUFValueType(self.scalar("<I"))
            count = self.scalar("<Q")
            if etype in _SCALAR_FMT and etype != GGUFValueType.BOOL:
                fmt = _SCALAR_FMT[etype]
                size = struct.calcsize(fmt)
                raw = self.take(count * size)
                return np.frombuffer(raw, dtype=fmt).tolist()
            return [self.value(etype) for _ in range(count)]
        fmt = _SCALAR_FMT.get(vtype)
        if fmt is None:
            raise GGUFFormatError(f"unknown metadata value type {vtype}")
        return self.scalar(fmt)


class GGUFReader:
    """Read-only view over a GGUF file: ``.metadata`` dict + ``.tensors``."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._file: BinaryIO = open(self.path, "rb")
        try:
            self._mmap = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)
            buf = memoryview(self._mmap)
        except (ValueError, OSError):  # empty file or fs without mmap
            self._mmap = None
            buf = memoryview(self._file.read())
        self._buf = buf
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, GGUFTensor] = {}
        try:
            self._parse()
        except Exception:
            self.close()  # don't leak the fd/mapping on malformed files
            raise

    def close(self) -> None:
        """Close the file handle. Dequantized tensors are zero-copy views
        over the mapping where possible; if any are still alive the mapping
        itself stays valid until they are garbage-collected (the OS frees it
        then), so close never invalidates outstanding arrays."""
        try:
            self._buf.release()
        except BufferError:
            pass
        if self._mmap is not None:
            try:
                self._mmap.close()
            except BufferError:
                pass
        self._file.close()

    def __enter__(self) -> "GGUFReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _parse(self) -> None:
        cur = _Cursor(self._buf)
        magic = cur.scalar("<I")
        if magic != GGUF_MAGIC:
            raise GGUFFormatError(f"bad magic {magic:#x} (not a GGUF file)")
        version = cur.scalar("<I")
        if version not in (2, 3):
            raise GGUFFormatError(f"unsupported GGUF version {version}")
        self.version = version
        n_tensors = cur.scalar("<Q")
        n_kv = cur.scalar("<Q")
        for _ in range(n_kv):
            key = cur.string()
            vtype = GGUFValueType(cur.scalar("<I"))
            self.metadata[key] = cur.value(vtype)

        infos: list[tuple[str, tuple[int, ...], GGMLType, int]] = []
        for _ in range(n_tensors):
            name = cur.string()
            n_dims = cur.scalar("<I")
            dims = [cur.scalar("<Q") for _ in range(n_dims)]
            ttype = GGMLType(cur.scalar("<I"))
            rel_offset = cur.scalar("<Q")
            # GGUF dims are reversed relative to row-major logical shape
            infos.append((name, tuple(reversed(dims)), ttype, rel_offset))

        try:
            alignment = int(self.metadata.get(KEY_ALIGNMENT, GGUF_DEFAULT_ALIGNMENT))
        except (TypeError, ValueError) as e:
            raise GGUFFormatError(f"bad general.alignment: {e}") from None
        if alignment <= 0:
            raise GGUFFormatError(f"bad general.alignment: {alignment}")
        data_start = (cur.pos + alignment - 1) // alignment * alignment
        for name, shape, ttype, rel in infos:
            self.tensors[name] = GGUFTensor(
                name=name, shape=shape, ggml_type=ttype, offset=data_start + rel, _buf=self._buf
            )

    # convenience -----------------------------------------------------------

    def tensor(self, name: str) -> GGUFTensor:
        try:
            return self.tensors[name]
        except KeyError:
            raise KeyError(f"tensor {name!r} not in {self.path.name}") from None

    @property
    def architecture(self) -> str:
        return str(self.metadata.get("general.architecture", ""))

    def arch_field(self, field: str, default: Any = None) -> Any:
        """Read ``<architecture>.<field>`` from metadata."""
        return self.metadata.get(f"{self.architecture}.{field}", default)


class GGUFShardedReader:
    """Reader over a split GGUF (llama.cpp `gguf-split` layout): shards named
    ``<base>-NNNNN-of-MMMMM.gguf``, each a complete GGUF holding a subset of
    the tensors, with ``split.no`` / ``split.count`` / ``split.tensors.count``
    metadata. 70B-class public checkpoints ship this way (single files cap
    around 48 GB on common hosts), so the serving loaders accept either form.

    Presents the same surface the loaders use: merged ``.tensors``,
    ``.metadata`` (from shard 1, which carries the full model metadata), and
    per-tensor dispatch to the owning shard's mapping.
    """

    def __init__(self, paths: "list[str | Path]"):
        if not paths:
            raise ValueError("no shard paths given")
        self.shards: list[GGUFReader] = []
        try:
            for p in sorted(Path(p) for p in paths):
                self.shards.append(GGUFReader(p))
            count = int(self.shards[0].metadata.get("split.count", len(self.shards)))
            if count != len(self.shards):
                raise ValueError(
                    f"split.count={count} but {len(self.shards)} shard files found"
                )
            first_no = int(self.shards[0].metadata.get("split.no", 0))
            if first_no != 0:
                raise ValueError(
                    "first shard (lexicographically) has split.no="
                    f"{first_no}; shard names must order the set"
                )
            self.path = self.shards[0].path
            self.metadata = self.shards[0].metadata
            self.tensors: dict[str, GGUFTensor] = {}
            for shard in self.shards:
                for name, tns in shard.tensors.items():
                    if name in self.tensors:
                        raise ValueError(f"tensor {name!r} appears in two shards")
                    self.tensors[name] = tns
        except Exception:
            self.close()
            raise

    def tensor(self, name: str) -> GGUFTensor:
        try:
            return self.tensors[name]
        except KeyError:
            raise KeyError(f"tensor {name!r} not in {self.path.name} shards") from None

    @property
    def architecture(self) -> str:
        return str(self.metadata.get("general.architecture", ""))

    def arch_field(self, field: str, default=None):
        return self.metadata.get(f"{self.architecture}.{field}", default)

    def close(self) -> None:
        for shard in getattr(self, "shards", []):
            shard.close()

    def __enter__(self) -> "GGUFShardedReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_SPLIT_RE = re.compile(r"^(.*)-(\d{5})-of-(\d{5})\.gguf$")


def is_split_shard(path: "str | Path") -> bool:
    """Whether a filename follows the gguf-split shard convention."""
    return _SPLIT_RE.match(Path(path).name) is not None


def open_gguf(path_or_paths):
    """Open a GGUF model file OR a split set.

    Accepts a single path (auto-detecting ``-NNNNN-of-MMMMM.gguf`` siblings),
    or an explicit list of shard paths. Returns a GGUFReader or
    GGUFShardedReader with the same read surface. A single path naming a
    shard requires every sibling to exist (partial downloads fail loudly).
    """
    if isinstance(path_or_paths, (list, tuple)):
        paths = [Path(p) for p in path_or_paths]
        if len(paths) == 1 and is_split_shard(paths[0]):
            return open_gguf(paths[0])  # enforce sibling discovery
        return GGUFShardedReader(paths) if len(paths) > 1 else GGUFReader(paths[0])
    path = Path(path_or_paths)
    m = _SPLIT_RE.match(path.name)
    if m:
        base, total = m.group(1), int(m.group(3))
        siblings = [
            path.with_name(f"{base}-{i + 1:05d}-of-{total:05d}.gguf")
            for i in range(total)
        ]
        missing = [p.name for p in siblings if not p.exists()]
        if missing:
            raise FileNotFoundError(f"missing GGUF shards: {missing}")
        return GGUFShardedReader(siblings)
    return GGUFReader(path)

"""GGUF model-file layer.

The reference never parses GGUF itself — model files are opaque blobs managed
by LM Studio under ``~/.lmstudio/models/<publisher>/<model>/``
(/root/reference/nats_llm_studio.go:120, README.md:48-52) and all tensor work
happens inside the external llama.cpp engine. Replacing that engine with an
in-process TPU path requires a native GGUF v3 reader: metadata + tokenizer
extraction, tensor index, and block dequantization (K-quants -> bf16/f32)
feeding sharded device buffers.

Everything here is implemented from the public GGUF/GGML format specification;
no reference code exists for it.
"""

from .constants import GGMLType, GGUFValueType
from .quants import dequantize, quantize, type_block_size, type_size
from .reader import GGUFReader, GGUFShardedReader, GGUFTensor, open_gguf
from .tokenizer import GGUFTokenizer
from .writer import GGUFWriter

__all__ = [
    "GGMLType",
    "GGUFValueType",
    "GGUFReader",
    "GGUFShardedReader",
    "open_gguf",
    "GGUFTensor",
    "GGUFTokenizer",
    "GGUFWriter",
    "dequantize",
    "quantize",
    "type_block_size",
    "type_size",
]

"""Tokenizers reconstructed from GGUF metadata.

Preserves the reference's "everything ships in the .gguf" property
(SURVEY.md §2.2): the vocab, merges, and scores are read from the file's
``tokenizer.ggml.*`` keys — no external tokenizer download. Two families:

- ``llama``  : SentencePiece-style BPE driven by per-token scores
               (Llama-2, Mistral/Mixtral, Granite-7b lineage)
- ``gpt2``   : byte-level BPE driven by ranked merges
               (Llama-3, Granite-3.x, GPT-2 lineage)
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Iterable

from .constants import (
    KEY_TOKENIZER_ADD_BOS,
    KEY_TOKENIZER_BOS,
    KEY_TOKENIZER_EOS,
    KEY_TOKENIZER_MERGES,
    KEY_TOKENIZER_MODEL,
    KEY_TOKENIZER_SCORES,
    KEY_TOKENIZER_TOKENS,
    KEY_TOKENIZER_TYPES,
    TokenType,
)

try:  # proper \p{L}/\p{N} classes for byte-level BPE pretokenization
    import regex as _re

    _HAVE_REGEX = True
except ImportError:  # pragma: no cover
    import re as _re  # type: ignore[no-redef]

    _HAVE_REGEX = False

_SPIECE = "▁"  # ▁

# llama-3 style pretokenizer (also a good default for gpt2-family vocabs)
_BPE_PATTERN = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}"
    r"| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)
_BPE_PATTERN_ASCII = (  # fallback when `regex` is unavailable
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\w\d]?[^\W\d_]+|\d{1,3}"
    r"| ?[^\s\w\d]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)


@lru_cache(maxsize=1)
def _byte_to_unicode() -> dict[int, str]:
    """GPT-2's invertible byte <-> printable-unicode mapping."""
    bs = list(range(ord("!"), ord("~") + 1)) + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


class GGUFTokenizer:
    """Encode/decode against a GGUF-embedded vocabulary."""

    def __init__(
        self,
        model: str,
        tokens: list[str],
        scores: list[float] | None = None,
        token_types: list[int] | None = None,
        merges: list[str] | None = None,
        bos_id: int | None = None,
        eos_id: int | None = None,
        add_bos: bool = True,
    ):
        if model not in ("llama", "gpt2"):
            raise NotImplementedError(
                f"tokenizer model {model!r} not supported (llama/gpt2 families only)"
            )
        self.model = model
        self.tokens = tokens
        self.scores = scores or []
        self.token_types = token_types or []
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.add_bos = add_bos
        self.vocab: dict[str, int] = {t: i for i, t in enumerate(tokens)}
        self._byte_tokens: dict[int, int] = {}  # byte value -> token id (SPM <0xXX>)
        if token_types:
            for i, tt in enumerate(token_types):
                if tt == TokenType.BYTE:
                    s = tokens[i]
                    if s.startswith("<0x") and s.endswith(">"):
                        self._byte_tokens[int(s[3:-1], 16)] = i
        self.merge_ranks: dict[tuple[str, str], int] = {}
        for rank, m in enumerate(merges or []):
            a, _, b = m.partition(" ")
            self.merge_ranks[(a, b)] = rank
        if model == "gpt2":
            self._b2u = _byte_to_unicode()
            self._u2b = {c: b for b, c in self._b2u.items()}
            pat = _BPE_PATTERN if _HAVE_REGEX else _BPE_PATTERN_ASCII
            self._pre = _re.compile(pat)
        self._control_ids = {
            i for i, tt in enumerate(token_types or []) if tt == TokenType.CONTROL
        }
        self.unk_id: int | None = next(
            (i for i, tt in enumerate(token_types or []) if tt == TokenType.UNKNOWN),
            self.vocab.get("<unk>"),
        )

    # -- construction -------------------------------------------------------

    @classmethod
    def from_metadata(cls, md: dict[str, Any]) -> "GGUFTokenizer":
        return cls(
            model=str(md.get(KEY_TOKENIZER_MODEL, "gpt2")),
            tokens=list(md[KEY_TOKENIZER_TOKENS]),
            scores=md.get(KEY_TOKENIZER_SCORES),
            token_types=md.get(KEY_TOKENIZER_TYPES),
            merges=md.get(KEY_TOKENIZER_MERGES),
            bos_id=md.get(KEY_TOKENIZER_BOS),
            eos_id=md.get(KEY_TOKENIZER_EOS),
            add_bos=bool(md.get(KEY_TOKENIZER_ADD_BOS, True)),
        )

    @property
    def vocab_size(self) -> int:
        return len(self.tokens)

    # -- encoding -----------------------------------------------------------

    def encode(self, text: str, add_bos: bool | None = None) -> list[int]:
        ids = self._encode_spm(text) if self.model == "llama" else self._encode_bpe(text)
        use_bos = self.add_bos if add_bos is None else add_bos
        if use_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def _encode_spm(self, text: str) -> list[int]:
        if not text:
            return []
        text = _SPIECE + text.replace(" ", _SPIECE)
        # seed with single characters (byte-fallback for unknowns)
        pieces: list[str] = list(text)
        ids: list[int] = []
        pieces = self._merge_by_score(pieces)
        for p in pieces:
            tid = self.vocab.get(p)
            if tid is not None:
                ids.append(tid)
                continue
            for byte in p.encode("utf-8"):
                bid = self._byte_tokens.get(byte)
                if bid is not None:
                    ids.append(bid)
                elif self.unk_id is not None:  # SentencePiece semantics
                    ids.append(self.unk_id)
        return ids

    def _merge_by_score(self, pieces: list[str]) -> list[str]:
        """Greedy SentencePiece BPE via a bigram heap: O(L log L) instead of
        rescanning every pair per merge (the prompt-encode hot path feeds
        TTFT, SURVEY.md §7 hard part #1)."""
        import heapq

        text = list(pieces)  # symbol table; consumed entries become ""
        prev = list(range(-1, len(text) - 1))
        nxt = list(range(1, len(text) + 1))

        heap: list[tuple[float, int, int, str]] = []

        def push(i: int, j: int) -> None:
            if i < 0 or j >= len(text):
                return
            cand = text[i] + text[j]
            tid = self.vocab.get(cand)
            if tid is not None and tid < len(self.scores):
                heapq.heappush(heap, (-self.scores[tid], i, j, cand))

        for i in range(len(text) - 1):
            push(i, i + 1)

        while heap:
            _, i, j, cand = heapq.heappop(heap)
            if text[i] + text[j] != cand or not text[i] or not text[j]:
                continue  # stale entry: one side already merged away
            text[i] = cand
            text[j] = ""
            nxt[i] = nxt[j]
            if nxt[j] < len(text):
                prev[nxt[j]] = i
            push(prev[i], i)
            push(i, nxt[i])
        return [t for t in text if t]

    def _encode_bpe(self, text: str) -> list[int]:
        ids: list[int] = []
        for piece in self._pre.findall(text):
            mapped = "".join(self._b2u[b] for b in piece.encode("utf-8"))
            for part in self._bpe_merge(mapped):
                tid = self.vocab.get(part)
                if tid is not None:
                    ids.append(tid)
        return ids

    def _bpe_merge(self, word: str) -> Iterable[str]:
        parts = list(word)
        while len(parts) > 1:
            ranked = [
                (self.merge_ranks.get((parts[i], parts[i + 1])), i)
                for i in range(len(parts) - 1)
            ]
            ranked = [(r, i) for r, i in ranked if r is not None]
            if not ranked:
                break
            _, i = min(ranked)
            parts = parts[:i] + [parts[i] + parts[i + 1]] + parts[i + 2 :]
        return parts

    # -- decoding -----------------------------------------------------------

    def decode(self, ids: Iterable[int], skip_control: bool = True) -> str:
        if self.model == "llama":
            out: list[bytes] = []
            for i in ids:
                if skip_control and i in self._control_ids:
                    continue
                tok = self.tokens[i]
                if tok.startswith("<0x") and tok.endswith(">") and len(tok) == 6:
                    out.append(bytes([int(tok[3:-1], 16)]))
                else:
                    out.append(tok.replace(_SPIECE, " ").encode("utf-8"))
            text = b"".join(out).decode("utf-8", errors="replace")
            return text[1:] if text.startswith(" ") else text
        # gpt2: unicode chars map back to bytes
        buf = bytearray()
        for i in ids:
            if skip_control and i in self._control_ids:
                continue
            for ch in self.tokens[i]:
                b = self._u2b.get(ch)
                if b is not None:
                    buf.append(b)
                else:
                    buf.extend(ch.encode("utf-8"))
        return buf.decode("utf-8", errors="replace")

// Native GGUF block-dequantization hot loop.
//
// SURVEY.md §2.2: the one genuinely native-worthy component — streaming a
// 40 GB 70B GGUF into bf16 device shards is bottlenecked on block decode.
// Bound via ctypes (no pybind11 in this environment); the NumPy path in
// gguf/quants.py remains the reference implementation and fallback.
//
// Layouts follow the public GGML block formats (see gguf/quants.py for the
// commented Python reference of each).

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

inline float f16_to_f32(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
    uint32_t exp = (h >> 10) & 0x1Fu;
    uint32_t man = h & 0x3FFu;
    uint32_t bits;
    if (exp == 0) {
        if (man == 0) {
            bits = sign;
        } else {  // subnormal half -> normalized float
            int e = 0;
            while (!(man & 0x400u)) {
                man <<= 1;
                e++;
            }
            man &= 0x3FFu;
            bits = sign | ((uint32_t)(113 - e) << 23) | (man << 13);
        }
    } else if (exp == 31) {
        bits = sign | 0x7F800000u | (man << 13);  // inf / nan
    } else {
        bits = sign | ((exp + 112u) << 23) | (man << 13);
    }
    float f;
    std::memcpy(&f, &bits, 4);
    return f;
}

inline uint16_t f32_to_bf16(float f) {
    uint32_t u;
    std::memcpy(&u, &f, 4);
    uint32_t rounded = (u + 0x7FFFu + ((u >> 16) & 1u)) >> 16;  // round-nearest-even
    return (uint16_t)rounded;
}

// unpack the 12-byte packed 6-bit (scale, min) pairs of Q4_K/Q5_K
inline void kquant_scales(const uint8_t* s, uint8_t* sc, uint8_t* m) {
    for (int j = 0; j < 4; j++) {
        sc[j] = s[j] & 63;
        m[j] = s[j + 4] & 63;
    }
    for (int j = 4; j < 8; j++) {
        sc[j] = (uint8_t)((s[j + 4] & 0x0F) | ((s[j - 4] >> 6) << 4));
        m[j] = (uint8_t)((s[j + 4] >> 4) | ((s[j] >> 6) << 4));
    }
}

}  // namespace

extern "C" {

void dequant_q8_0(const uint8_t* in, float* out, int64_t nb) {
    for (int64_t i = 0; i < nb; i++) {
        const uint8_t* b = in + i * 34;
        uint16_t dh;
        std::memcpy(&dh, b, 2);
        const float d = f16_to_f32(dh);
        const int8_t* q = (const int8_t*)(b + 2);
        float* o = out + i * 32;
        for (int j = 0; j < 32; j++) o[j] = d * (float)q[j];
    }
}

void dequant_q4_0(const uint8_t* in, float* out, int64_t nb) {
    for (int64_t i = 0; i < nb; i++) {
        const uint8_t* b = in + i * 18;
        uint16_t dh;
        std::memcpy(&dh, b, 2);
        const float d = f16_to_f32(dh);
        const uint8_t* q = b + 2;
        float* o = out + i * 32;
        for (int j = 0; j < 16; j++) {
            o[j] = d * (float)((int)(q[j] & 0x0F) - 8);
            o[j + 16] = d * (float)((int)(q[j] >> 4) - 8);
        }
    }
}

void dequant_q4_k(const uint8_t* in, float* out, int64_t nb) {
    for (int64_t i = 0; i < nb; i++) {
        const uint8_t* b = in + i * 144;
        uint16_t dh, mh;
        std::memcpy(&dh, b, 2);
        std::memcpy(&mh, b + 2, 2);
        const float d = f16_to_f32(dh);
        const float dmin = f16_to_f32(mh);
        uint8_t sc[8], mn[8];
        kquant_scales(b + 4, sc, mn);
        const uint8_t* q = b + 16;
        float* o = out + i * 256;
        for (int c = 0; c < 4; c++) {  // chunk c: sub-blocks 2c (lo), 2c+1 (hi)
            const float d1 = d * sc[2 * c], m1 = dmin * mn[2 * c];
            const float d2 = d * sc[2 * c + 1], m2 = dmin * mn[2 * c + 1];
            const uint8_t* qc = q + 32 * c;
            float* oc = o + 64 * c;
            for (int l = 0; l < 32; l++) {
                oc[l] = d1 * (float)(qc[l] & 0x0F) - m1;
                oc[l + 32] = d2 * (float)(qc[l] >> 4) - m2;
            }
        }
    }
}

void dequant_q5_k(const uint8_t* in, float* out, int64_t nb) {
    for (int64_t i = 0; i < nb; i++) {
        const uint8_t* b = in + i * 176;
        uint16_t dh, mh;
        std::memcpy(&dh, b, 2);
        std::memcpy(&mh, b + 2, 2);
        const float d = f16_to_f32(dh);
        const float dmin = f16_to_f32(mh);
        uint8_t sc[8], mn[8];
        kquant_scales(b + 4, sc, mn);
        const uint8_t* qh = b + 16;
        const uint8_t* ql = b + 48;
        float* o = out + i * 256;
        for (int c = 0; c < 4; c++) {
            const float d1 = d * sc[2 * c], m1 = dmin * mn[2 * c];
            const float d2 = d * sc[2 * c + 1], m2 = dmin * mn[2 * c + 1];
            const uint8_t* qc = ql + 32 * c;
            const uint8_t u1 = (uint8_t)(1u << (2 * c)), u2 = (uint8_t)(1u << (2 * c + 1));
            float* oc = o + 64 * c;
            for (int l = 0; l < 32; l++) {
                oc[l] = d1 * (float)((qc[l] & 0x0F) + ((qh[l] & u1) ? 16 : 0)) - m1;
                oc[l + 32] = d2 * (float)((qc[l] >> 4) + ((qh[l] & u2) ? 16 : 0)) - m2;
            }
        }
    }
}

void dequant_q6_k(const uint8_t* in, float* out, int64_t nb) {
    for (int64_t i = 0; i < nb; i++) {
        const uint8_t* b = in + i * 210;
        const uint8_t* ql = b;
        const uint8_t* qh = b + 128;
        const int8_t* sc = (const int8_t*)(b + 192);
        uint16_t dh;
        std::memcpy(&dh, b + 208, 2);
        const float d = f16_to_f32(dh);
        float* o = out + i * 256;
        for (int h = 0; h < 2; h++) {
            const uint8_t* qlh = ql + 64 * h;
            const uint8_t* qhh = qh + 32 * h;
            const int8_t* sch = sc + 8 * h;
            float* oh = o + 128 * h;
            for (int l = 0; l < 32; l++) {
                const int is = l / 16;
                const int q1 = (int)((qlh[l] & 0x0F) | (((qhh[l] >> 0) & 3) << 4)) - 32;
                const int q2 = (int)((qlh[l + 32] & 0x0F) | (((qhh[l] >> 2) & 3) << 4)) - 32;
                const int q3 = (int)((qlh[l] >> 4) | (((qhh[l] >> 4) & 3) << 4)) - 32;
                const int q4 = (int)((qlh[l + 32] >> 4) | (((qhh[l] >> 6) & 3) << 4)) - 32;
                oh[l] = d * sch[is] * (float)q1;
                oh[l + 32] = d * sch[is + 2] * (float)q2;
                oh[l + 64] = d * sch[is + 4] * (float)q3;
                oh[l + 96] = d * sch[is + 6] * (float)q4;
            }
        }
    }
}

void f16_to_f32_buf(const uint16_t* in, float* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) out[i] = f16_to_f32(in[i]);
}

// direct-to-bf16 variants: halve the host buffer for the 70B load path
void f32_to_bf16_buf(const float* in, uint16_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) out[i] = f32_to_bf16(in[i]);
}

}  // extern "C"

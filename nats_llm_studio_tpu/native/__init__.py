"""ctypes bridge to the native dequant hot loop (dequant.cpp).

Compiled lazily on first use with the system toolchain (g++ is part of the
target environment; pybind11 is not, hence ctypes) and cached per source
hash. Every entry point degrades to the NumPy reference in gguf/quants.py if
the toolchain or build is unavailable, so the native layer is a pure
accelerator, never a requirement.

Disable with NATIVE_DEQUANT=0.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from pathlib import Path

import numpy as np

log = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "dequant.cpp"
_LIB: ctypes.CDLL | None = None
_TRIED = False

# ggml type id -> (exported symbol, block elems, block bytes)
_FNS = {
    8: ("dequant_q8_0", 32, 34),   # Q8_0
    2: ("dequant_q4_0", 32, 18),   # Q4_0
    12: ("dequant_q4_k", 256, 144),  # Q4_K
    13: ("dequant_q5_k", 256, 176),  # Q5_K
    14: ("dequant_q6_k", 256, 210),  # Q6_K
}


def _build() -> Path | None:
    cache_dir = Path(os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache")) / "nats-llm-studio-tpu"
    cache_dir.mkdir(parents=True, exist_ok=True)
    tag = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    so = cache_dir / f"dequant_{tag}.so"
    if so.exists():
        return so
    with tempfile.NamedTemporaryFile(suffix=".so", dir=cache_dir, delete=False) as tmp:
        tmp_path = Path(tmp.name)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", str(tmp_path), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native dequant build failed (%s); using NumPy path", e)
        tmp_path.unlink(missing_ok=True)
        return None
    tmp_path.replace(so)
    return so


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("NATIVE_DEQUANT", "1") in ("0", "false"):
        return None
    so = _build()
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(str(so))
    except OSError as e:
        log.warning("native dequant load failed (%s)", e)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    for sym, _, _ in _FNS.values():
        fn = getattr(lib, sym)
        fn.argtypes = [u8p, f32p, ctypes.c_int64]
        fn.restype = None
    lib.f16_to_f32_buf.argtypes = [ctypes.POINTER(ctypes.c_uint16), f32p, ctypes.c_int64]
    lib.f16_to_f32_buf.restype = None
    _LIB = lib
    return lib


def available() -> bool:
    return _load() is not None


def dequantize_native(data, ggml_type: int, n_elements: int) -> np.ndarray | None:
    """Decode to float32, or None when this type/toolchain isn't covered."""
    spec = _FNS.get(int(ggml_type))
    lib = _load()
    if spec is None or lib is None:
        return None
    sym, block_elems, block_bytes = spec
    if n_elements % block_elems:
        return None
    nb = n_elements // block_elems
    src = np.frombuffer(data, dtype=np.uint8, count=nb * block_bytes)
    out = np.empty(n_elements, dtype=np.float32)
    getattr(lib, sym)(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(nb),
    )
    return out

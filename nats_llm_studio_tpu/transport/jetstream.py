"""Object Store client speaking the public JetStream wire protocol.

Implements the README's model-repository pattern for real
(/root/reference/README.md:250-318): bucket = stream ``OBJ_<bucket>`` over
subjects ``$O.<bucket>.C.>`` (chunks) / ``$O.<bucket>.M.>`` (metadata),
chunked puts with SHA-256 digests, reads via direct-get lookups. Works
against the in-tree broker module (store/objectstore.py) and, by construction
of the subjects/payloads, against a real nats-server with JetStream enabled.
"""

from __future__ import annotations

import base64
import hashlib
import json
import time
from dataclasses import dataclass, field

from ..utils.nuid import next_nuid
from .client import Msg, NatsClient

DEFAULT_CHUNK = 128 * 1024


class ObjectStoreError(Exception):
    pass


class ObjectNotFound(ObjectStoreError):
    pass


def _b64name(name: str) -> str:
    return base64.urlsafe_b64encode(name.encode()).decode()


def _digest(data: bytes) -> str:
    return "SHA-256=" + base64.urlsafe_b64encode(hashlib.sha256(data).digest()).decode()


@dataclass
class ObjectInfo:
    name: str
    bucket: str
    nuid: str
    size: int
    chunks: int
    digest: str
    mtime: str = ""
    deleted: bool = False
    description: str = ""
    raw: dict = field(default_factory=dict)

    @classmethod
    def from_json(cls, data: bytes | dict) -> "ObjectInfo":
        d = data if isinstance(data, dict) else json.loads(data)
        return cls(
            name=d.get("name", ""),
            bucket=d.get("bucket", ""),
            nuid=d.get("nuid", ""),
            size=int(d.get("size", 0)),
            chunks=int(d.get("chunks", 0)),
            digest=d.get("digest", ""),
            mtime=d.get("mtime", ""),
            deleted=bool(d.get("deleted", False)),
            description=d.get("description", ""),
            raw=d,
        )


class ObjectStore:
    """Async object-store API bound to one NATS connection."""

    def __init__(self, nc: NatsClient, timeout: float = 30.0):
        self.nc = nc
        self.timeout = timeout

    # -- JS API helpers ------------------------------------------------------

    async def _api(self, op: str, payload: dict | None = None) -> dict:
        msg = await self.nc.request(
            f"$JS.API.{op}",
            json.dumps(payload or {}).encode(),
            timeout=self.timeout,
        )
        status = (msg.headers or {}).get("Status")
        if status and status.startswith("404"):
            raise ObjectNotFound((msg.headers or {}).get("Description", "not found"))
        body = json.loads(msg.payload) if msg.payload.strip() else {}
        err = body.get("error")
        if err:
            code = int(err.get("code", 500))
            if code == 404:
                raise ObjectNotFound(err.get("description", "not found"))
            raise ObjectStoreError(err.get("description", str(err)))
        return body

    async def _direct_get(self, stream: str, query: dict) -> Msg:
        msg = await self.nc.request(
            f"$JS.API.DIRECT.GET.{stream}", json.dumps(query).encode(), timeout=self.timeout
        )
        status = (msg.headers or {}).get("Status")
        if status and status.startswith("404"):
            raise ObjectNotFound((msg.headers or {}).get("Description", "message not found"))
        if status and not status.startswith("200"):
            raise ObjectStoreError(f"direct get status {status}")
        if not (msg.headers or {}).get("Nats-Subject") and msg.payload[:1] == b"{":
            # JSON error envelope from the API layer
            body = json.loads(msg.payload)
            if body.get("error"):
                code = int(body["error"].get("code", 500))
                exc = ObjectNotFound if code == 404 else ObjectStoreError
                raise exc(body["error"].get("description", "error"))
        return msg

    # -- buckets -------------------------------------------------------------

    @staticmethod
    def _stream(bucket: str) -> str:
        return f"OBJ_{bucket}"

    async def ensure_bucket(self, bucket: str, description: str = "") -> None:
        cfg = {
            "name": self._stream(bucket),
            "description": description,
            "subjects": [f"$O.{bucket}.C.>", f"$O.{bucket}.M.>"],
            "retention": "limits",
            "discard": "new",
            "allow_rollup_hdrs": True,
            "allow_direct": True,
            "max_msgs": -1,
            "max_bytes": -1,
        }
        await self._api(f"STREAM.CREATE.{self._stream(bucket)}", cfg)

    async def delete_bucket(self, bucket: str) -> None:
        await self._api(f"STREAM.DELETE.{self._stream(bucket)}")

    async def list_buckets(self) -> list[str]:
        body = await self._api("STREAM.NAMES")
        return [s[4:] for s in body.get("streams") or [] if s.startswith("OBJ_")]

    # -- objects -------------------------------------------------------------

    async def put(
        self, bucket: str, name: str, data: bytes, chunk_size: int = DEFAULT_CHUNK,
        description: str = "",
    ) -> ObjectInfo:
        # overwrite: remember the previous revision's chunk subject so its
        # chunks can be purged after the metadata rollup (otherwise every
        # re-publish leaks the full old blob in the stream)
        old_nuid: str | None = None
        try:
            old_nuid = (await self.info(bucket, name)).nuid
        except ObjectStoreError:
            pass
        nuid = next_nuid()
        chunk_subject = f"$O.{bucket}.C.{nuid}"
        n_chunks = 0
        for off in range(0, len(data), chunk_size):
            await self.nc.publish(chunk_subject, data[off : off + chunk_size])
            n_chunks += 1
        if n_chunks == 0:  # zero-byte object still needs no chunks
            pass
        await self.nc.flush()
        info = ObjectInfo(
            name=name,
            bucket=bucket,
            nuid=nuid,
            size=len(data),
            chunks=n_chunks,
            digest=_digest(data),
            mtime=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            description=description,
        )
        meta = {
            "name": info.name,
            "bucket": info.bucket,
            "nuid": info.nuid,
            "size": info.size,
            "chunks": info.chunks,
            "digest": info.digest,
            "mtime": info.mtime,
            "description": description,
        }
        await self.nc.publish(
            f"$O.{bucket}.M.{_b64name(name)}",
            json.dumps(meta, separators=(",", ":")).encode(),
            headers={"Nats-Rollup": "sub"},
        )
        await self.nc.flush()
        if old_nuid and old_nuid != nuid:
            await self._api(
                f"STREAM.PURGE.{self._stream(bucket)}",
                {"filter": f"$O.{bucket}.C.{old_nuid}"},
            )
        return info

    async def info(self, bucket: str, name: str) -> ObjectInfo:
        msg = await self._direct_get(
            self._stream(bucket), {"last_by_subj": f"$O.{bucket}.M.{_b64name(name)}"}
        )
        inf = ObjectInfo.from_json(msg.payload)
        if inf.deleted:
            raise ObjectNotFound(f"object {name!r} is deleted")
        return inf

    async def get_chunks(self, bucket: str, name: str):
        """Stream an object chunk by chunk (async generator).

        O(chunk) memory regardless of object size — the path multi-GB model
        pulls ride (the 100 GiB file-store contract, setup_unix.sh analog).
        Size and SHA-256 digest are verified incrementally; a mismatch
        raises after the last chunk, before the caller commits the result.
        """
        inf = await self.info(bucket, name)
        chunk_subject = f"$O.{bucket}.C.{inf.nuid}"
        seq = 0
        total = 0
        h = hashlib.sha256()
        for _ in range(inf.chunks):
            msg = await self._direct_get(
                self._stream(bucket), {"seq": seq + 1, "next_by_subj": chunk_subject}
            )
            seq = int((msg.headers or {}).get("Nats-Sequence", seq + 1))
            total += len(msg.payload)
            h.update(msg.payload)
            yield msg.payload
        if total != inf.size:
            raise ObjectStoreError(f"size mismatch for {name!r}: {total} != {inf.size}")
        want = "SHA-256=" + base64.urlsafe_b64encode(h.digest()).decode()
        if inf.digest and want != inf.digest:
            raise ObjectStoreError(f"digest mismatch for {name!r}")

    async def get(self, bucket: str, name: str) -> bytes:
        parts = [chunk async for chunk in self.get_chunks(bucket, name)]
        return b"".join(parts)

    async def delete(self, bucket: str, name: str) -> None:
        inf = await self.info(bucket, name)
        await self._api(
            f"STREAM.PURGE.{self._stream(bucket)}", {"filter": f"$O.{bucket}.C.{inf.nuid}"}
        )
        meta = dict(inf.raw)
        meta.update({"deleted": True, "size": 0, "chunks": 0, "digest": ""})
        await self.nc.publish(
            f"$O.{bucket}.M.{_b64name(name)}",
            json.dumps(meta, separators=(",", ":")).encode(),
            headers={"Nats-Rollup": "sub"},
        )
        await self.nc.flush()

    async def list(self, bucket: str, include_deleted: bool = False) -> list[ObjectInfo]:
        out: list[ObjectInfo] = []
        seq = 0
        pat = f"$O.{bucket}.M.>"
        while True:
            try:
                msg = await self._direct_get(
                    self._stream(bucket), {"seq": seq + 1, "next_by_subj": pat}
                )
            except ObjectNotFound:
                break
            inf = ObjectInfo.from_json(msg.payload)
            if include_deleted or not inf.deleted:
                out.append(inf)
            seq = int((msg.headers or {}).get("Nats-Sequence", seq + 1))
        return out

from .client import ConnectionClosedError, Msg, NatsClient, RetryPolicy, Subscription, connect
from .broker import EmbeddedBroker
from .envelope import envelope_error, envelope_ok, is_retryable_envelope, shed_cause_of

__all__ = [
    "ConnectionClosedError",
    "Msg",
    "NatsClient",
    "RetryPolicy",
    "Subscription",
    "connect",
    "EmbeddedBroker",
    "envelope_error",
    "envelope_ok",
    "is_retryable_envelope",
    "shed_cause_of",
]

from .client import Msg, NatsClient, Subscription, connect
from .broker import EmbeddedBroker
from .envelope import envelope_error, envelope_ok

__all__ = [
    "Msg",
    "NatsClient",
    "Subscription",
    "connect",
    "EmbeddedBroker",
    "envelope_error",
    "envelope_ok",
]

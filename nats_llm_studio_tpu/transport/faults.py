"""Deterministic fault injection: the chaos harness behind the resilience
story (transport reconnect, engine supervision).

A :class:`FaultPlan` is a seeded, step-indexed list of fault rules. Every
hook site ("broker.publish", "batcher.pump", "client.connect") calls
``plan.check(site, subject)`` once per event; each rule keeps its own count
of *matching* calls and fires exactly once, when that count passes the
rule's 0-based ``step``. Given a deterministic event sequence the firing
point is deterministic — tests assert exact recovery behavior instead of
sleeping and hoping.

Off ⇒ zero cost: with no plan installed every hook is a single module
attribute read (``faults.ACTIVE is None``) — no allocation, no lock, no
branch into this module. Production paths pay nothing.

Env wiring (parsed by :func:`plan_from_env`, installed by ``main.py``):

    CHAOS_SPEC="sever@broker.publish:3:subject=lmstudio.chat_model;raise@batcher.pump:40"
    CHAOS_SEED=0

Rule grammar: ``kind@site:step[:key=value]...`` where ``kind`` is one of
``sever`` | ``drop`` | ``delay`` | ``raise``, ``site`` is a hook-site name
below, ``step`` is the 0-based matching-call index at which the rule fires,
and optional keys are ``subject=<pattern>`` (NATS wildcard filter — only
matching publishes count), ``client=<glob>`` (connection-name filter: only
events from a client whose CONNECT name matches count — the worker-scoped
kill switch, since every worker connects as ``tpu-worker-<worker_id>``),
``delay=<seconds>`` and ``msg=<text>``.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from dataclasses import dataclass, field
from fnmatch import fnmatchcase

from ..utils import subject_matches

log = logging.getLogger(__name__)

# hook-site names — the stable fault-injection surface
BROKER_PUBLISH = "broker.publish"  # a client's PUB/HPUB arriving at the broker
PUMP = "batcher.pump"              # one batcher owner-loop iteration
CLIENT_CONNECT = "client.connect"  # one NatsClient dial attempt (incl. reconnects)
TIER_SPILL = "tier.spill"          # one host-tier → Object Store blob write
TIER_FETCH = "tier.fetch"          # one Object Store → host-tier blob read
SUSPEND = "batcher.suspend"        # one slot suspend attempt (swap-don't-shed)

SITES = (BROKER_PUBLISH, PUMP, CLIENT_CONNECT, TIER_SPILL, TIER_FETCH, SUSPEND)
KINDS = ("sever", "drop", "delay", "raise")


class InjectedFault(RuntimeError):
    """Raised inside a hooked loop by a ``raise`` rule."""


@dataclass
class Fault:
    site: str
    step: int  # fires on the (step+1)-th MATCHING check() call (0-based index)
    kind: str  # "sever" | "drop" | "delay" | "raise"
    subject: str | None = None  # NATS wildcard filter; None matches everything
    client: str | None = None  # connection-name glob; None matches everything
    delay_s: float = 0.0
    message: str = "injected fault (chaos)"
    fired: bool = False
    hits: int = 0  # matching check() calls observed so far

    def exception(self) -> BaseException:
        return InjectedFault(self.message)

    def describe(self) -> str:
        s = f"{self.kind}@{self.site}:{self.step}"
        if self.subject:
            s += f":subject={self.subject}"
        if self.client:
            s += f":client={self.client}"
        if self.kind == "delay":
            s += f":delay={self.delay_s}"
        return s


class FaultPlan:
    """Seeded, step-indexed fault schedule. Thread-safe: ``check`` is called
    from the asyncio loop (broker/client hooks) AND batcher owner threads."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)  # reserved for probabilistic rules
        self.faults: list[Fault] = []
        self.log: list[dict] = []  # fired rules, in firing order (test asserts)
        self._lock = threading.Lock()

    # -- builders (chainable) ------------------------------------------------

    def add(self, fault: Fault) -> "FaultPlan":
        if fault.site not in SITES:
            raise ValueError(f"unknown fault site {fault.site!r} (have {SITES})")
        if fault.kind not in KINDS:
            raise ValueError(f"unknown fault kind {fault.kind!r} (have {KINDS})")
        self.faults.append(fault)
        return self

    def sever(self, site: str, step: int, subject: str | None = None,
              client: str | None = None) -> "FaultPlan":
        return self.add(
            Fault(site=site, step=step, kind="sever", subject=subject, client=client)
        )

    def sever_worker(self, worker_id: str, step: int,
                     subject: str | None = None) -> "FaultPlan":
        """Worker-scoped kill switch: sever the connection of the worker
        whose id is ``worker_id`` on its (step+1)-th matching publish — the
        wire-level equivalent of kill -9 on that worker, mid-flight. Matches
        the ``tpu-worker-<worker_id>`` CONNECT name serve/worker.py uses."""
        return self.sever(BROKER_PUBLISH, step, subject=subject,
                          client=f"tpu-worker-{worker_id}")

    def drop(self, site: str, step: int, subject: str | None = None) -> "FaultPlan":
        return self.add(Fault(site=site, step=step, kind="drop", subject=subject))

    def delay(self, site: str, step: int, delay_s: float,
              subject: str | None = None) -> "FaultPlan":
        return self.add(
            Fault(site=site, step=step, kind="delay", delay_s=delay_s, subject=subject)
        )

    def raise_at(self, site: str, step: int, message: str | None = None) -> "FaultPlan":
        f = Fault(site=site, step=step, kind="raise")
        if message:
            f.message = message
        return self.add(f)

    # -- hook API ------------------------------------------------------------

    def check(
        self, site: str, subject: str | None = None, client: str | None = None
    ) -> Fault | None:
        """Count one event at ``site`` against every matching rule; return
        the first rule that fires on this event (None otherwise). A rule
        fires exactly once, when its matching-call count passes ``step``.
        ``client`` is the originating connection's CONNECT name, for
        client-scoped (worker-scoped) rules."""
        if not self.faults:
            return None
        with self._lock:
            hit: Fault | None = None
            for f in self.faults:
                if f.site != site:
                    continue
                if f.subject is not None and not (
                    subject is not None and subject_matches(f.subject, subject)
                ):
                    continue
                if f.client is not None and not fnmatchcase(client or "", f.client):
                    continue
                f.hits += 1
                if not f.fired and f.hits > f.step:
                    f.fired = True
                    entry = {"site": site, "kind": f.kind, "step": f.step,
                             "subject": subject}
                    if f.client is not None:
                        # only client-scoped rules record the connection
                        # name: the log-entry shape of existing rules is a
                        # test contract
                        entry["client"] = client
                    self.log.append(entry)
                    if hit is None:
                        hit = f
            return hit

    def fired(self, site: str | None = None) -> list[dict]:
        with self._lock:
            return [e for e in self.log if site is None or e["site"] == site]

    def done(self) -> bool:
        """True when every rule has fired (chaos tests assert this)."""
        with self._lock:
            return all(f.fired for f in self.faults)

    def describe(self) -> str:
        rules = ";".join(f.describe() for f in self.faults)
        return f"seed={self.seed} {rules or '(empty)'}"


# module-global active plan: the single attribute hooks read. None in
# production — the whole harness costs one `is None` check per hook event.
ACTIVE: FaultPlan | None = None


def install(plan: FaultPlan | None) -> FaultPlan | None:
    """Install (or, with None, clear) the process-wide fault plan."""
    global ACTIVE
    ACTIVE = plan
    if plan is not None:
        log.warning("chaos fault plan installed: %s", plan.describe())
    return plan


def clear() -> None:
    install(None)


def plan_from_env(environ=None) -> FaultPlan | None:
    """Build a plan from ``CHAOS_SPEC`` / ``CHAOS_SEED`` (None when unset).
    See the module docstring for the rule grammar."""
    env = os.environ if environ is None else environ
    spec = (env.get("CHAOS_SPEC") or "").strip()
    if not spec:
        return None
    try:
        seed = int((env.get("CHAOS_SEED") or "0").strip() or 0)
    except ValueError:
        seed = 0
    plan = FaultPlan(seed)
    for rule in spec.split(";"):
        rule = rule.strip()
        if not rule:
            continue
        try:
            kind, rest = rule.split("@", 1)
            parts = rest.split(":")
            site = parts[0]
            step = int(parts[1])
            f = Fault(site=site, step=step, kind=kind.strip())
            for extra in parts[2:]:
                key, _, val = extra.partition("=")
                if key == "subject":
                    f.subject = val
                elif key == "client":
                    f.client = val
                elif key == "delay":
                    f.delay_s = float(val)
                elif key == "msg":
                    f.message = val
                else:
                    raise ValueError(f"unknown key {key!r}")
            plan.add(f)
        except (ValueError, IndexError) as e:
            raise ValueError(f"bad CHAOS_SPEC rule {rule!r}: {e}") from None
    return plan

"""The uniform JSON response envelope: ``{ok, error?, data?}``.

Byte-for-byte contract of the reference's ``NATSResponse``
(/root/reference/nats_llm_studio.go:186-190): ``ok`` always present, ``error``
and ``data`` omitted when empty. ``FALLBACK`` reproduces the hardcoded
marshal-failure reply (nats_llm_studio.go:211).
"""

from __future__ import annotations

import json
import time
from typing import Any

FALLBACK = b'{"ok":false,"error":"internal serialization error"}'

# error-message shapes that mean "this worker cannot serve the request right
# now, but a queue-group peer (or this worker, shortly) can" — the single
# source of truth shared by the worker (stamping ``retryable`` on envelopes)
# and the client retry policy (recognizing unstamped legacy envelopes):
# drain truncation (serve/registry.py), submit-after-stop, depth/age sheds
# (serve/batcher.py), supervisor crash-failures and poisoned refusals.
RETRYABLE_MARKERS = (
    "retry on another worker",
    "overloaded:",
    "shed after",
    "worker draining",
    # every QoS shed carries a machine-readable cause token (below); the
    # marker keeps a cause-stamped error retryable even if a future shed
    # path forgets the human "retry ..." suffix
    "shed_cause=",
)

# machine-readable shed causes a QoS-aware shed embeds in its error text as
# a ``shed_cause=<cause>`` token (serve/batcher.py, gateway quota checks).
# The gateway surfaces the cause in its 429/503 body instead of a generic
# "overloaded", and picks the status from it: quota/fair_share are the
# CALLER's budget (429 — retrying elsewhere cannot help), the rest are
# worker-local pressure (503 — a peer may serve it).
SHED_CAUSES = (
    "quota",        # gateway: rate limit or monthly token quota
    "fair_share",   # batcher: DRR/depth displacement by weighted fair share
    "preempted",    # batcher: slot taken by a higher-priority admit
    "brownout",     # batcher: load-shed level gated this class out
    "depth",        # batcher: admit queue depth bound
    "age",          # batcher: admit queue age bound
    "kv_pool",      # batcher: block pool dry after reclaim+suspend
    "deadline",     # batcher: client budget expired
)


def shed_cause(cause: str) -> str:
    """The cause token to embed in a shed's error text."""
    return f"shed_cause={cause}"


def shed_cause_of(error) -> str | None:
    """Extract the ``shed_cause=<cause>`` token from an error string (or a
    decoded envelope's ``error`` field); None when absent/unknown — old
    workers' cause-less sheds still read as generic overload."""
    if isinstance(error, dict):
        error = error.get("error", "")
    low = str(error or "").lower()
    i = low.find("shed_cause=")
    if i < 0:
        return None
    tok = low[i + len("shed_cause="):].split()[0].strip(";,.()[]")
    return tok if tok in SHED_CAUSES else None


def error_is_retryable(error: str) -> bool:
    """True when the error text matches a known transient/retryable shape."""
    low = error.lower()
    return any(m in low for m in RETRYABLE_MARKERS)


def is_retryable_envelope(env: Any) -> bool:
    """True for a decoded ``{ok: false, ...}`` envelope a client retry
    policy may retry: either explicitly stamped ``retryable: true`` or
    carrying a recognized retryable error message."""
    if not isinstance(env, dict) or env.get("ok", False):
        return False
    if env.get("retryable"):
        return True
    return error_is_retryable(str(env.get("error", "")))


def deadline_header_value(timeout_s: float) -> str:
    """Absolute wall-clock deadline (ms since the epoch) for
    ``protocol.DEADLINE_HEADER``, derived from the caller's timeout."""
    return str(int((time.time() + timeout_s) * 1000))


def deadline_remaining_s(header_value: str | None) -> float | None:
    """Seconds of client budget left for a ``DEADLINE_HEADER`` value
    (negative once expired), or None when absent or unparseable — a garbled
    header must never fail a request that would otherwise serve."""
    if not header_value:
        return None
    try:
        deadline_ms = int(header_value)
    except (TypeError, ValueError):
        return None
    return deadline_ms / 1000.0 - time.time()


def envelope_ok(data: Any = None, trace_id: str | None = None) -> bytes:
    env: dict[str, Any] = {"ok": True}
    if data is not None:
        env["data"] = data
    if trace_id:
        # top-level, next to ok/error: omitted entirely for untraced ops so
        # the reference's byte-for-byte envelope shape is unchanged there
        env["trace_id"] = trace_id
    return _dump(env)


def envelope_error(
    error: str,
    data: Any = None,
    trace_id: str | None = None,
    retryable: bool | None = None,
) -> bytes:
    env: dict[str, Any] = {"ok": False, "error": error}
    if data is not None:
        env["data"] = data
    if trace_id:
        env["trace_id"] = trace_id
    if retryable is None:
        retryable = error_is_retryable(error)
    if retryable:
        # additive field: only present (and true) on retryable errors, so
        # the reference's byte-for-byte envelope shape is unchanged on every
        # terminal error path
        env["retryable"] = True
    return _dump(env)


def _dump(env: dict) -> bytes:
    try:
        return json.dumps(env, separators=(",", ":")).encode()
    except (TypeError, ValueError):
        return FALLBACK

"""The uniform JSON response envelope: ``{ok, error?, data?}``.

Byte-for-byte contract of the reference's ``NATSResponse``
(/root/reference/nats_llm_studio.go:186-190): ``ok`` always present, ``error``
and ``data`` omitted when empty. ``FALLBACK`` reproduces the hardcoded
marshal-failure reply (nats_llm_studio.go:211).
"""

from __future__ import annotations

import json
import time
from typing import Any

FALLBACK = b'{"ok":false,"error":"internal serialization error"}'

# error-message shapes that mean "this worker cannot serve the request right
# now, but a queue-group peer (or this worker, shortly) can" — the single
# source of truth shared by the worker (stamping ``retryable`` on envelopes)
# and the client retry policy (recognizing unstamped legacy envelopes):
# drain truncation (serve/registry.py), submit-after-stop, depth/age sheds
# (serve/batcher.py), supervisor crash-failures and poisoned refusals.
RETRYABLE_MARKERS = (
    "retry on another worker",
    "overloaded:",
    "shed after",
    "worker draining",
)


def error_is_retryable(error: str) -> bool:
    """True when the error text matches a known transient/retryable shape."""
    low = error.lower()
    return any(m in low for m in RETRYABLE_MARKERS)


def is_retryable_envelope(env: Any) -> bool:
    """True for a decoded ``{ok: false, ...}`` envelope a client retry
    policy may retry: either explicitly stamped ``retryable: true`` or
    carrying a recognized retryable error message."""
    if not isinstance(env, dict) or env.get("ok", False):
        return False
    if env.get("retryable"):
        return True
    return error_is_retryable(str(env.get("error", "")))


def deadline_header_value(timeout_s: float) -> str:
    """Absolute wall-clock deadline (ms since the epoch) for
    ``protocol.DEADLINE_HEADER``, derived from the caller's timeout."""
    return str(int((time.time() + timeout_s) * 1000))


def deadline_remaining_s(header_value: str | None) -> float | None:
    """Seconds of client budget left for a ``DEADLINE_HEADER`` value
    (negative once expired), or None when absent or unparseable — a garbled
    header must never fail a request that would otherwise serve."""
    if not header_value:
        return None
    try:
        deadline_ms = int(header_value)
    except (TypeError, ValueError):
        return None
    return deadline_ms / 1000.0 - time.time()


def envelope_ok(data: Any = None, trace_id: str | None = None) -> bytes:
    env: dict[str, Any] = {"ok": True}
    if data is not None:
        env["data"] = data
    if trace_id:
        # top-level, next to ok/error: omitted entirely for untraced ops so
        # the reference's byte-for-byte envelope shape is unchanged there
        env["trace_id"] = trace_id
    return _dump(env)


def envelope_error(
    error: str,
    data: Any = None,
    trace_id: str | None = None,
    retryable: bool | None = None,
) -> bytes:
    env: dict[str, Any] = {"ok": False, "error": error}
    if data is not None:
        env["data"] = data
    if trace_id:
        env["trace_id"] = trace_id
    if retryable is None:
        retryable = error_is_retryable(error)
    if retryable:
        # additive field: only present (and true) on retryable errors, so
        # the reference's byte-for-byte envelope shape is unchanged on every
        # terminal error path
        env["retryable"] = True
    return _dump(env)


def _dump(env: dict) -> bytes:
    try:
        return json.dumps(env, separators=(",", ":")).encode()
    except (TypeError, ValueError):
        return FALLBACK

"""The uniform JSON response envelope: ``{ok, error?, data?}``.

Byte-for-byte contract of the reference's ``NATSResponse``
(/root/reference/nats_llm_studio.go:186-190): ``ok`` always present, ``error``
and ``data`` omitted when empty. ``FALLBACK`` reproduces the hardcoded
marshal-failure reply (nats_llm_studio.go:211).
"""

from __future__ import annotations

import json
from typing import Any

FALLBACK = b'{"ok":false,"error":"internal serialization error"}'


def envelope_ok(data: Any = None, trace_id: str | None = None) -> bytes:
    env: dict[str, Any] = {"ok": True}
    if data is not None:
        env["data"] = data
    if trace_id:
        # top-level, next to ok/error: omitted entirely for untraced ops so
        # the reference's byte-for-byte envelope shape is unchanged there
        env["trace_id"] = trace_id
    return _dump(env)


def envelope_error(error: str, data: Any = None, trace_id: str | None = None) -> bytes:
    env: dict[str, Any] = {"ok": False, "error": error}
    if data is not None:
        env["data"] = data
    if trace_id:
        env["trace_id"] = trace_id
    return _dump(env)


def _dump(env: dict) -> bytes:
    try:
        return json.dumps(env, separators=(",", ":")).encode()
    except (TypeError, ValueError):
        return FALLBACK

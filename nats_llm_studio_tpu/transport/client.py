"""Asyncio NATS client: pub/sub, queue groups, request-reply, streaming requests.

Provides the client capabilities the reference gets from nats.go v1.47.0
(/root/reference/go.mod:8): ``Publish``/``Subscribe``/``QueueSubscribe``/
``Request`` with a muxed ``_INBOX.<nuid>.*`` reply subscription, plus
``request_stream`` — the multi-reply extension the TPU build uses for token
streaming (SURVEY.md §7 hard-part 3): many messages arrive on the reply inbox
and the terminal one carries a ``Nats-Stream-Done`` header with the aggregate,
so naive single-reply clients still see a complete response.

Fault tolerance (the nats.go behaviors the first cut dropped):

* **auto-reconnect** with exponential backoff + jitter when the TCP
  connection is lost (``max_reconnects`` attempts, 0 disables); live
  subscriptions are automatically re-issued on the new connection and
  publishes made while down are buffered (bounded by
  ``pending_buffer_bytes``) and flushed on reconnect
* **PING keepalive** (``ping_interval_s`` > 0): a connection that stops
  answering ``max_outstanding_pings`` consecutive PINGs is declared stale
  and dropped into the reconnect path instead of hanging forever
* **fail-fast closed-connection errors**: ``flush()``/``request()`` raise
  :class:`ConnectionClosedError` the moment the connection is gone instead
  of waiting out the full request timeout, and in-flight request futures
  are failed the same way on a disconnect — so a retry policy can re-issue
  immediately after the reconnect
* **opt-in request retries**: ``request(..., retry=RetryPolicy(...))``
  retries on lost connections and on *retryable* error envelopes (the
  ``"worker draining, retry on another worker"`` / shed shapes — see
  ``transport/envelope.py``), with bounded attempts and backoff
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from dataclasses import dataclass
from typing import AsyncIterator, Awaitable, Callable
from urllib.parse import urlparse

from ..obs import new_trace_id
from ..obs import emit as obs_emit
from ..utils import next_nuid
from . import faults as _faults
from . import protocol as p
from .envelope import deadline_header_value, deadline_remaining_s, is_retryable_envelope

log = logging.getLogger(__name__)


class ConnectionClosedError(ConnectionError):
    """The connection is gone and no reply can arrive on it: closed, never
    connected, reconnect disabled/exhausted, or dropped mid-request. Raised
    instead of letting callers wait out a request timeout on a dead socket."""


@dataclass(slots=True)
class RetryPolicy:
    """Bounded retry for ``request()``: lost connections and *retryable*
    error envelopes (``envelope.is_retryable_envelope``) are re-issued after
    exponential backoff with jitter; other errors surface immediately.
    ``retry_on_timeout`` additionally retries request timeouts — only safe
    for idempotent operations (the first attempt may still execute)."""

    max_attempts: int = 3
    backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    jitter: float = 0.25  # fraction of the delay added uniformly at random
    retry_on_timeout: bool = False

    def delay_s(self, attempt: int) -> float:
        """Backoff before attempt ``attempt + 1`` (attempt is 1-based)."""
        d = min(self.backoff_s * (2 ** (attempt - 1)), self.max_backoff_s)
        return d * (1.0 + random.random() * self.jitter)


@dataclass(slots=True)
class Msg:
    subject: str
    payload: bytes
    reply: str | None = None
    headers: dict[str, str] | None = None
    _client: "NatsClient | None" = None

    def json(self):
        return json.loads(self.payload or b"null")

    async def respond(self, payload: bytes, headers: dict[str, str] | None = None) -> None:
        """Reply via this message's own connection — mirrors msg.Respond in the
        reference (/root/reference/nats_llm_studio.go:214)."""
        if not self.reply:
            raise ValueError("message has no reply subject")
        assert self._client is not None
        await self._client.publish(self.reply, payload, headers=headers)


# queue sentinel a reconnect pushes into gap-sensitive subscriptions (only
# request_stream opts in): replies published while the connection was down
# are gone, so the stream must fail fast rather than idle out
_GAP = object()


class Subscription:
    def __init__(self, client: "NatsClient", sid: str, subject: str, queue: str | None):
        self._client = client
        self.sid = sid
        self.subject = subject
        self.queue = queue
        self._queue: asyncio.Queue[Msg | None] = asyncio.Queue()
        self._cb: Callable[[Msg], Awaitable[None]] | None = None
        self._cb_tasks: set[asyncio.Task] = set()
        self.closed = False
        self._delivered = 0  # total messages handed to this sub
        self._max_msgs: int | None = None  # auto-unsub bound, if any
        self._fail_on_gap = False  # next_msg raises after a reconnect gap

    def _deliver(self, msg: Msg) -> None:
        self._delivered += 1
        if self._cb is not None:
            task = asyncio.ensure_future(self._cb(msg))
            self._cb_tasks.add(task)
            task.add_done_callback(self._cb_tasks.discard)
        else:
            self._queue.put_nowait(msg)

    def _deliver_gap(self) -> None:
        """Reconnect notice for gap-sensitive consumers (request_stream):
        messages may have been lost while the connection was down."""
        if self._fail_on_gap and not self.closed:
            self._queue.put_nowait(_GAP)

    def _close_local(self) -> None:
        """Mark closed and wake pending next_msg waiters (no wire traffic)."""
        if not self.closed:
            self.closed = True
            self._queue.put_nowait(None)

    async def next_msg(self, timeout: float | None = None) -> Msg:
        if self.closed and self._queue.empty():
            raise BrokenPipeError("subscription closed")
        msg = await asyncio.wait_for(self._queue.get(), timeout)
        if msg is None:
            raise BrokenPipeError("subscription closed")
        if msg is _GAP:
            raise ConnectionClosedError(
                "connection lost mid-stream; replies may have been missed"
            )
        return msg

    def __aiter__(self) -> AsyncIterator[Msg]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[Msg]:
        while True:
            try:
                yield await self.next_msg()
            except BrokenPipeError:
                return

    async def unsubscribe(self) -> None:
        if not self.closed:
            self._close_local()
            await self._client._unsubscribe(self.sid)

    async def auto_unsubscribe(self, max_msgs: int) -> None:
        """UNSUB <sid> <max_msgs>: the server stops after ``max_msgs`` total
        deliveries to this sid; the client closes the sub at the same count."""
        await self._client._unsubscribe(self.sid, max_msgs)


class NatsClient:
    """A single NATS connection (with automatic reconnection)."""

    def __init__(self) -> None:
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._parser = p.Parser()
        self._subs: dict[str, Subscription] = {}
        self._next_sid = 0
        self._read_task: asyncio.Task | None = None
        self._pong_waiters: list[asyncio.Future] = []
        self._inbox_prefix = f"_INBOX.{next_nuid()}"
        self._resp_futures: dict[str, asyncio.Future[Msg]] = {}
        self._resp_sub_started = False
        self._closed = asyncio.Event()
        self.server_info: dict = {}
        self._write_lock = asyncio.Lock()
        # -- reconnect state --------------------------------------------------
        self._url = "nats://127.0.0.1:4222"
        self._name: str | None = None
        self._connected = asyncio.Event()  # cleared while the link is down
        self._reconnect_task: asyncio.Task | None = None
        self._ping_task: asyncio.Task | None = None
        self._pending: list[bytes] = []  # frames buffered while reconnecting
        self._pending_bytes = 0
        self._outstanding_pings = 0
        self.reconnects = 0  # completed reconnects (prometheus: lmstudio_reconnects_total)
        self.last_reconnect_s = 0.0  # duration of the last reconnect (bench reports it)
        # knobs (overridable via connect()): nats.go-like defaults, scaled
        # for the embedded single-host broker
        self.max_reconnects = 60  # 0 disables auto-reconnect entirely
        self.reconnect_wait_s = 0.05  # backoff base (doubles per attempt)
        self.reconnect_max_wait_s = 2.0  # backoff cap
        self.ping_interval_s = 0.0  # 0 disables the keepalive task
        self.max_outstanding_pings = 2  # unanswered PINGs before declaring stale
        self.pending_buffer_bytes = 1 << 20  # publish buffer bound while down

    # -- lifecycle ----------------------------------------------------------

    async def connect(
        self,
        url: str = "nats://127.0.0.1:4222",
        name: str | None = None,
        max_reconnects: int | None = None,
        reconnect_wait_s: float | None = None,
        reconnect_max_wait_s: float | None = None,
        ping_interval_s: float | None = None,
        max_outstanding_pings: int | None = None,
        pending_buffer_bytes: int | None = None,
    ) -> None:
        self._url = url
        self._name = name
        if max_reconnects is not None:
            self.max_reconnects = max_reconnects
        if reconnect_wait_s is not None:
            self.reconnect_wait_s = reconnect_wait_s
        if reconnect_max_wait_s is not None:
            self.reconnect_max_wait_s = reconnect_max_wait_s
        if ping_interval_s is not None:
            self.ping_interval_s = ping_interval_s
        if max_outstanding_pings is not None:
            self.max_outstanding_pings = max_outstanding_pings
        if pending_buffer_bytes is not None:
            self.pending_buffer_bytes = pending_buffer_bytes
        await self._dial()
        self._connected.set()
        await self.flush()
        if self.ping_interval_s > 0 and self._ping_task is None:
            self._ping_task = asyncio.ensure_future(self._ping_loop())

    async def _dial(self) -> None:
        """One connection attempt: TCP connect, INFO/CONNECT handshake, fresh
        read loop. Shared by the initial connect and every reconnect."""
        if _faults.ACTIVE is not None:
            f = _faults.ACTIVE.check(_faults.CLIENT_CONNECT)
            if f is not None and f.kind == "raise":
                raise ConnectionError("injected connect failure (chaos)")
        u = urlparse(self._url)
        host = u.hostname or "127.0.0.1"
        port = u.port or 4222
        reader, writer = await asyncio.open_connection(host, port)
        parser = p.Parser()
        # read INFO
        line = await reader.readline()
        events = list(parser.feed(line))
        if not events or not isinstance(events[0], p.InfoEvent):
            writer.close()
            raise ConnectionError(f"expected INFO, got {events!r}")
        self.server_info = events[0].info
        opts = {
            "verbose": False,
            "pedantic": False,
            "lang": "python-tpu",
            "version": "0.1.0",
            "protocol": 1,
            "headers": True,
        }
        if self._name:
            opts["name"] = self._name
        writer.write(p.encode_connect(opts) + p.PING)
        await writer.drain()
        self._reader, self._writer, self._parser = reader, writer, parser
        self._outstanding_pings = 0
        self._read_task = asyncio.ensure_future(self._read_loop(reader))
        # NOTE: callers set _connected — the reconnect path restores subs and
        # flushes the pending buffer FIRST, so concurrent publishes can't
        # jump ahead of buffered ones

    async def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        self._connected.clear()
        # the read loop calls close() on EOF: cancelling the task running us
        # would abort the cleanup below at the first await
        cur = asyncio.current_task()
        for task in (self._read_task, self._reconnect_task, self._ping_task):
            if task is not None and task is not cur:
                task.cancel()
        for sub in self._subs.values():
            sub._close_local()
        for fut in self._resp_futures.values():
            if not fut.done():
                fut.set_exception(ConnectionClosedError("connection closed"))
        self._resp_futures.clear()
        for fut in self._pong_waiters:
            if not fut.done():
                fut.set_exception(ConnectionClosedError("connection closed"))
        self._pong_waiters.clear()
        self._pending.clear()
        self._pending_bytes = 0
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def drain(self) -> None:
        """Unsubscribe everything, flush, close — graceful worker shutdown
        (the runtime behavior /root/reference/README.md:475-484 leaves to the
        embedding application)."""
        for sub in list(self._subs.values()):
            await sub.unsubscribe()
        try:
            await self.flush()
        except ConnectionError:
            pass
        await self.close()

    # -- reconnect machinery -------------------------------------------------

    @property
    def is_connected(self) -> bool:
        return self._connected.is_set() and not self._closed.is_set()

    def _begin_reconnect(self) -> None:
        """The link just dropped: fail in-flight request/flush waiters FAST
        (so retry policies can re-issue after the reconnect instead of
        waiting out their timeouts), notify gap-sensitive streams, and start
        the reconnect task. Idempotent while a reconnect is in flight."""
        if self._closed.is_set():
            return
        self._connected.clear()
        self._outstanding_pings = 0
        if self._writer is not None:
            try:
                self._writer.close()
            except (ConnectionError, OSError):
                pass
        err = ConnectionClosedError("connection lost; reconnecting")
        for fut in self._resp_futures.values():
            if not fut.done():
                fut.set_exception(err)
        self._resp_futures.clear()
        for fut in self._pong_waiters:
            if not fut.done():
                fut.set_exception(err)
        self._pong_waiters.clear()
        for sub in list(self._subs.values()):
            sub._deliver_gap()
        if self._reconnect_task is None or self._reconnect_task.done():
            self._reconnect_task = asyncio.ensure_future(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        """Exponential backoff + jitter until the dial succeeds (or the
        attempt budget runs out → close). On success: re-issue every live
        subscription, flush the pending publish buffer, count the reconnect."""
        t0 = time.monotonic()
        attempt = 0
        while not self._closed.is_set():
            attempt += 1
            if self.max_reconnects > 0 and attempt > self.max_reconnects:
                log.error(
                    "reconnect to %s abandoned after %d attempts", self._url,
                    self.max_reconnects,
                )
                await self.close()
                return
            delay = min(
                self.reconnect_wait_s * (2 ** (attempt - 1)),
                self.reconnect_max_wait_s,
            )
            # jitter: avoids a reconnect stampede when many clients lose the
            # same broker at the same instant
            await asyncio.sleep(delay * (1.0 + random.random() * 0.25))
            if self._closed.is_set():
                return
            try:
                await self._dial()
            except (ConnectionError, OSError, asyncio.TimeoutError):
                continue
            n_subs = sum(1 for s in self._subs.values() if not s.closed)
            n_flushed = len(self._pending)
            try:
                await self._restore_state()
            except (ConnectionError, OSError):
                # the fresh connection died during restore: its read loop
                # saw the EOF too, but _begin_reconnect no-ops while THIS
                # task is alive — so loop and dial again ourselves
                self._connected.clear()
                continue
            self._connected.set()
            self.reconnects += 1
            self.last_reconnect_s = time.monotonic() - t0
            log.info(
                "reconnected to %s after %d attempt(s) in %.3fs "
                "(%d subs restored, %d buffered frames flushed)",
                self._url, attempt, self.last_reconnect_s, n_subs, n_flushed,
            )
            obs_emit(
                "client_reconnect", url=self._url, attempts=attempt,
                seconds=round(self.last_reconnect_s, 4),
            )
            return

    async def _restore_state(self) -> None:
        """Re-SUB every live subscription (re-arming remaining auto-unsub
        bounds) and flush publishes buffered while the link was down."""
        assert self._writer is not None
        async with self._write_lock:
            for sid, sub in list(self._subs.items()):
                if sub.closed:
                    continue
                self._writer.write(p.encode_sub(sub.subject, sid, sub.queue))
                if sub._max_msgs is not None:
                    # server delivery counts reset with the new SUB: re-arm
                    # with what this sub is still owed
                    remaining = max(1, sub._max_msgs - sub._delivered)
                    self._writer.write(p.encode_unsub(sid, remaining))
            # loop: the drain awaits can interleave with _send calls that
            # buffer more frames (we are still "down" until the caller sets
            # _connected) — flush until the buffer stays empty
            while self._pending:
                pending, self._pending = self._pending, []
                self._pending_bytes = 0
                for frame in pending:
                    self._writer.write(frame)
                await self._writer.drain()
            await self._writer.drain()

    async def _ping_loop(self) -> None:
        """Client-originated keepalive: a connection that stops answering
        ``max_outstanding_pings`` consecutive PINGs is stale (half-open TCP,
        hung broker) and is dropped into the reconnect path — the silent
        hang the reference's request timeout was the only detector for."""
        try:
            while not self._closed.is_set():
                await asyncio.sleep(self.ping_interval_s)
                if not self._connected.is_set():
                    continue
                if self._outstanding_pings >= self.max_outstanding_pings:
                    log.warning(
                        "stale connection to %s (%d unanswered PINGs); dropping",
                        self._url, self._outstanding_pings,
                    )
                    obs_emit("client_stale_connection", url=self._url,
                             outstanding_pings=self._outstanding_pings)
                    self._begin_reconnect()
                    continue
                self._outstanding_pings += 1
                try:
                    await self._send(p.PING)
                except ConnectionError:
                    continue
        except asyncio.CancelledError:
            pass

    # -- core ops -----------------------------------------------------------

    async def _send(self, data: bytes) -> None:
        if self._closed.is_set():
            raise ConnectionClosedError("connection closed")
        if not self._connected.is_set():
            if self._reconnect_task is not None and not self._reconnect_task.done():
                # reconnecting: buffer (bounded) and flush on the new link
                if self._pending_bytes + len(data) > self.pending_buffer_bytes:
                    raise ConnectionClosedError(
                        f"pending buffer full ({self._pending_bytes} bytes) "
                        f"while reconnecting"
                    )
                self._pending.append(data)
                self._pending_bytes += len(data)
                return
            raise ConnectionClosedError("not connected")
        assert self._writer is not None
        try:
            async with self._write_lock:
                self._writer.write(data)
                await self._writer.drain()
        except (ConnectionError, OSError) as e:
            # the write path noticed the drop before the read loop did
            if self.max_reconnects:
                self._begin_reconnect()
            raise ConnectionClosedError(f"connection lost during send: {e}") from e

    async def publish(
        self,
        subject: str,
        payload: bytes = b"",
        reply: str | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        # client-side guard, same as nats.go/nats.py: the server would answer
        # a violation with -ERR (and real nats-server drops the connection),
        # so fail fast with the advertised limit instead
        limit = (self.server_info or {}).get("max_payload")
        if limit and len(payload) > int(limit):
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds server max_payload {limit}"
            )
        await self._send(p.encode_pub(subject, payload, reply, headers))

    async def subscribe(
        self,
        subject: str,
        queue: str | None = None,
        cb: Callable[[Msg], Awaitable[None]] | None = None,
    ) -> Subscription:
        if self._closed.is_set():
            raise ConnectionClosedError("connection closed")
        if self._writer is None:
            raise ConnectionClosedError("not connected")
        self._next_sid += 1
        sid = str(self._next_sid)
        sub = Subscription(self, sid, subject, queue)
        sub._cb = cb
        self._subs[sid] = sub
        if self._connected.is_set():
            await self._send(p.encode_sub(subject, sid, queue))
        # else: registered locally; the reconnect's _restore_state re-issues
        # SUB for every live sub, including this one
        return sub

    async def _unsubscribe(self, sid: str, max_msgs: int | None = None) -> None:
        if max_msgs is None:
            # immediate unsubscribe: the server stops routing now, drop ours
            self._subs.pop(sid, None)
        else:
            # auto-unsub: the SERVER stops after max_msgs total deliveries;
            # mirror the bound client-side so the sub is closed and removed
            # when the count is exhausted (see _dispatch) instead of leaking
            # in _subs forever
            sub = self._subs.get(sid)
            if sub is not None:
                if sub._delivered >= max_msgs:
                    self._subs.pop(sid, None)
                    sub._close_local()
                else:
                    sub._max_msgs = max_msgs
        try:
            await self._send(p.encode_unsub(sid, max_msgs))
        except ConnectionError:
            pass

    async def flush(self, timeout: float = 10.0) -> None:
        if self._closed.is_set() or self._writer is None:
            # fail fast: no PONG can ever arrive on a closed connection —
            # waiting out `timeout` here was the satellite bug
            raise ConnectionClosedError("connection closed")
        if not self._connected.is_set():
            raise ConnectionClosedError("connection lost; reconnecting")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pong_waiters.append(fut)
        await self._send(p.PING)
        await asyncio.wait_for(fut, timeout)

    # -- request-reply ------------------------------------------------------

    async def _ensure_resp_sub(self) -> None:
        if self._resp_sub_started:
            return
        self._resp_sub_started = True

        async def on_resp(msg: Msg) -> None:
            token = msg.subject.rsplit(".", 1)[-1]
            fut = self._resp_futures.pop(token, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)

        await self.subscribe(self._inbox_prefix + ".*", cb=on_resp)

    def new_inbox(self) -> str:
        return f"_INBOX.{next_nuid()}"

    async def request(
        self,
        subject: str,
        payload: bytes = b"",
        timeout: float = 2.0,
        headers: dict[str, str] | None = None,
        retry: RetryPolicy | None = None,
    ) -> Msg:
        """Single request, single reply — the pattern every reference subject
        uses (/root/reference/README.md:86-88, :131-134, :181-186, :237-245).

        A trace id is minted into the ``X-Trace-Id`` header when the caller
        did not set one, so every request is traceable end-to-end (the worker
        echoes it in the envelope and stamps per-stage spans under it).

        With ``retry``, lost connections (``ConnectionClosedError``) and
        *retryable* error envelopes are re-issued up to
        ``retry.max_attempts`` times with backoff; each re-issue uses a
        fresh inbox token, so a late reply to an abandoned attempt can never
        be mistaken for the current one. The final attempt's envelope (even
        a retryable error) is returned honestly.

        ONE absolute deadline (``X-Deadline-Ms``, minted from the first
        attempt's timeout unless the caller stamped it) spans every attempt:
        each attempt's timeout is capped by the remaining budget, backoff
        sleeps never outlast it, and when the budget is gone the last
        retryable envelope (or error) surfaces immediately instead of
        sleeping past the caller's deadline.

        Workers echo their id in the ``X-Worker-Id`` reply header (and the
        envelope's ``data.worker_id``); each retryable failure adds it to
        the ``X-Excluded-Workers`` header of the next attempt, so a worker
        that just shed (or died under) this request bounces a queue-group
        redelivery retryably instead of serving the retry."""
        if retry is None:
            return await self._request_once(subject, payload, timeout, headers)
        # ONE trace id spans every attempt of a retried request (minted
        # here, before the attempt loop): the retries are the same logical
        # request, and a per-attempt id would shatter its story across the
        # cluster's traces. The attempt header tells the spans apart.
        headers = dict(headers) if headers else {}
        headers.setdefault(p.TRACE_HEADER, new_trace_id())
        headers.setdefault(p.DEADLINE_HEADER, deadline_header_value(timeout))
        deadline_hdr = headers[p.DEADLINE_HEADER]
        excluded = p.parse_worker_list(headers.get(p.EXCLUDED_WORKERS_HEADER))
        last_exc: BaseException | None = None
        last_msg: Msg | None = None
        for attempt in range(1, retry.max_attempts + 1):
            remaining = deadline_remaining_s(deadline_hdr)
            attempt_timeout = (
                timeout if remaining is None else min(timeout, remaining)
            )
            if attempt_timeout <= 0:
                break  # budget exhausted: report the last outcome honestly
            headers[p.ATTEMPT_HEADER] = str(attempt)
            try:
                msg = await self._request_once(
                    subject, payload, attempt_timeout, headers
                )
            except ConnectionClosedError as e:
                last_exc, last_msg = e, None
            except asyncio.TimeoutError as e:
                if not retry.retry_on_timeout:
                    raise
                last_exc, last_msg = e, None
            else:
                if attempt < retry.max_attempts and self._retryable_reply(msg):
                    last_exc, last_msg = None, msg
                    wid = self._reply_worker_id(msg)
                    if wid:
                        if self._is_excluded_bounce(msg):
                            # exclusion is one-shot: the bounce already
                            # deflected the immediate retry, so drop the
                            # worker — a single-worker group (or one whose
                            # every member shed once) must stay servable
                            if wid in excluded:
                                excluded.remove(wid)
                        elif wid not in excluded:
                            excluded.append(wid)
                        if excluded:
                            headers[p.EXCLUDED_WORKERS_HEADER] = (
                                p.format_worker_list(excluded)
                            )
                        else:
                            headers.pop(p.EXCLUDED_WORKERS_HEADER, None)
                    if not await self._backoff_within_budget(
                        retry.delay_s(attempt), deadline_hdr
                    ):
                        break
                    continue
                return msg
            if attempt >= retry.max_attempts:
                break
            if isinstance(last_exc, ConnectionClosedError) and not self._closed.is_set():
                # give the reconnect a chance before burning the next attempt
                try:
                    await asyncio.wait_for(self._connected.wait(), attempt_timeout)
                except asyncio.TimeoutError:
                    pass
            if not await self._backoff_within_budget(
                retry.delay_s(attempt), deadline_hdr
            ):
                break
        if last_msg is not None:
            return last_msg
        if last_exc is not None:
            raise last_exc
        raise asyncio.TimeoutError(
            f"deadline budget exhausted before request to {subject}"
        )

    @staticmethod
    async def _backoff_within_budget(delay: float, deadline_hdr: str) -> bool:
        """Sleep ``delay`` only if the deadline budget survives it; False
        means the budget is (or would be) exhausted and retrying must stop
        now rather than sleeping past the caller's deadline."""
        remaining = deadline_remaining_s(deadline_hdr)
        if remaining is not None and delay >= remaining:
            return False
        await asyncio.sleep(delay)
        return True

    @staticmethod
    def _reply_worker_id(msg: Msg) -> str | None:
        """The replying worker's id: the ``X-Worker-Id`` header when
        present, else the envelope's ``data.worker_id``."""
        wid = (msg.headers or {}).get(p.WORKER_HEADER)
        if wid:
            return wid
        try:
            env = json.loads(msg.payload or b"null")
        except ValueError:
            return None
        if isinstance(env, dict) and isinstance(env.get("data"), dict):
            wid = env["data"].get("worker_id")
            return wid if isinstance(wid, str) and wid else None
        return None

    @staticmethod
    def _retryable_reply(msg: Msg) -> bool:
        try:
            env = json.loads(msg.payload or b"null")
        except ValueError:
            return False
        return is_retryable_envelope(env)

    @staticmethod
    def _is_excluded_bounce(msg: Msg) -> bool:
        """True for a worker's self-check bounce (it matched the request's
        ``X-Excluded-Workers`` header) — the one retryable reply that should
        SHRINK the exclusion list instead of growing it."""
        try:
            env = json.loads(msg.payload or b"null")
        except ValueError:
            return False
        return isinstance(env, dict) and isinstance(env.get("data"), dict) \
            and bool(env["data"].get("excluded_bounce"))

    async def _request_once(
        self,
        subject: str,
        payload: bytes,
        timeout: float,
        headers: dict[str, str] | None,
    ) -> Msg:
        await self._ensure_resp_sub()
        headers = dict(headers) if headers else {}
        headers.setdefault(p.TRACE_HEADER, new_trace_id())
        # absolute budget: the worker sheds/aborts work the caller has
        # already abandoned (capped server-side by the per-op ladder)
        headers.setdefault(p.DEADLINE_HEADER, deadline_header_value(timeout))
        token = next_nuid()
        inbox = f"{self._inbox_prefix}.{token}"
        fut: asyncio.Future[Msg] = asyncio.get_running_loop().create_future()
        self._resp_futures[token] = fut
        await self.publish(subject, payload, reply=inbox, headers=headers)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._resp_futures.pop(token, None)
            raise
        except BaseException:
            self._resp_futures.pop(token, None)
            raise

    async def request_stream(
        self,
        subject: str,
        payload: bytes = b"",
        timeout: float = 120.0,
        idle_timeout: float = 30.0,
        headers: dict[str, str] | None = None,
    ) -> AsyncIterator[Msg]:
        """Multi-reply request: yields every message published to the reply
        inbox until one carries the ``Nats-Stream-Done`` header (the terminal
        aggregate) or timeout elapses. Mints ``X-Trace-Id`` like request().

        A reconnect mid-stream raises :class:`ConnectionClosedError`
        immediately: replies published while the link was down are gone, so
        continuing would silently drop tokens — callers retry the whole
        logical request (with a fresh inbox) instead."""
        headers = dict(headers) if headers else {}
        headers.setdefault(p.TRACE_HEADER, new_trace_id())
        headers.setdefault(p.DEADLINE_HEADER, deadline_header_value(timeout))
        inbox = self.new_inbox()
        sub = await self.subscribe(inbox)
        sub._fail_on_gap = True
        await self.publish(subject, payload, reply=inbox, headers=headers)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        done = False
        try:
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError(f"stream request to {subject} timed out")
                msg = await sub.next_msg(timeout=min(remaining, idle_timeout))
                yield msg
                if msg.headers and "Nats-Stream-Done" in msg.headers:
                    done = True
                    return
        finally:
            if not done:
                # consumer-gone: the caller abandoned the stream before the
                # terminal message (HTTP client disconnected, deadline hit,
                # generator closed). Tell the serving worker so it frees the
                # batcher slot NOW instead of decoding to max_tokens for
                # nobody. Best-effort: the worker's own idle timeout is the
                # backstop if this publish is lost.
                try:
                    await self.publish(inbox + p.STREAM_CANCEL_SUFFIX, b"")
                except Exception:  # noqa: BLE001 — connection may be gone
                    pass
            await sub.unsubscribe()

    # -- read loop ----------------------------------------------------------

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                data = await reader.read(64 * 1024)
                if not data:
                    break
                for ev in self._parser.feed(data):
                    await self._dispatch(ev)
        except asyncio.CancelledError:
            return
        except (ConnectionError, OSError):
            pass
        # connection lost (EOF or socket error). Only the CURRENT
        # connection's read loop may react — a stale loop unwinding after a
        # successful reconnect must not tear the new link down.
        if self._closed.is_set() or self._reader is not reader:
            return
        if self.max_reconnects:
            self._begin_reconnect()
        else:
            await self.close()

    async def _dispatch(self, ev: p.Event) -> None:
        if isinstance(ev, p.MsgEvent):
            sub = self._subs.get(ev.sid or "")
            if sub is not None:
                sub._deliver(
                    Msg(
                        subject=ev.subject,
                        payload=ev.payload,
                        reply=ev.reply,
                        headers=ev.headers,
                        _client=self,
                    )
                )
                if sub._max_msgs is not None and sub._delivered >= sub._max_msgs:
                    # server-side auto-unsub just exhausted: it will send no
                    # more messages on this sid, so retire the sub locally too
                    self._subs.pop(sub.sid, None)
                    sub._close_local()
        elif isinstance(ev, p.CtrlEvent):
            if ev.op == "PING":
                await self._send(p.PONG)
            elif ev.op == "PONG":
                self._outstanding_pings = 0  # keepalive: the link is live
                while self._pong_waiters:
                    fut = self._pong_waiters.pop(0)
                    if not fut.done():
                        fut.set_result(None)
                    break
        elif isinstance(ev, p.ErrEvent):
            # fatal server errors close the connection; others are logged
            pass


async def connect(
    url: str = "nats://127.0.0.1:4222", name: str | None = None, **kwargs
) -> NatsClient:
    nc = NatsClient()
    await nc.connect(url, name=name, **kwargs)
    return nc

"""Asyncio NATS client: pub/sub, queue groups, request-reply, streaming requests.

Provides the client capabilities the reference gets from nats.go v1.47.0
(/root/reference/go.mod:8): ``Publish``/``Subscribe``/``QueueSubscribe``/
``Request`` with a muxed ``_INBOX.<nuid>.*`` reply subscription, plus
``request_stream`` — the multi-reply extension the TPU build uses for token
streaming (SURVEY.md §7 hard-part 3): many messages arrive on the reply inbox
and the terminal one carries a ``Nats-Stream-Done`` header with the aggregate,
so naive single-reply clients still see a complete response.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import AsyncIterator, Awaitable, Callable
from urllib.parse import urlparse

from ..obs import new_trace_id
from ..utils import next_nuid
from . import protocol as p


@dataclass(slots=True)
class Msg:
    subject: str
    payload: bytes
    reply: str | None = None
    headers: dict[str, str] | None = None
    _client: "NatsClient | None" = None

    def json(self):
        return json.loads(self.payload or b"null")

    async def respond(self, payload: bytes, headers: dict[str, str] | None = None) -> None:
        """Reply via this message's own connection — mirrors msg.Respond in the
        reference (/root/reference/nats_llm_studio.go:214)."""
        if not self.reply:
            raise ValueError("message has no reply subject")
        assert self._client is not None
        await self._client.publish(self.reply, payload, headers=headers)


class Subscription:
    def __init__(self, client: "NatsClient", sid: str, subject: str, queue: str | None):
        self._client = client
        self.sid = sid
        self.subject = subject
        self.queue = queue
        self._queue: asyncio.Queue[Msg | None] = asyncio.Queue()
        self._cb: Callable[[Msg], Awaitable[None]] | None = None
        self._cb_tasks: set[asyncio.Task] = set()
        self.closed = False
        self._delivered = 0  # total messages handed to this sub
        self._max_msgs: int | None = None  # auto-unsub bound, if any

    def _deliver(self, msg: Msg) -> None:
        self._delivered += 1
        if self._cb is not None:
            task = asyncio.ensure_future(self._cb(msg))
            self._cb_tasks.add(task)
            task.add_done_callback(self._cb_tasks.discard)
        else:
            self._queue.put_nowait(msg)

    def _close_local(self) -> None:
        """Mark closed and wake pending next_msg waiters (no wire traffic)."""
        if not self.closed:
            self.closed = True
            self._queue.put_nowait(None)

    async def next_msg(self, timeout: float | None = None) -> Msg:
        if self.closed and self._queue.empty():
            raise BrokenPipeError("subscription closed")
        msg = await asyncio.wait_for(self._queue.get(), timeout)
        if msg is None:
            raise BrokenPipeError("subscription closed")
        return msg

    def __aiter__(self) -> AsyncIterator[Msg]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[Msg]:
        while True:
            try:
                yield await self.next_msg()
            except BrokenPipeError:
                return

    async def unsubscribe(self) -> None:
        if not self.closed:
            self._close_local()
            await self._client._unsubscribe(self.sid)

    async def auto_unsubscribe(self, max_msgs: int) -> None:
        """UNSUB <sid> <max_msgs>: the server stops after ``max_msgs`` total
        deliveries to this sid; the client closes the sub at the same count."""
        await self._client._unsubscribe(self.sid, max_msgs)


class NatsClient:
    """A single NATS connection."""

    def __init__(self) -> None:
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._parser = p.Parser()
        self._subs: dict[str, Subscription] = {}
        self._next_sid = 0
        self._read_task: asyncio.Task | None = None
        self._pong_waiters: list[asyncio.Future] = []
        self._inbox_prefix = f"_INBOX.{next_nuid()}"
        self._resp_futures: dict[str, asyncio.Future[Msg]] = {}
        self._resp_sub_started = False
        self._closed = asyncio.Event()
        self.server_info: dict = {}
        self._write_lock = asyncio.Lock()

    # -- lifecycle ----------------------------------------------------------

    async def connect(self, url: str = "nats://127.0.0.1:4222", name: str | None = None) -> None:
        u = urlparse(url)
        host = u.hostname or "127.0.0.1"
        port = u.port or 4222
        self._reader, self._writer = await asyncio.open_connection(host, port)
        # read INFO
        line = await self._reader.readline()
        events = list(self._parser.feed(line))
        if not events or not isinstance(events[0], p.InfoEvent):
            raise ConnectionError(f"expected INFO, got {events!r}")
        self.server_info = events[0].info
        opts = {
            "verbose": False,
            "pedantic": False,
            "lang": "python-tpu",
            "version": "0.1.0",
            "protocol": 1,
            "headers": True,
        }
        if name:
            opts["name"] = name
        self._writer.write(p.encode_connect(opts) + p.PING)
        await self._writer.drain()
        self._read_task = asyncio.ensure_future(self._read_loop())
        await self.flush()

    async def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        if self._read_task:
            self._read_task.cancel()
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        for sub in self._subs.values():
            sub._close_local()
        for fut in self._resp_futures.values():
            if not fut.done():
                fut.set_exception(ConnectionError("connection closed"))

    async def drain(self) -> None:
        """Unsubscribe everything, flush, close — graceful worker shutdown
        (the runtime behavior /root/reference/README.md:475-484 leaves to the
        embedding application)."""
        for sub in list(self._subs.values()):
            await sub.unsubscribe()
        try:
            await self.flush()
        except ConnectionError:
            pass
        await self.close()

    # -- core ops -----------------------------------------------------------

    async def _send(self, data: bytes) -> None:
        if self._writer is None or self._closed.is_set():
            raise ConnectionError("not connected")
        async with self._write_lock:
            self._writer.write(data)
            await self._writer.drain()

    async def publish(
        self,
        subject: str,
        payload: bytes = b"",
        reply: str | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        # client-side guard, same as nats.go/nats.py: the server would answer
        # a violation with -ERR (and real nats-server drops the connection),
        # so fail fast with the advertised limit instead
        limit = (self.server_info or {}).get("max_payload")
        if limit and len(payload) > int(limit):
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds server max_payload {limit}"
            )
        await self._send(p.encode_pub(subject, payload, reply, headers))

    async def subscribe(
        self,
        subject: str,
        queue: str | None = None,
        cb: Callable[[Msg], Awaitable[None]] | None = None,
    ) -> Subscription:
        self._next_sid += 1
        sid = str(self._next_sid)
        sub = Subscription(self, sid, subject, queue)
        sub._cb = cb
        self._subs[sid] = sub
        await self._send(p.encode_sub(subject, sid, queue))
        return sub

    async def _unsubscribe(self, sid: str, max_msgs: int | None = None) -> None:
        if max_msgs is None:
            # immediate unsubscribe: the server stops routing now, drop ours
            self._subs.pop(sid, None)
        else:
            # auto-unsub: the SERVER stops after max_msgs total deliveries;
            # mirror the bound client-side so the sub is closed and removed
            # when the count is exhausted (see _dispatch) instead of leaking
            # in _subs forever
            sub = self._subs.get(sid)
            if sub is not None:
                if sub._delivered >= max_msgs:
                    self._subs.pop(sid, None)
                    sub._close_local()
                else:
                    sub._max_msgs = max_msgs
        try:
            await self._send(p.encode_unsub(sid, max_msgs))
        except ConnectionError:
            pass

    async def flush(self, timeout: float = 10.0) -> None:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pong_waiters.append(fut)
        await self._send(p.PING)
        await asyncio.wait_for(fut, timeout)

    # -- request-reply ------------------------------------------------------

    async def _ensure_resp_sub(self) -> None:
        if self._resp_sub_started:
            return
        self._resp_sub_started = True

        async def on_resp(msg: Msg) -> None:
            token = msg.subject.rsplit(".", 1)[-1]
            fut = self._resp_futures.pop(token, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)

        await self.subscribe(self._inbox_prefix + ".*", cb=on_resp)

    def new_inbox(self) -> str:
        return f"_INBOX.{next_nuid()}"

    async def request(
        self,
        subject: str,
        payload: bytes = b"",
        timeout: float = 2.0,
        headers: dict[str, str] | None = None,
    ) -> Msg:
        """Single request, single reply — the pattern every reference subject
        uses (/root/reference/README.md:86-88, :131-134, :181-186, :237-245).

        A trace id is minted into the ``X-Trace-Id`` header when the caller
        did not set one, so every request is traceable end-to-end (the worker
        echoes it in the envelope and stamps per-stage spans under it)."""
        await self._ensure_resp_sub()
        headers = dict(headers) if headers else {}
        headers.setdefault(p.TRACE_HEADER, new_trace_id())
        token = next_nuid()
        inbox = f"{self._inbox_prefix}.{token}"
        fut: asyncio.Future[Msg] = asyncio.get_running_loop().create_future()
        self._resp_futures[token] = fut
        await self.publish(subject, payload, reply=inbox, headers=headers)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._resp_futures.pop(token, None)
            raise
        except BaseException:
            self._resp_futures.pop(token, None)
            raise

    async def request_stream(
        self,
        subject: str,
        payload: bytes = b"",
        timeout: float = 120.0,
        idle_timeout: float = 30.0,
        headers: dict[str, str] | None = None,
    ) -> AsyncIterator[Msg]:
        """Multi-reply request: yields every message published to the reply
        inbox until one carries the ``Nats-Stream-Done`` header (the terminal
        aggregate) or timeout elapses. Mints ``X-Trace-Id`` like request()."""
        headers = dict(headers) if headers else {}
        headers.setdefault(p.TRACE_HEADER, new_trace_id())
        inbox = self.new_inbox()
        sub = await self.subscribe(inbox)
        await self.publish(subject, payload, reply=inbox, headers=headers)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        try:
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError(f"stream request to {subject} timed out")
                msg = await sub.next_msg(timeout=min(remaining, idle_timeout))
                yield msg
                if msg.headers and "Nats-Stream-Done" in msg.headers:
                    return
        finally:
            await sub.unsubscribe()

    # -- read loop ----------------------------------------------------------

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                data = await self._reader.read(64 * 1024)
                if not data:
                    break
                for ev in self._parser.feed(data):
                    await self._dispatch(ev)
        except (asyncio.CancelledError, ConnectionError):
            pass
        finally:
            if not self._closed.is_set():
                await self.close()

    async def _dispatch(self, ev: p.Event) -> None:
        if isinstance(ev, p.MsgEvent):
            sub = self._subs.get(ev.sid or "")
            if sub is not None:
                sub._deliver(
                    Msg(
                        subject=ev.subject,
                        payload=ev.payload,
                        reply=ev.reply,
                        headers=ev.headers,
                        _client=self,
                    )
                )
                if sub._max_msgs is not None and sub._delivered >= sub._max_msgs:
                    # server-side auto-unsub just exhausted: it will send no
                    # more messages on this sid, so retire the sub locally too
                    self._subs.pop(sub.sid, None)
                    sub._close_local()
        elif isinstance(ev, p.CtrlEvent):
            if ev.op == "PING":
                await self._send(p.PONG)
            elif ev.op == "PONG":
                while self._pong_waiters:
                    fut = self._pong_waiters.pop(0)
                    if not fut.done():
                        fut.set_result(None)
                    break
        elif isinstance(ev, p.ErrEvent):
            # fatal server errors close the connection; others are logged
            pass


async def connect(url: str = "nats://127.0.0.1:4222", name: str | None = None) -> NatsClient:
    nc = NatsClient()
    await nc.connect(url, name=name)
    return nc

"""Embedded NATS broker: core pub/sub, wildcards, queue groups, headers.

The reference requires an external ``nats-server`` binary (installed and
launched by /root/reference/scripts/setup_unix.sh:72-102). This build ships a
wire-compatible broker in-tree so the whole stack — tests, benchmarks, and
single-host deployments — runs hermetically with zero external processes.
Queue-group delivery (one random member per group per message) reproduces the
competing-consumers scale-out contract (/root/reference/README.md:478-484).

The broker also hosts server-side modules (e.g. the object store,
``store/objectstore.py``) which register internal handlers on API subjects —
the in-tree analog of nats-server's JetStream subsystem.
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass
from typing import Awaitable, Callable

from ..utils import subject_matches, valid_subject
from . import faults as _faults
from . import protocol as p

log = logging.getLogger(__name__)

MAX_PAYLOAD = 1024 * 1024  # real nats-server's default; chunks are 128 KiB
MAX_PENDING = 64 * 1024 * 1024  # per-client outbound buffer bound (nats-server
# default max_pending): a stalled subscriber must not buffer without limit —
# it is dropped with -ERR 'Slow Consumer' like the real server


@dataclass(slots=True)
class _Sub:
    client: "_ClientConn"
    sid: str
    subject: str
    queue: str | None
    delivered: int = 0  # total messages sent to this sid since SUB
    max_msgs: int | None = None  # auto-unsub bound: TOTAL deliveries since
    # SUB (real nats-server semantics — NOT a countdown from the UNSUB)


class _ClientConn:
    def __init__(self, broker: "EmbeddedBroker", reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.broker = broker
        self.reader = reader
        self.writer = writer
        self.parser = p.Parser()
        self.subs: dict[str, _Sub] = {}
        self.cid = broker._next_cid()
        self.name = ""  # CONNECT name; chaos rules scope severs by it
        self.closed = False
        self._out = asyncio.Queue[bytes | None]()
        self._pending = 0  # bytes enqueued but not yet written to the socket
        self._dropping = False  # slow-consumer drop already scheduled
        self._writer_task: asyncio.Task | None = None

    def send(self, data: bytes) -> None:
        if self.closed or self._dropping:
            return
        if self._pending + len(data) > self.broker.max_pending:
            self._dropping = True
            # slow consumer: the write loop is not draining (stalled reader).
            # Bound broker memory by dropping the client, as nats-server does.
            log.warning(
                "client %d exceeded %d pending bytes; dropping (slow consumer)",
                self.cid, self.broker.max_pending,
            )
            self._out.put_nowait(p.encode_err("Slow Consumer"))  # best-effort
            asyncio.ensure_future(self._close())
            return
        self._pending += len(data)
        self._out.put_nowait(data)

    async def _write_loop(self) -> None:
        try:
            done = False
            while not done:
                data = await self._out.get()
                if data is None:
                    break
                # coalesce pending writes; a None pulled mid-coalesce is the
                # shutdown sentinel — flush what we have, then exit (it must
                # not be swallowed, or _close() stalls its full 1 s wait)
                chunks = [data]
                while not self._out.empty():
                    nxt = self._out.get_nowait()
                    if nxt is None:
                        done = True
                        break
                    chunks.append(nxt)
                buf = b"".join(chunks)
                self.writer.write(buf)
                await self.writer.drain()
                self._pending = max(0, self._pending - len(buf))
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass

    async def run(self) -> None:
        self._writer_task = asyncio.ensure_future(self._write_loop())
        info = {
            "server_id": self.broker.server_id,
            "server_name": "nats-llm-studio-tpu-embedded",
            "version": "2.10.12-compat",
            "proto": 1,
            "headers": True,
            "max_payload": self.broker.max_payload,
            "client_id": self.cid,
        }
        self.send(p.encode_info(info))
        try:
            while True:
                data = await self.reader.read(64 * 1024)
                if not data:
                    break
                for ev in self.parser.feed(data):
                    await self._handle(ev)
        except (ConnectionError, OSError, p.ProtocolError, ValueError) as e:
            # ValueError covers malformed CONNECT JSON (json.JSONDecodeError)
            # and non-numeric size fields — a hostile or broken peer must get
            # -ERR + drop, never an unhandled task exception (SURVEY.md §5
            # failure detection; found by the protocol fuzz test)
            if isinstance(e, (p.ProtocolError, ValueError)) and not isinstance(
                e, (ConnectionError, OSError)
            ):
                self.send(p.encode_err(f"protocol violation: {e}"))
        finally:
            await self._close()

    async def _close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for sub in list(self.subs.values()):
            self.broker._remove_sub(sub)
        self.subs.clear()
        self.broker._clients.discard(self)
        self._out.put_nowait(None)
        if self._writer_task:
            try:
                await asyncio.wait_for(self._writer_task, 1.0)
            except asyncio.TimeoutError:
                self._writer_task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, OSError):
            pass

    async def _handle(self, ev: p.Event) -> None:
        if isinstance(ev, p.MsgEvent):  # PUB / HPUB
            if len(ev.payload) > self.broker.max_payload:
                self.send(p.encode_err("Maximum Payload Violation"))
                return
            if _faults.ACTIVE is not None:  # chaos harness; off ⇒ one attr read
                f = _faults.ACTIVE.check(_faults.BROKER_PUBLISH, ev.subject,
                                         client=self.name)
                if f is not None:
                    if f.kind == "sever":
                        # drop the publisher's TCP connection; the message is
                        # lost, exactly like a broker crash mid-publish (or,
                        # with a client= scoped rule, that worker dying)
                        log.warning("chaos: severing client %d (%s) on publish to %s",
                                    self.cid, self.name or "unnamed", ev.subject)
                        await self._close()
                        return
                    if f.kind == "drop":
                        return  # silently lose this one message
                    if f.kind == "delay":
                        await asyncio.sleep(f.delay_s)
            await self.broker.route(ev.subject, ev.payload, ev.reply, ev.headers)
        elif isinstance(ev, p.SubEvent):
            if not valid_subject(ev.subject, allow_wildcards=True):
                self.send(p.encode_err(f"Invalid Subject: {ev.subject}"))
                return
            sub = _Sub(self, ev.sid, ev.subject, ev.queue)
            self.subs[ev.sid] = sub
            self.broker._add_sub(sub)
        elif isinstance(ev, p.UnsubEvent):
            sub = self.subs.get(ev.sid)
            if sub is None:
                return
            if ev.max_msgs is None or sub.delivered >= ev.max_msgs:
                # immediate unsub, or the bound is already met (UNSUB max is
                # total deliveries since SUB — a sub that already received
                # that many must be retired NOW, or a queue group could
                # route a message to a sid the client has dropped)
                del self.subs[ev.sid]
                self.broker._remove_sub(sub)
            else:
                sub.max_msgs = ev.max_msgs
        elif isinstance(ev, p.CtrlEvent):
            if ev.op == "PING":
                self.send(p.PONG)
        elif isinstance(ev, p.ConnectEvent):
            # no auth in embedded mode; keep the advertised name so
            # client-scoped chaos rules can target one worker's connection
            name = ev.options.get("name")
            if isinstance(name, str):
                self.name = name


InternalHandler = Callable[[str, bytes, str | None, dict[str, str] | None], Awaitable[None]]


class EmbeddedBroker:
    """In-process NATS-compatible broker. ``await start()`` binds the port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, max_payload: int = MAX_PAYLOAD,
                 max_pending: int = MAX_PENDING):
        self.host = host
        self.port = port
        self.max_payload = max_payload
        self.max_pending = max_pending
        self.server_id = f"EMB{random.getrandbits(48):012X}"
        self._server: asyncio.base_events.Server | None = None
        self._clients: set[_ClientConn] = set()
        self._subs: list[_Sub] = []
        self._cid = 0
        # internal modules: (pattern, handler) — called in-process, no socket
        self._internal: list[tuple[str, InternalHandler]] = []
        # modules with lifecycle (closed deterministically on stop())
        self._modules: list = []

    @property
    def url(self) -> str:
        return f"nats://{self.host}:{self.port}"

    def _next_cid(self) -> int:
        self._cid += 1
        return self._cid

    async def start(self) -> "EmbeddedBroker":
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for c in list(self._clients):
            await c._close()
        # close registered modules (e.g. the object store's append-log file
        # handles) deterministically instead of leaving them to GC
        for m in self._modules:
            close = getattr(m, "close", None)
            if close is not None:
                close()
        self._modules.clear()

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn = _ClientConn(self, reader, writer)
        self._clients.add(conn)
        await conn.run()

    # -- interest management -------------------------------------------------

    def _add_sub(self, sub: _Sub) -> None:
        self._subs.append(sub)

    def _remove_sub(self, sub: _Sub) -> None:
        try:
            self._subs.remove(sub)
        except ValueError:
            pass

    def register_internal(self, pattern: str, handler: InternalHandler) -> None:
        """Register a server-side module handler (object store, health...)."""
        self._internal.append((pattern, handler))

    def register_module(self, module) -> None:
        """Track a module for lifecycle: its ``close()`` runs on ``stop()``."""
        self._modules.append(module)

    # -- routing -------------------------------------------------------------

    async def route(
        self,
        subject: str,
        payload: bytes,
        reply: str | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        """Deliver a message: plain subs each get a copy; queue groups get one
        randomly-chosen member (README.md:478-484 semantics)."""
        plain: list[_Sub] = []
        groups: dict[tuple[str, str], list[_Sub]] = {}
        for sub in self._subs:
            if sub.client.closed or not subject_matches(sub.subject, subject):
                continue
            if sub.queue:
                groups.setdefault((sub.subject, sub.queue), []).append(sub)
            else:
                plain.append(sub)
        targets = plain + [random.choice(members) for members in groups.values()]
        for sub in targets:
            sub.client.send(p.encode_msg(subject, sub.sid, payload, reply, headers))
            sub.delivered += 1
            if sub.max_msgs is not None and sub.delivered >= sub.max_msgs:
                sub.client.subs.pop(sub.sid, None)
                self._remove_sub(sub)
        for pattern, handler in self._internal:
            if subject_matches(pattern, subject):
                try:
                    await handler(subject, payload, reply, headers)
                except Exception:  # module errors must not kill the router
                    log.exception("internal handler error on %s", subject)

    async def publish_internal(
        self,
        subject: str,
        payload: bytes,
        reply: str | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        """Publish from a server-side module."""
        await self.route(subject, payload, reply, headers)

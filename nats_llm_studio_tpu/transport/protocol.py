"""NATS wire protocol: incremental parser + serializers.

The reference delegates the wire protocol to nats.go v1.47.0
(/root/reference/go.mod:8) and an external nats-server binary
(/root/reference/scripts/setup_unix.sh:72-102). This build ships the protocol
in-tree: one incremental parser used by both the client (parsing
INFO/MSG/HMSG/PING/PONG/+OK/-ERR) and the embedded broker (parsing
CONNECT/PUB/HPUB/SUB/UNSUB/PING/PONG), wire-compatible with the real NATS
text protocol so external NATS tooling can interoperate.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Iterator

CRLF = b"\r\n"

# --- events -----------------------------------------------------------------


@dataclass(slots=True)
class MsgEvent:
    """Server->client MSG/HMSG, or client->server PUB/HPUB (same shape)."""

    op: str  # "MSG" | "HMSG" | "PUB" | "HPUB"
    subject: str
    sid: str | None  # subscription id (MSG/HMSG only)
    reply: str | None
    payload: bytes
    headers: dict[str, str] | None = None


@dataclass(slots=True)
class SubEvent:
    subject: str
    queue: str | None
    sid: str


@dataclass(slots=True)
class UnsubEvent:
    sid: str
    max_msgs: int | None


@dataclass(slots=True)
class CtrlEvent:
    op: str  # "PING" | "PONG" | "OK"


@dataclass(slots=True)
class ErrEvent:
    message: str


@dataclass(slots=True)
class InfoEvent:
    info: dict


@dataclass(slots=True)
class ConnectEvent:
    options: dict


Event = MsgEvent | SubEvent | UnsubEvent | CtrlEvent | ErrEvent | InfoEvent | ConnectEvent


# --- parser -----------------------------------------------------------------


@dataclass
class Parser:
    """Incremental NATS protocol parser. Feed bytes, iterate events."""

    _buf: bytearray = field(default_factory=bytearray)
    # pending payload state: (event-to-complete, total_payload_len, header_len)
    _pending: tuple[MsgEvent, int, int] | None = None

    def feed(self, data: bytes) -> Iterator[Event]:
        self._buf.extend(data)
        while True:
            if self._pending is not None:
                ev, need, hdr_len = self._pending
                if len(self._buf) < need + 2:  # payload + CRLF
                    return
                raw = bytes(self._buf[:need])
                if self._buf[need : need + 2] != CRLF:
                    raise ProtocolError("payload not terminated by CRLF")
                del self._buf[: need + 2]
                self._pending = None
                if hdr_len:
                    ev.headers = parse_headers(raw[:hdr_len])
                    ev.payload = raw[hdr_len:]
                else:
                    ev.payload = raw
                yield ev
                continue

            idx = self._buf.find(CRLF)
            if idx < 0:
                if len(self._buf) > 1 << 20:
                    raise ProtocolError("control line too long")
                return
            line = bytes(self._buf[:idx])
            del self._buf[: idx + 2]
            ev = self._parse_line(line)
            if ev is not None:
                yield ev

    def _parse_line(self, line: bytes) -> Event | None:
        if not line:
            return None
        try:
            text = line.decode()
        except UnicodeDecodeError as e:
            raise ProtocolError(f"bad control line: {line!r}") from e
        op, _, rest = text.partition(" ")
        opu = op.upper()
        if opu in ("MSG", "PUB"):
            self._msg_event(opu, rest.split(), with_headers=False)
            return None
        if opu in ("HMSG", "HPUB"):
            self._msg_event(opu, rest.split(), with_headers=True)
            return None
        if opu == "PING":
            return CtrlEvent("PING")
        if opu == "PONG":
            return CtrlEvent("PONG")
        if opu == "+OK":
            return CtrlEvent("OK")
        if opu == "-ERR":
            return ErrEvent(rest.strip().strip("'"))
        if opu == "INFO":
            return InfoEvent(json.loads(rest))
        if opu == "CONNECT":
            return ConnectEvent(json.loads(rest))
        if opu == "SUB":
            args = rest.split()
            if len(args) == 2:
                return SubEvent(args[0], None, args[1])
            if len(args) == 3:
                return SubEvent(args[0], args[1], args[2])
            raise ProtocolError(f"bad SUB line: {text!r}")
        if opu == "UNSUB":
            args = rest.split()
            if len(args) == 1:
                return UnsubEvent(args[0], None)
            if len(args) == 2:
                return UnsubEvent(args[0], int(args[1]))
            raise ProtocolError(f"bad UNSUB line: {text!r}")
        raise ProtocolError(f"unknown protocol op: {op!r}")

    def _msg_event(self, op: str, args: list[str], with_headers: bool) -> MsgEvent:
        # MSG  <subject> <sid> [reply] <#bytes>
        # PUB  <subject> [reply] <#bytes>
        # HMSG <subject> <sid> [reply] <#hdr> <#total>
        # HPUB <subject> [reply] <#hdr> <#total>
        server_side = op in ("MSG", "HMSG")
        n_fixed = (2 if server_side else 1) + (2 if with_headers else 1)
        if len(args) == n_fixed:
            reply = None
        elif len(args) == n_fixed + 1:
            reply = args[2 if server_side else 1]
        else:
            raise ProtocolError(f"bad {op} line: {args!r}")
        subject = args[0]
        sid = args[1] if server_side else None
        if with_headers:
            hdr_len = int(args[-2])
            total = int(args[-1])
        else:
            hdr_len = 0
            total = int(args[-1])
        if total < hdr_len or total < 0:
            raise ProtocolError(f"bad sizes in {op}: hdr={hdr_len} total={total}")
        ev = MsgEvent(op=op, subject=subject, sid=sid, reply=reply, payload=b"")
        # stash expected sizes for feed() loop
        self._pending = (ev, total, hdr_len)
        return ev


class ProtocolError(Exception):
    pass


# --- headers ----------------------------------------------------------------

HDR_PREAMBLE = b"NATS/1.0\r\n"

# request-scoped trace id (obs/trace.py): minted by the client when absent,
# read by the worker, echoed in the response envelope — one id names the
# request across every hop without touching the JSON payload
TRACE_HEADER = "X-Trace-Id"

# retry attempt number (1-based): RetryPolicy keeps ONE trace id across
# every attempt of a request and stamps this per attempt, so the worker's
# trace report (and a flight dump's slow-request trace) can tell the
# attempts of one logical request apart
ATTEMPT_HEADER = "X-Attempt"

# absolute client deadline in wall-clock milliseconds since the epoch:
# stamped by request()/request_stream() from the caller's timeout, read by
# the worker (capped by the per-op ladder) so the serving path can shed or
# abort work whose caller has already given up
DEADLINE_HEADER = "X-Deadline-Ms"

# the serving worker's stable cluster id, echoed on every reply (and in
# cluster adverts): the client retry loop reads it to learn WHO shed the
# request, so the next hop can steer around that worker
WORKER_HEADER = "X-Worker-Id"

# comma-separated worker ids that already failed/shed this logical request:
# stamped by the retrying client before each re-issue, read by workers
# (which bounce retryably when they see their own id — a queue-group
# redelivery must not land a retry back on the worker that just shed it)
# and by the router (which never steers at an excluded worker)
EXCLUDED_WORKERS_HEADER = "X-Excluded-Workers"


# disaggregated prefill/decode (serve/worker.py + serve/router.py): the
# router stamps the chosen prefill-role worker's id on the chat request it
# steers at a decode-role worker. The decode worker pulls the prompt's
# exported KV blocks from ``{prefix}.worker.<id>.kv_export`` before serving;
# any transfer failure falls back to local prefill, so a stale or bogus
# value degrades cleanly instead of failing the request.
KV_PREFILL_HEADER = "X-KV-Prefill-Worker"


# multi-tenant QoS (serve/qos.py): the gateway resolves an API key to a
# tenant id + priority class and stamps both here; router → worker →
# batcher read them so admission (deficit round-robin), brownout shedding
# (batch < standard < premium), and preemption all know WHO is asking.
# Absent headers (raw-NATS callers, every pre-QoS client) default to the
# anonymous tenant at standard priority — tenancy is purely additive on
# the wire. The priority value is clamped to the known classes at the
# worker (qos.normalize_priority): a self-stamped bogus class degrades to
# standard, it never grants premium.
TENANT_HEADER = "X-Tenant"
PRIORITY_HEADER = "X-Priority"


# W3C traceparent-style span context (obs/trace.py): ``00-<trace>-<span>-01``
# where <span> is the *sender's* span id — the receiving hop records it as
# parent_span_id on the span it emits to ``{prefix}.obs.spans``, so the
# fleet aggregator can assemble a causally-correct tree across retries,
# excluded-worker hops, and the kv_export two-hop. Parsed leniently
# (obs.trace.parse_span_context): a malformed value is ignored, never fatal.
TRACEPARENT_HEADER = "Traceparent"


# consumer-gone signal for streaming replies: when a streaming consumer
# abandons its inbox before the terminal Nats-Stream-Done message, the
# client publishes an empty message to ``<inbox> + STREAM_CANCEL_SUFFIX``.
# The serving worker subscribes to that subject for the stream's lifetime
# and aborts generation (closing the engine stream frees the batcher slot)
# instead of decoding to max_tokens for nobody.
STREAM_CANCEL_SUFFIX = ".cancel"


def parse_worker_list(value: str | None) -> list[str]:
    """Decode an ``X-Excluded-Workers`` header into worker ids (order kept,
    empties dropped); tolerant of None/garbage — a bad header must never
    fail a request that would otherwise serve."""
    if not value:
        return []
    return [w for w in (tok.strip() for tok in value.split(",")) if w]


def format_worker_list(ids: list[str]) -> str:
    return ",".join(ids)


def parse_headers(raw: bytes) -> dict[str, str]:
    headers: dict[str, str] = {}
    lines = raw.split(CRLF)
    # first line is the version preamble, possibly with an inline status
    # ("NATS/1.0 503"); keep status under a reserved key.
    if lines and lines[0].startswith(b"NATS/1.0"):
        status = lines[0][len(b"NATS/1.0") :].strip()
        if status:
            headers["Status"] = status.decode()
        lines = lines[1:]
    for line in lines:
        if not line:
            continue
        k, _, v = line.partition(b":")
        headers[k.decode().strip()] = v.decode().strip()
    return headers


def encode_headers(headers: dict[str, str]) -> bytes:
    out = bytearray(HDR_PREAMBLE)
    for k, v in headers.items():
        out += f"{k}: {v}".encode() + CRLF
    out += CRLF
    return bytes(out)


# --- serializers ------------------------------------------------------------


def encode_pub(
    subject: str, payload: bytes, reply: str | None = None, headers: dict[str, str] | None = None
) -> bytes:
    r = f" {reply}" if reply else ""
    if headers:
        h = encode_headers(headers)
        head = f"HPUB {subject}{r} {len(h)} {len(h) + len(payload)}".encode()
        return head + CRLF + h + payload + CRLF
    head = f"PUB {subject}{r} {len(payload)}".encode()
    return head + CRLF + payload + CRLF


def encode_msg(
    subject: str,
    sid: str,
    payload: bytes,
    reply: str | None = None,
    headers: dict[str, str] | None = None,
) -> bytes:
    r = f" {reply}" if reply else ""
    if headers:
        h = encode_headers(headers)
        head = f"HMSG {subject} {sid}{r} {len(h)} {len(h) + len(payload)}".encode()
        return head + CRLF + h + payload + CRLF
    head = f"MSG {subject} {sid}{r} {len(payload)}".encode()
    return head + CRLF + payload + CRLF


def encode_sub(subject: str, sid: str, queue: str | None = None) -> bytes:
    q = f" {queue}" if queue else ""
    return f"SUB {subject}{q} {sid}".encode() + CRLF


def encode_unsub(sid: str, max_msgs: int | None = None) -> bytes:
    m = f" {max_msgs}" if max_msgs is not None else ""
    return f"UNSUB {sid}{m}".encode() + CRLF


def encode_connect(options: dict) -> bytes:
    return b"CONNECT " + json.dumps(options, separators=(",", ":")).encode() + CRLF


def encode_info(info: dict) -> bytes:
    return b"INFO " + json.dumps(info, separators=(",", ":")).encode() + CRLF


PING = b"PING" + CRLF
PONG = b"PONG" + CRLF
OK = b"+OK" + CRLF


def encode_err(message: str) -> bytes:
    return f"-ERR '{message}'".encode() + CRLF

"""Block-transfer wire format for disaggregated prefill/decode serving.

A prefill-role worker gathers a request's finished KV blocks to host memory
(per prefill chunk: one [1, L, Hkv, C, D] K row and V row — bf16/f32 dense,
or int8 KVQ codes plus [1, L, Hkv, C] f32 scales — with the chunk-end logits
where the prefill harvested them) and ships the set to a decode-role peer.
This module owns ONLY the byte layout of that shipment; the transport
(chunked NATS publishes or the JetStream Object Store) treats the blob as
opaque bytes under a SHA-256 digest.

Layout (all integers little-endian):

    magic   b"KVX1"
    u32     header length
    header  canonical JSON (sorted keys) describing layout/dtypes/shapes,
            the covered token ids, and which chunks carry logits
    body    per chunk, in order: K codes, [K scales], V codes, [V scales],
            [logits f32] — raw C-order array bytes, sizes derivable from
            the header alone

The format is pinned by golden fixtures in tests/test_wire_goldens.py: a
silent serialization change corrupts shipped KV on mixed-version clusters,
so any byte-level change must bump the magic and regenerate the goldens.
"""

from __future__ import annotations

import json
import struct

import numpy as np

MAGIC = b"KVX1"

_MAX_HEADER_BYTES = 16 << 20  # corrupt-length guard, far above any real header


class KVTransferFormatError(ValueError):
    """The blob is not a well-formed KV transfer payload."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 lives in ml_dtypes (a jax dependency) until the import
        # registers it with numpy
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _leaf_pair(arr):
    """Normalize a chunk leaf: dense ndarray -> (codes, None); a KVQ-style
    (codes, scales) pair passes through."""
    if isinstance(arr, tuple):
        q, s = arr
        return np.ascontiguousarray(q), np.ascontiguousarray(s)
    return np.ascontiguousarray(arr), None


def encode_kv_blob(export: dict) -> bytes:
    """Serialize one prefill export.

    ``export`` is the dict ``ContinuousBatcher.export_prefix_blocks``
    returns: ``token_ids`` (covered prompt ids), ``chunk_tokens`` (C), and
    ``chunks`` — per prefill chunk a dict with ``k``/``v`` leaves (ndarray,
    or ``(codes, scales)`` for KVQ) and optional ``logits`` (f32 [vocab]).
    """
    chunks = export["chunks"]
    if not chunks:
        raise KVTransferFormatError("empty export: nothing to ship")
    k0, s0 = _leaf_pair(chunks[0]["k"])
    layout = "kvq" if s0 is not None else "dense"
    header = {
        "version": 1,
        "layout": layout,
        "dtype": k0.dtype.name,
        "chunk_tokens": int(export["chunk_tokens"]),
        "n_chunks": len(chunks),
        "token_ids": [int(t) for t in export["token_ids"]],
        "k_shape": list(k0.shape),
        "logits": [],
        "vocab": 0,
    }
    if layout == "kvq":
        header["scale_dtype"] = s0.dtype.name
        header["s_shape"] = list(s0.shape)
    body = bytearray()
    for ch in chunks:
        logits = ch.get("logits")
        header["logits"].append(logits is not None)
        for leaf in (ch["k"], ch["v"]):
            q, s = _leaf_pair(leaf)
            if (s is not None) != (layout == "kvq"):
                raise KVTransferFormatError("mixed dense/kvq leaves in one export")
            if list(q.shape) != header["k_shape"]:
                raise KVTransferFormatError(
                    f"ragged chunk shape {q.shape} vs {header['k_shape']}"
                )
            body += q.tobytes()
            if s is not None:
                body += s.tobytes()
        if logits is not None:
            lg = np.ascontiguousarray(logits, dtype=np.float32).reshape(-1)
            header["vocab"] = int(lg.shape[0])
            body += lg.tobytes()
    hdr = json.dumps(header, sort_keys=True, separators=(",", ":")).encode()
    return MAGIC + struct.pack("<I", len(hdr)) + hdr + bytes(body)


def decode_kv_blob(blob: bytes) -> dict:
    """Parse a blob back into the ``export_prefix_blocks`` dict shape
    (numpy leaves; KVQ chunks come back as ``(codes, scales)`` pairs).
    Raises :class:`KVTransferFormatError` on any malformed input — the
    decode worker treats that as a transfer failure and falls back to
    local prefill rather than importing garbage KV."""
    if len(blob) < len(MAGIC) + 4 or blob[: len(MAGIC)] != MAGIC:
        raise KVTransferFormatError("bad magic: not a KV transfer blob")
    (hlen,) = struct.unpack_from("<I", blob, len(MAGIC))
    off = len(MAGIC) + 4
    if hlen > _MAX_HEADER_BYTES or off + hlen > len(blob):
        raise KVTransferFormatError("header length out of range")
    try:
        header = json.loads(blob[off : off + hlen])
    except ValueError as e:
        raise KVTransferFormatError(f"unparseable header: {e}") from e
    off += hlen
    if header.get("version") != 1:
        raise KVTransferFormatError(f"unknown version {header.get('version')!r}")
    layout = header["layout"]
    if layout not in ("dense", "kvq"):
        raise KVTransferFormatError(f"unknown layout {layout!r}")
    k_shape = tuple(header["k_shape"])
    dtype = _np_dtype(header["dtype"])
    leaf_bytes = int(np.prod(k_shape)) * dtype.itemsize
    if layout == "kvq":
        s_shape = tuple(header["s_shape"])
        s_dtype = _np_dtype(header["scale_dtype"])
        scale_bytes = int(np.prod(s_shape)) * s_dtype.itemsize
    vocab = int(header.get("vocab", 0))

    def take(n: int) -> bytes:
        nonlocal off
        if off + n > len(blob):
            raise KVTransferFormatError("truncated body")
        out = blob[off : off + n]
        off += n
        return out

    chunks = []
    for has_logits in header["logits"]:
        ch: dict = {}
        for name in ("k", "v"):
            q = np.frombuffer(take(leaf_bytes), dtype=dtype).reshape(k_shape)
            if layout == "kvq":
                s = np.frombuffer(take(scale_bytes), dtype=s_dtype).reshape(s_shape)
                ch[name] = (q, s)
            else:
                ch[name] = q
        if has_logits:
            if vocab <= 0:
                raise KVTransferFormatError("logits flagged but vocab missing")
            ch["logits"] = np.frombuffer(
                take(vocab * 4), dtype=np.float32
            ).reshape(vocab)
        else:
            ch["logits"] = None
        chunks.append(ch)
    if len(chunks) != int(header["n_chunks"]):
        raise KVTransferFormatError("chunk count mismatch")
    if off != len(blob):
        raise KVTransferFormatError(f"{len(blob) - off} trailing bytes")
    return {
        "token_ids": list(header["token_ids"]),
        "chunk_tokens": int(header["chunk_tokens"]),
        "chunks": chunks,
    }

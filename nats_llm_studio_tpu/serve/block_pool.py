"""Host-side refcounted allocator for the paged KV block pool.

The device side of paged KV is a single pair of arrays shaped
``[n_blocks, n_layers, n_kv_heads, block_tokens, head_dim]`` (plus int8
scale leaves under KVQ).  This module owns the *host* bookkeeping for
those blocks: a refcount per block id, a free list, and the shared/CoW
counters the metrics endpoint exports.

Design points (vLLM PagedAttention + RadixAttention sharing):

- Block id 0 is the **null block**: permanently referenced, never
  allocated, used to pad device block tables.  Padded gathers read junk
  from it and padded scatters write junk into it; both are masked out by
  the causal attention mask, so its contents never reach a logit.
- A live slot holds one reference per block in its table; the prefix
  cache holds its own reference per cached block.  Sharing a prefix is a
  refcount bump, never a copy.  ``refs > 1`` means the block is shared
  and must be copy-on-write'd before an in-place write.
- ``epoch`` guards against stale frees: when the batcher rebuilds the
  device pool after a poisoned dispatch it calls :meth:`reset`, which
  bumps the epoch; deferred frees from the old pool (e.g. pinned
  prefix-cache nodes released later) carry the old epoch and are
  ignored instead of corrupting the fresh refcounts.

Thread safety: the batcher owner thread does alloc/free/CoW, while the
registry event loop and the metrics scrape thread read stats and may
trigger prefix-cache eviction — hence the lock.
"""

from __future__ import annotations

import threading

__all__ = ["BlockPool"]


class BlockPool:
    """Refcounts for a fixed population of KV blocks; id 0 is the null block."""

    def __init__(self, n_blocks: int, block_tokens: int):
        if n_blocks < 2:
            raise ValueError(f"pool needs >= 2 blocks (null + 1), got {n_blocks}")
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self._lock = threading.Lock()
        self.epoch = 0
        self.cow_copies = 0
        # high-water mark of live (non-free) blocks since the last reset:
        # the pressure signal the tiering bench reads to prove a working
        # set really exceeded the pool, not just the prefix budget
        self.peak_live = 0
        self._refs = [0] * self.n_blocks
        self._refs[0] = 1  # the null block is never allocatable
        self._free = list(range(self.n_blocks - 1, 0, -1))  # pop() -> low ids first

    # -- allocation ----------------------------------------------------------

    def alloc(self, k: int) -> list[int] | None:
        """Take ``k`` fresh blocks (refcount 1 each), or None if short."""
        with self._lock:
            if k > len(self._free):
                return None
            ids = [self._free.pop() for _ in range(k)]
            for i in ids:
                self._refs[i] = 1
            live = self.n_blocks - 1 - len(self._free)
            if live > self.peak_live:
                self.peak_live = live
            return ids

    def incref(self, ids) -> None:
        with self._lock:
            for i in ids:
                if self._refs[i] <= 0:
                    raise RuntimeError(f"incref of free block {i}")
                self._refs[i] += 1

    def decref(self, ids, epoch: int | None = None) -> None:
        """Drop one reference per id; freed blocks rejoin the free list.

        ``epoch`` (when given) must match the pool's current epoch or the
        call is a no-op — that is how deferred frees from a pre-reset pool
        are discarded safely.
        """
        with self._lock:
            if epoch is not None and epoch != self.epoch:
                return
            for i in ids:
                if i == 0:
                    continue  # the null block never dies
                r = self._refs[i] - 1
                if r < 0:
                    raise RuntimeError(f"double free of block {i}")
                self._refs[i] = r
                if r == 0:
                    self._free.append(i)

    def refcount(self, i: int) -> int:
        with self._lock:
            return self._refs[i]

    def reset(self) -> None:
        """Forget everything (the device pool was rebuilt); bump the epoch."""
        with self._lock:
            self.epoch += 1
            self.cow_copies = 0
            self.peak_live = 0
            self._refs = [0] * self.n_blocks
            self._refs[0] = 1
            self._free = list(range(self.n_blocks - 1, 0, -1))

    # -- introspection -------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def stats(self) -> dict:
        with self._lock:
            shared = sum(1 for r in self._refs[1:] if r > 1)
            live = sum(1 for r in self._refs[1:] if r > 0)
            return {
                "blocks_total": self.n_blocks - 1,  # null block excluded
                "blocks_free": len(self._free),
                "blocks_live": live,
                "blocks_shared": shared,
                "blocks_peak_live": self.peak_live,
                "block_tokens": self.block_tokens,
                "cow_copies": self.cow_copies,
                "epoch": self.epoch,
            }

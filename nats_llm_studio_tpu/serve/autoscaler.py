"""Elastic autoscaling: advert-driven worker lifecycle (ROADMAP item 3).

The control loop the fleet observability plane (PR 13) was built to feed:
one :class:`Autoscaler` per cluster watches the same two broadcast streams
every other control component already uses —

* ``{prefix}.cluster.adverts`` — per-worker queue depth, brownout level,
  HBM headroom, draining flag (membership + load),
* ``{prefix}.events`` — the aggregator's ``slo_burn`` alerts (the demand
  signal against the TTFT p95 target),

and changes the fleet's shape instead of letting it shed: sustained
pressure spawns a local worker subprocess, sustained calm drains the
least-loaded member. Every decision is deliberately conservative —

* **hysteresis**: pressure must persist ``up_dwell_s`` before a spawn and
  calm must persist ``down_dwell_s`` before a drain, with a global
  ``cooldown_s`` between actions, so an oscillating load cannot flap the
  fleet;
* **bounds**: never below ``min_workers`` (a dead worker is replaced
  immediately — the kill-and-replace path bypasses the dwell), never
  above ``max_workers`` counting spawns still in flight;
* **circuit breaker**: ``breaker_failures`` consecutive spawn failures
  (the subprocess dies, or never advertises within ``spawn_grace_s``)
  open the breaker for ``breaker_cooldown_s`` — a broken image or full
  host degrades to a reasoned event stream, not a spawn storm;

and every decision — acted on or suppressed — is emitted as a reasoned
``autoscale`` event on ``{prefix}.events`` and counted in the
``lmstudio_autoscale_*`` Prometheus families served on
``{prefix}.autoscale.metrics.prom`` (and merged into the cluster
exposition when embedded next to an :class:`obs.aggregator.Aggregator`).

Cold-start is ~seconds, not minutes, because the rest of ISSUE 15 meets
the spawn halfway: ``pull_model`` precompiled the jit grid into the
persistent XLA compile cache (serve/registry.py), and the replacement's
prefix cache is warmed by a ``kv_handoff`` push from the best live donor
(serve/worker.py) as soon as its first advert lands.

Like ClusterRouter and Aggregator, the class is injected with an
already-connected duck-typed client and never imports jax — the
``tick()``/``plan()`` split takes an explicit clock so tests drive the
loop deterministically.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import time

from ..obs import PromRenderer
from ..obs import emit as obs_emit
from ..utils.nuid import next_nuid
from .router import ADVERT_SUBJECT

log = logging.getLogger(__name__)

AUTOSCALE_METRICS_SUBJECT = "autoscale.metrics.prom"

_INF = float("inf")


class Autoscaler:
    """The elastic control loop; see the module docstring.

    ``spawn_fn(worker_id)`` and ``drain_fn(worker_id, handoff_to)`` are
    injectable (sync or async): the defaults spawn ``python -m
    nats_llm_studio_tpu serve`` subprocesses and request the existing
    ``admin.drain`` subject; tests substitute in-process workers.
    """

    def __init__(self, nc, *, prefix: str = "lmstudio",
                 nats_url: str = "nats://127.0.0.1:4222",
                 min_workers: int = 1, max_workers: int = 4,
                 interval_s: float = 1.0,
                 up_dwell_s: float = 2.0, down_dwell_s: float = 15.0,
                 cooldown_s: float = 5.0,
                 up_queue_depth: float = 8.0, down_queue_depth: float = 1.0,
                 spawn_grace_s: float = 20.0,
                 breaker_failures: int = 3, breaker_cooldown_s: float = 30.0,
                 burn_hold_s: float = 10.0,
                 handoff_prefixes: int = 4,
                 drain_deadline_s: float = 10.0,
                 stale_after_s: float = 5.0,
                 spawn_fn=None, drain_fn=None):
        self.nc = nc
        self.prefix = prefix
        self.nats_url = nats_url
        self.min_workers = max(0, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.interval_s = interval_s
        self.up_dwell_s = up_dwell_s
        self.down_dwell_s = down_dwell_s
        self.cooldown_s = cooldown_s
        self.up_queue_depth = up_queue_depth
        self.down_queue_depth = down_queue_depth
        self.spawn_grace_s = spawn_grace_s
        self.breaker_failures = max(1, int(breaker_failures))
        self.breaker_cooldown_s = breaker_cooldown_s
        self.burn_hold_s = burn_hold_s
        self.handoff_prefixes = int(handoff_prefixes)
        self.drain_deadline_s = drain_deadline_s
        self.stale_after_s = stale_after_s
        self.spawn_fn = spawn_fn if spawn_fn is not None else self._default_spawn
        self.drain_fn = drain_fn if drain_fn is not None else self._default_drain
        # membership (aggregator-style: mono-keyed, so a respawned worker
        # reusing an id is simply fresher — no seq guard to trip over)
        self._members: dict[str, dict] = {}  # wid -> {"mono": t, "advert": d}
        # spawns awaiting their first advert: wid -> {"mono": t, "proc": p}
        self._pending: dict[str, dict] = {}
        self._last_burn_mono = -_INF
        self._pressure_since: float | None = None
        self._idle_since: float | None = None
        self._cooldown_until = -_INF
        self._consecutive_failures = 0
        self._breaker_open_until = -_INF
        self._breaker_announced = False
        self._spawn_counter = 0
        self.spawns_total = 0
        self.drains_total = 0
        self.spawn_failures_total = 0
        self._subs: list = []
        self._task: asyncio.Task | None = None
        self._bg_tasks: set[asyncio.Task] = set()

    @classmethod
    def from_config(cls, nc, cfg, **overrides) -> "Autoscaler":
        kw = dict(
            prefix=cfg.subject_prefix,
            nats_url=cfg.nats_url,
            min_workers=cfg.autoscale_min_workers,
            max_workers=cfg.autoscale_max_workers,
            interval_s=cfg.autoscale_interval_s,
            up_dwell_s=cfg.autoscale_up_dwell_s,
            down_dwell_s=cfg.autoscale_down_dwell_s,
            cooldown_s=cfg.autoscale_cooldown_s,
            up_queue_depth=cfg.autoscale_up_queue_depth,
            down_queue_depth=cfg.autoscale_down_queue_depth,
            spawn_grace_s=cfg.autoscale_spawn_grace_s,
            breaker_failures=cfg.autoscale_breaker_failures,
            breaker_cooldown_s=cfg.autoscale_breaker_cooldown_s,
            handoff_prefixes=cfg.autoscale_handoff_prefixes,
            drain_deadline_s=cfg.drain_deadline_s,
        )
        kw.update(overrides)
        return cls(nc, **kw)

    # -- lifecycle -----------------------------------------------------------

    async def start(self, *, control_loop: bool = True) -> None:
        sub = await self.nc.subscribe(
            f"{self.prefix}.{ADVERT_SUBJECT}", cb=self._on_advert
        )
        self._subs.append(sub)
        # plain sub (no queue group): slo_burn alerts are broadcast with no
        # reply; requests on the same subject carry a reply and are the
        # workers' event-ring queries — not ours
        sub = await self.nc.subscribe(f"{self.prefix}.events", cb=self._on_event)
        self._subs.append(sub)
        sub = await self.nc.subscribe(
            f"{self.prefix}.{AUTOSCALE_METRICS_SUBJECT}", cb=self._on_metrics
        )
        self._subs.append(sub)
        if control_loop:
            self._task = asyncio.ensure_future(self._loop())
        log.info(
            "autoscaler up: prefix=%s bounds=[%d,%d] interval=%.1fs",
            self.prefix, self.min_workers, self.max_workers, self.interval_s,
        )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for t in list(self._bg_tasks):
            t.cancel()
        self._bg_tasks.clear()
        for sub in self._subs:
            try:
                await sub.unsubscribe()
            except (ConnectionError, ValueError):
                pass
        self._subs.clear()

    async def _loop(self) -> None:
        try:
            # let the advert stream settle before the first decision: every
            # live member adverts within stale_after_s, so a younger member
            # view cannot distinguish "below min" from "not yet heard from"
            # — acting on it would spawn surplus workers at every control
            # plane restart
            await asyncio.sleep(max(self.interval_s, self.stale_after_s))
            while True:
                try:
                    await self.tick()
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — the loop must survive a bad tick
                    log.exception("autoscale tick failed")
                await asyncio.sleep(self.interval_s)
        except asyncio.CancelledError:
            return

    # -- signal ingestion ----------------------------------------------------

    async def _on_advert(self, msg) -> None:
        try:
            d = json.loads(msg.payload or b"{}")
        except ValueError:
            return
        wid = d.get("worker_id") if isinstance(d, dict) else None
        if not wid:
            return
        self.observe_advert(wid, d)

    def observe_advert(self, wid: str, d: dict) -> None:
        """Fold one advert into the member table (also the test seam)."""
        self._members[wid] = {"mono": time.monotonic(), "advert": d}
        pending = self._pending.pop(wid, None)
        if pending is not None:
            self._consecutive_failures = 0
            ready_s = time.monotonic() - pending["mono"]
            self._emit_soon("spawn_live", "first_advert", worker_id=wid,
                            ready_s=round(ready_s, 3))
            log.info("autoscaler: spawned worker %s live after %.1fs",
                     wid, ready_s)
            if self.handoff_prefixes > 0:
                donor = self._pick_donor(exclude=wid)
                if donor is not None:
                    self._spawn_bg(self._request_handoff(donor, wid))

    async def _on_event(self, msg) -> None:
        if getattr(msg, "reply", None):
            return  # event-ring query addressed to the workers, not a broadcast
        try:
            d = json.loads(msg.payload or b"{}")
        except ValueError:
            return
        if isinstance(d, dict) and d.get("kind") == "slo_burn":
            self._last_burn_mono = time.monotonic()

    async def _on_metrics(self, msg) -> None:
        if not getattr(msg, "reply", None):
            return
        try:
            await msg.respond(self.render_prometheus().encode())
        except (ConnectionError, ValueError):
            pass

    # -- membership views ----------------------------------------------------

    def live_workers(self, now: float | None = None) -> list[str]:
        """Non-draining workers advertising within the staleness window —
        the fleet's effective serving capacity."""
        now = time.monotonic() if now is None else now
        return sorted(
            wid for wid, m in self._members.items()
            if now - m["mono"] <= self.stale_after_s
            and not m["advert"].get("draining")
            # gateway adverts are metrics-only membership: zero-depth
            # non-serving entries must not dilute the scaling signals
            and m["advert"].get("role") != "gateway"
        )

    def _prune(self, now: float) -> None:
        for wid in [w for w, m in self._members.items()
                    if now - m["mono"] > 10 * self.stale_after_s]:
            del self._members[wid]

    def _pick_donor(self, exclude: str) -> str | None:
        """The best live peer to warm-hand a fresh worker from: the least
        loaded non-draining member (it can best afford the export work)."""
        candidates = [w for w in self.live_workers() if w != exclude]
        if not candidates:
            return None

        def load(wid: str) -> tuple:
            adv = self._members[wid]["advert"]
            return (int(adv.get("brownout", 0) or 0),
                    int(adv.get("queue_depth", 0) or 0), wid)

        return min(candidates, key=load)

    def _pick_victim(self, live: list[str]) -> str | None:
        """Scale-down target: the least-loaded live member (fewest in-flight
        requests to hand off; ties break on worker_id for determinism)."""
        if not live:
            return None
        return min(
            live,
            key=lambda w: (int(self._members[w]["advert"].get("queue_depth", 0)
                               or 0), w),
        )

    # -- the control loop ----------------------------------------------------

    def plan(self, now: float | None = None) -> dict | None:
        """One planning step against the member table: returns the decision
        (``{"action": "spawn"|"drain", "reason": ...}``) or None. Pure
        policy — no I/O — so tests drive it with a synthetic clock; dwell
        bookkeeping (pressure/idle since) lives here."""
        now = time.monotonic() if now is None else now
        live = self.live_workers(now)
        n_effective = len(live) + len(self._pending)
        # below the floor: replace NOW (the kill-and-replace path) — a dead
        # worker's absence is not "pressure" to dwell on
        if n_effective < self.min_workers:
            return {"action": "spawn", "reason": "below_min",
                    "workers_live": len(live)}
        adverts = [self._members[w]["advert"] for w in live]
        depths = [int(a.get("queue_depth", 0) or 0) for a in adverts]
        total_depth = sum(depths)
        avg_depth = (total_depth / len(depths)) if depths else 0.0
        brownout = max((int(a.get("brownout", 0) or 0) for a in adverts),
                       default=0)
        burn = (now - self._last_burn_mono) <= self.burn_hold_s
        pressure = burn or avg_depth >= self.up_queue_depth or brownout >= 2
        idle = (not burn and brownout == 0
                and total_depth <= self.down_queue_depth)
        if pressure:
            if self._pressure_since is None:
                self._pressure_since = now
        else:
            self._pressure_since = None
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        if now < self._cooldown_until:
            return None
        if (self._pressure_since is not None
                and now - self._pressure_since >= self.up_dwell_s):
            if n_effective >= self.max_workers:
                return None  # pressed against the ceiling: shedding handles it
            reason = ("slo_burn" if burn
                      else f"queue_depth avg {avg_depth:.1f}" if
                      avg_depth >= self.up_queue_depth
                      else f"brownout {brownout}")
            return {"action": "spawn", "reason": reason,
                    "workers_live": len(live)}
        if (self._idle_since is not None
                and now - self._idle_since >= self.down_dwell_s):
            if len(live) <= self.min_workers:
                return None
            victim = self._pick_victim(live)
            if victim is None:
                return None
            return {"action": "drain", "reason": "idle", "victim": victim,
                    "workers_live": len(live)}
        return None

    async def tick(self, now: float | None = None) -> dict | None:
        """One control tick: expire overdue spawns, plan, act. Returns the
        decision acted on (or suppressed by the breaker), for tests."""
        now = time.monotonic() if now is None else now
        self._prune(now)
        self._expire_pending(now)
        decision = self.plan(now)
        if decision is None:
            return None
        if decision["action"] == "spawn":
            await self._spawn(now, decision)
        elif decision["action"] == "drain":
            await self._drain(now, decision)
        return decision

    def _expire_pending(self, now: float) -> None:
        for wid in [w for w, p in self._pending.items()
                    if now - p["mono"] > self.spawn_grace_s]:
            p = self._pending.pop(wid)
            proc = p.get("proc")
            if proc is not None and getattr(proc, "poll", None) is not None:
                try:
                    if proc.poll() is None:
                        proc.kill()
                except Exception:  # noqa: BLE001 — cleanup is best-effort
                    pass
            self._record_spawn_failure(now, wid, "no_advert_within_grace")

    def _record_spawn_failure(self, now: float, wid: str, why: str) -> None:
        self.spawn_failures_total += 1
        self._consecutive_failures += 1
        self._emit_soon("spawn_failed", why, worker_id=wid,
                        consecutive=self._consecutive_failures)
        log.warning("autoscaler: spawn of %s failed (%s; %d consecutive)",
                    wid, why, self._consecutive_failures)
        if self._consecutive_failures >= self.breaker_failures:
            self._breaker_open_until = now + self.breaker_cooldown_s
            self._breaker_announced = False

    def breaker_open(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        return now < self._breaker_open_until

    async def _spawn(self, now: float, decision: dict) -> None:
        if self.breaker_open(now):
            if not self._breaker_announced:
                self._breaker_announced = True
                await self._emit(
                    "spawn_suppressed", "breaker_open",
                    wanted=decision["reason"],
                    open_for_s=round(self._breaker_open_until - now, 1),
                )
            return
        self._spawn_counter += 1
        wid = f"w-as{self._spawn_counter}-{next_nuid()[-6:].lower()}"
        try:
            res = self.spawn_fn(wid)
            if asyncio.iscoroutine(res):
                res = await res
        except Exception as e:  # noqa: BLE001 — a failed exec is a spawn failure
            self._record_spawn_failure(now, wid, f"{type(e).__name__}: {e}")
            return
        # stamped with the tick clock (== monotonic in live operation) so
        # grace expiry composes with test-driven synthetic time
        self._pending[wid] = {"mono": now, "proc": res}
        self.spawns_total += 1
        self._cooldown_until = now + self.cooldown_s
        self._pressure_since = None
        await self._emit("spawn", decision["reason"], worker_id=wid,
                         workers_live=decision.get("workers_live", 0),
                         workers_pending=len(self._pending))

    async def _drain(self, now: float, decision: dict) -> None:
        victim = decision["victim"]
        # the drained worker's hot cache should survive on a peer, not die
        # with it: hand off to the least-loaded survivor
        handoff_to = (self._pick_donor(exclude=victim)
                      if self.handoff_prefixes > 0 else None)
        self.drains_total += 1
        self._cooldown_until = now + self.cooldown_s
        self._idle_since = None
        await self._emit("drain", decision["reason"], worker_id=victim,
                         handoff_to=handoff_to or "",
                         workers_live=decision.get("workers_live", 0))
        try:
            res = self.drain_fn(victim, handoff_to)
            if asyncio.iscoroutine(res):
                await res
        except Exception as e:  # noqa: BLE001 — a lost drain ages out via staleness
            log.warning("autoscaler: drain of %s failed: %s", victim, e)

    # -- actions (defaults) --------------------------------------------------

    def _default_spawn(self, wid: str):
        env = {**os.environ, "WORKER_ID": wid, "NATS_URL": self.nats_url}
        # a spawned worker is a worker, not another control plane
        for k in ("OBS_AUTOSCALE", "OBS_AGGREGATOR"):
            env.pop(k, None)
        return subprocess.Popen(
            [sys.executable, "-m", "nats_llm_studio_tpu", "serve"], env=env
        )

    async def _default_drain(self, wid: str, handoff_to: str | None):
        req = {"worker_id": wid, "deadline_s": self.drain_deadline_s}
        if handoff_to:
            req["handoff_to"] = handoff_to
        await self.nc.request(
            f"{self.prefix}.admin.drain",
            json.dumps(req, separators=(",", ":")).encode(),
            timeout=self.drain_deadline_s + 10.0,
        )

    async def _request_handoff(self, donor: str, recipient: str) -> None:
        """Ask ``donor`` to push its hottest prefixes to ``recipient``
        (fire-and-forget warm-up of a fresh spawn)."""
        try:
            await self.nc.request(
                f"{self.prefix}.worker.{donor}.kv_handoff",
                json.dumps({"to": recipient, "limit": self.handoff_prefixes},
                           separators=(",", ":")).encode(),
                timeout=30.0,
            )
        except Exception as e:  # noqa: BLE001 — warm-up is best-effort
            log.warning("autoscaler: warm handoff %s -> %s failed: %s",
                        donor, recipient, e)

    # -- observability -------------------------------------------------------

    def _spawn_bg(self, coro) -> None:
        t = asyncio.ensure_future(coro)
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    def _emit_soon(self, action: str, reason: str, **extra) -> None:
        """Event emission from sync code paths: ring-buffer immediately,
        bus publish as a background task."""
        self._spawn_bg(self._emit(action, reason, _ring=False, **extra))
        obs_emit("autoscale", action=action, reason=reason, **extra)

    async def _emit(self, action: str, reason: str, _ring: bool = True,
                    **extra) -> None:
        if _ring:
            obs_emit("autoscale", action=action, reason=reason, **extra)
        payload = {"kind": "autoscale", "action": action, "reason": reason,
                   **extra}
        try:
            await self.nc.publish(
                f"{self.prefix}.events",
                json.dumps(payload, separators=(",", ":")).encode(),
            )
        except (ConnectionError, ValueError):
            pass  # reconnect in flight; the decision still sits in the ring

    def render_prometheus(self, now: float | None = None) -> str:
        """The ``lmstudio_autoscale_*`` families — served directly on
        ``{prefix}.autoscale.metrics.prom`` and foldable into the cluster
        exposition via Aggregator(extra_expositions=[...]). All families
        are always present (zero-valued) so dashboards can assert on
        existence."""
        now = time.monotonic() if now is None else now
        r = PromRenderer()
        r.counter("lmstudio_autoscale_spawns_total", self.spawns_total,
                  help="worker spawns initiated by the autoscaler")
        r.counter("lmstudio_autoscale_drains_total", self.drains_total,
                  help="scale-down drains initiated by the autoscaler")
        r.counter("lmstudio_autoscale_spawn_failures_total",
                  self.spawn_failures_total,
                  help="spawns that failed to exec or never advertised "
                       "within the grace window")
        r.gauge("lmstudio_autoscale_workers_live",
                len(self.live_workers(now)),
                help="non-draining workers advertising within the "
                     "staleness window")
        r.gauge("lmstudio_autoscale_workers_pending", len(self._pending),
                help="spawned workers awaiting their first advert")
        r.gauge("lmstudio_autoscale_breaker_open",
                1 if self.breaker_open(now) else 0,
                help="1 while the spawn circuit breaker is open")
        return r.render()

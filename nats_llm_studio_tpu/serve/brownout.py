"""Adaptive brownout: degrade service under overload instead of falling over.

The batcher's only overload responses so far are binary — serve, or shed at
the depth/age bounds. Under sustained pressure that means full-quality
service right up to the cliff, then 503s. This module adds the middle
ground: a small state machine the batcher owner thread ticks every loop,

    NORMAL  →  BROWNOUT  →  SHED_ONLY

driven by three signals (admit queue depth as a fraction of the limit,
queue age p95, HBM headroom) with hysteresis — escalation is immediate when
any signal crosses its high-water mark, de-escalation requires *every*
signal below its low-water mark continuously for ``dwell_s`` so the
controller cannot flap at a threshold.

Per level the batcher applies cheap, reversible levers (serve/batcher.py):

- BROWNOUT: pause speculative decoding (verify slots go back to plain
  decode throughput), halve ``decode_burst`` (shorter dispatch windows →
  faster shed/abort reaction), stop harvesting new prefix-cache blocks
  (admits stop paying the copy-out), and tighten the effective admit queue
  limit to ``tighten_frac`` of the configured one.
- SHED_ONLY: all of the above, burst forced to 1, and *new* submits are
  shed immediately with a retryable envelope — already-queued work drains.

Every transition is emitted to the obs event ring (kind ``brownout``) and
the current level is exposed as the ``lmstudio_brownout_level`` gauge and
in ``health``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..obs.events import emit as obs_emit

NORMAL = 0
BROWNOUT = 1
SHED_ONLY = 2

LEVEL_NAMES = {NORMAL: "normal", BROWNOUT: "brownout", SHED_ONLY: "shed_only"}


@dataclass(frozen=True)
class BrownoutConfig:
    """Thresholds for the controller; env-tunable via BROWNOUT_* knobs
    (config.py). ``*_hi`` marks escalate one level when crossed, ``*_lo``
    marks must ALL hold for ``dwell_s`` before de-escalating one level.
    ``shed_only_scale`` multiplies the hi marks for the BROWNOUT→SHED_ONLY
    edge (pressure well past the first response)."""

    depth_hi: float = 0.75     # queue depth / queue limit
    depth_lo: float = 0.40
    age_hi_ms: float = 1500.0  # queue age p95
    age_lo_ms: float = 500.0
    hbm_lo_frac: float = 0.05  # headroom below this escalates
    dwell_s: float = 2.0       # calm required before stepping back down
    shed_only_scale: float = 1.5
    tighten_frac: float = 0.5  # effective admit-limit fraction in brownout
    # SHED_ONLY slot target as a fraction of max_slots: with tiered KV +
    # slot suspend enabled the batcher suspends (not cancels) the youngest
    # slots down to this fraction on the SHED_ONLY edge, freeing pool blocks
    # and decode width for the oldest streams without losing any work
    suspend_frac: float = 0.5


class BrownoutController:
    """Ticked by the batcher owner thread only; ``level`` is a plain int
    read cross-thread by the submit path (single attribute read — no lock)."""

    def __init__(self, cfg: BrownoutConfig | None = None, *, engine: str = ""):
        self.cfg = cfg or BrownoutConfig()
        self.engine = engine
        self.level = NORMAL
        self.transitions = 0  # lifetime transition count (bench deltas)
        self._calm_since: float | None = None

    def _pressure(self, depth_frac: float, age_p95_ms: float,
                  hbm_headroom_frac: float | None, scale: float) -> list[str]:
        """Names of the signals over their (scaled) high-water marks."""
        c = self.cfg
        over = []
        if depth_frac >= c.depth_hi * scale:
            over.append("depth")
        if age_p95_ms >= c.age_hi_ms * scale:
            over.append("age")
        if hbm_headroom_frac is not None and hbm_headroom_frac <= c.hbm_lo_frac / scale:
            over.append("hbm")
        return over

    def update(self, *, depth_frac: float, age_p95_ms: float,
               hbm_headroom_frac: float | None = None,
               now: float | None = None) -> int:
        """Feed the current signals; returns the (possibly new) level."""
        c = self.cfg
        now = time.monotonic() if now is None else now
        hot = self._pressure(depth_frac, age_p95_ms, hbm_headroom_frac, 1.0)
        very_hot = self._pressure(depth_frac, age_p95_ms, hbm_headroom_frac,
                                  c.shed_only_scale)
        calm = (
            depth_frac < c.depth_lo
            and age_p95_ms < c.age_lo_ms
            and (hbm_headroom_frac is None or hbm_headroom_frac > c.hbm_lo_frac)
        )

        target = self.level
        if self.level < SHED_ONLY and very_hot:
            target = SHED_ONLY
        elif self.level < BROWNOUT and hot:
            target = BROWNOUT

        if target > self.level:
            self._calm_since = None
            self._transition(target, reasons=very_hot or hot)
            return self.level

        if self.level > NORMAL and calm:
            if self._calm_since is None:
                self._calm_since = now
            elif now - self._calm_since >= c.dwell_s:
                self._calm_since = now  # restart the dwell for the next step
                self._transition(self.level - 1, reasons=["calm"])
        else:
            self._calm_since = None
        return self.level

    def _transition(self, new_level: int, reasons: list[str]) -> None:
        old = self.level
        self.level = new_level
        self.transitions += 1
        obs_emit(
            "brownout",
            engine=self.engine,
            level=new_level,
            level_name=LEVEL_NAMES[new_level],
            prev=LEVEL_NAMES[old],
            reasons=reasons,
        )

    # -- levers the batcher consults ------------------------------------

    @property
    def pause_spec(self) -> bool:
        return self.level >= BROWNOUT

    @property
    def pause_prefix_harvest(self) -> bool:
        return self.level >= BROWNOUT

    def effective_burst(self, burst: int) -> int:
        if self.level >= SHED_ONLY:
            return 1
        if self.level >= BROWNOUT:
            return max(1, burst // 2)
        return burst

    def effective_queue_limit(self, max_queue: int) -> int:
        """Tightened admit limit (0 keeps the zero-disables convention)."""
        if max_queue and self.level >= BROWNOUT:
            return max(1, int(max_queue * self.cfg.tighten_frac))
        return max_queue

    def suspend_target(self, max_slots: int) -> int:
        """Slot-count target for the suspend lever: below it, the batcher
        stops suspending. Only binds in SHED_ONLY; resume is gated on the
        level dropping back below SHED_ONLY."""
        if self.level >= SHED_ONLY:
            return max(1, int(max_slots * self.cfg.suspend_frac))
        return max_slots

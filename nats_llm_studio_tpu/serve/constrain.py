"""JSON-schema constrained decoding: schema -> character NFA -> lazy DFA ->
per-state token-vocabulary masks.

The gateway accepts OpenAI's ``response_format: {"type": "json_schema"}``;
this module turns the schema into a :class:`TokenDFA` whose per-state boolean
masks the batcher uploads as a per-step logit mask (Outlines/XGrammar line of
work). Everything is in-tree — no regex/automata dependency:

* a JSON-schema subset compiles into a Thompson NFA via combinators
  (no intermediate regex string to mis-parse): objects with properties in
  declaration order, strings, integers, numbers, booleans, null, enum/const,
  bounded arrays, anyOf/oneOf
* the DFA is the lazy subset construction over the NFA, memoized per
  (state-set, character) — character classes may be negated, so the
  alphabet is discovered from token walks instead of enumerated
* :class:`TokenDFA` walks every vocabulary token's surface string through
  the DFA once per visited state and caches the resulting [vocab] bool mask;
  EOS/stop tokens are allowed only at accepting states

The emitted language is *canonical tight JSON* (no whitespace between
tokens): constrained output is parseable and schema-valid by construction,
and the DFA stays small. Multi-byte/partial-UTF-8 byte-fallback tokens are
excluded from masks (a constrained stream can still emit any ASCII JSON).
"""

from __future__ import annotations

import json
import os
from typing import Iterable

import numpy as np

__all__ = [
    "ConstraintError",
    "TokenDFA",
    "compile_token_dfa",
    "enabled",
    "token_strings",
    "validate_response_format",
]


def enabled() -> bool:
    """``CONSTRAIN=0`` is the operator off-switch: constrained requests are
    rejected up front instead of entering the single-step ext decode regime
    (which trades batcher throughput for schema guarantees)."""
    return os.environ.get("CONSTRAIN", "").strip().lower() not in (
        "0", "false", "off",
    )

# guard rails: schemas compiling past these bounds are rejected up front
# (the DFA walk is per-token per-state — unbounded blowup would stall the
# engine thread, not just this request)
_MAX_NFA_STATES = 20_000
_MAX_DFA_STATES = 20_000
_MAX_REPEAT = 64
# canonical JSON string contents: anything except the quote, the backslash,
# and raw control characters (escapes are not generated — tight JSON without
# them is still schema-valid)
_STRING_BANNED = frozenset('"\\') | frozenset(chr(c) for c in range(0x20))


class ConstraintError(ValueError):
    """Schema rejected: unsupported construct or compiled automaton too big."""


# -- Thompson NFA via combinators -------------------------------------------
#
# Fragments are (start, accepts) over a shared transition table. Transitions:
#   eps[s]   -> list of epsilon successor states
#   edges[s] -> list of ((negate, charset), successor)


class _NFA:
    def __init__(self) -> None:
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[tuple[bool, frozenset], int]]] = []

    def state(self) -> int:
        if len(self.eps) >= _MAX_NFA_STATES:
            raise ConstraintError(
                f"schema too complex: > {_MAX_NFA_STATES} NFA states"
            )
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    # fragments ----------------------------------------------------------

    def char(self, chars: Iterable[str], negate: bool = False):
        a, b = self.state(), self.state()
        self.edges[a].append(((negate, frozenset(chars)), b))
        return a, b

    def lit(self, text: str):
        a = self.state()
        cur = a
        for ch in text:
            nxt = self.state()
            self.edges[cur].append(((False, frozenset((ch,))), nxt))
            cur = nxt
        return a, cur

    def seq(self, *frags):
        if not frags:
            a = self.state()
            return a, a
        start, end = frags[0]
        for s, e in frags[1:]:
            self.eps[end].append(s)
            end = e
        return start, end

    def alt(self, *frags):
        a, b = self.state(), self.state()
        for s, e in frags:
            self.eps[a].append(s)
            self.eps[e].append(b)
        return a, b

    def opt(self, frag):
        s, e = frag
        self.eps[s].append(e)
        return s, e

    def star(self, frag):
        s, e = frag
        a, b = self.state(), self.state()
        self.eps[a] += [s, b]
        self.eps[e] += [s, b]
        return a, b

    def plus(self, frag):
        s, e = frag
        self.eps[e].append(s)
        return s, e

    def repeat(self, make_frag, lo: int, hi: int):
        """``make_frag()`` repeated between lo and hi times (fresh states per
        copy — fragments cannot be reused once wired)."""
        if hi > _MAX_REPEAT:
            raise ConstraintError(f"repetition bound {hi} > {_MAX_REPEAT}")
        frags = [make_frag() for _ in range(lo)]
        frags += [self.opt(make_frag()) for _ in range(hi - lo)]
        return self.seq(*frags) if frags else self.seq()


# -- JSON-schema subset -> NFA fragment --------------------------------------


def _string_frag(n: _NFA, schema: dict):
    body = n.star(n.char(_STRING_BANNED, negate=True))
    return n.seq(n.lit('"'), body, n.lit('"'))


def _integer_frag(n: _NFA, schema: dict):
    nonzero = n.seq(
        n.char("123456789"),
        n.repeat(lambda: n.char("0123456789"), 0, 17),
    )
    return n.seq(n.opt(n.lit("-")), n.alt(n.lit("0"), nonzero))


def _number_frag(n: _NFA, schema: dict):
    frac = n.seq(n.lit("."), n.plus(n.char("0123456789")))
    exp = n.seq(
        n.char("eE"), n.opt(n.char("+-")), n.repeat(lambda: n.char("0123456789"), 1, 3)
    )
    return n.seq(_integer_frag(n, schema), n.opt(frac), n.opt(exp))


def _enum_frag(n: _NFA, values):
    if not values:
        raise ConstraintError("enum must be non-empty")
    frags = []
    for v in values:
        try:
            frags.append(n.lit(json.dumps(v, separators=(",", ":"))))
        except TypeError as e:  # non-JSON value in the schema
            raise ConstraintError(f"enum value not JSON-serializable: {v!r}") from e
    return n.alt(*frags)


def _array_frag(n: _NFA, schema: dict, depth: int):
    items = schema.get("items") or {}
    lo = int(schema.get("minItems", 0))
    hi = int(schema.get("maxItems", 8))
    if not (0 <= lo <= hi):
        raise ConstraintError(f"bad array bounds minItems={lo} maxItems={hi}")
    if hi == 0:
        return n.lit("[]")
    first = _schema_frag(n, items, depth)
    rest = n.repeat(
        lambda: n.seq(n.lit(","), _schema_frag(n, items, depth)),
        max(lo - 1, 0), hi - 1,
    )
    body = n.seq(first, rest)
    if lo == 0:
        body = n.opt(body)
    return n.seq(n.lit("["), body, n.lit("]"))


def _object_frag(n: _NFA, schema: dict, depth: int):
    props = schema.get("properties") or {}
    if not isinstance(props, dict):
        raise ConstraintError("'properties' must be an object")
    if not props:
        # generic object: bounded string->value members
        member = lambda: n.seq(  # noqa: E731 — tiny local factory
            _string_frag(n, {}), n.lit(":"), _value_frag(n, depth - 1)
        )
        body = n.opt(n.seq(member(), n.repeat(
            lambda: n.seq(n.lit(","), member()), 0, 8,
        )))
        return n.seq(n.lit("{"), body, n.lit("}"))
    # canonical form: every declared property present, declaration order —
    # the DFA needs one fixed member order, and requiring all of them keeps
    # optional-member combinatorics out of the automaton
    frags = [n.lit("{")]
    for i, (key, sub) in enumerate(props.items()):
        if i:
            frags.append(n.lit(","))
        frags.append(n.lit(json.dumps(str(key)) + ":"))
        frags.append(_schema_frag(n, sub if isinstance(sub, dict) else {}, depth))
    frags.append(n.lit("}"))
    return n.seq(*frags)


def _value_frag(n: _NFA, depth: int):
    """Generic JSON value, nesting bounded at ``depth`` (DFAs cannot count
    unbounded nesting; a bounded approximation keeps output parseable)."""
    scalars = [
        _string_frag(n, {}),
        _number_frag(n, {}),
        n.lit("true"), n.lit("false"), n.lit("null"),
    ]
    if depth <= 0:
        return n.alt(*scalars)
    return n.alt(
        *scalars,
        _object_frag(n, {}, depth - 1),
        _array_frag(n, {"items": {}}, depth - 1),
    )


def _schema_frag(n: _NFA, schema: dict, depth: int = 2):
    if not isinstance(schema, dict):
        raise ConstraintError(f"schema must be an object, got {type(schema).__name__}")
    if "const" in schema:
        return _enum_frag(n, [schema["const"]])
    if "enum" in schema:
        return _enum_frag(n, schema["enum"])
    for key in ("anyOf", "oneOf"):
        if key in schema:
            subs = schema[key]
            if not isinstance(subs, list) or not subs:
                raise ConstraintError(f"'{key}' must be a non-empty array")
            return n.alt(*[_schema_frag(n, s, depth) for s in subs])
    t = schema.get("type")
    if isinstance(t, list):
        return n.alt(*[_schema_frag(n, {**schema, "type": ti}, depth) for ti in t])
    if t == "object" or (t is None and "properties" in schema):
        return _object_frag(n, schema, depth)
    if t == "string":
        return _string_frag(n, schema)
    if t == "integer":
        return _integer_frag(n, schema)
    if t == "number":
        return _number_frag(n, schema)
    if t == "boolean":
        return n.alt(n.lit("true"), n.lit("false"))
    if t == "null":
        return n.lit("null")
    if t == "array":
        return _array_frag(n, schema, depth)
    if t is None:
        return _value_frag(n, depth)
    raise ConstraintError(f"unsupported schema type: {t!r}")


# -- lazy subset-construction DFA --------------------------------------------


class _DFA:
    """Subset construction over the NFA, built lazily: transitions are
    memoized per (state, char) because negated character classes make the
    alphabet effectively unbounded. State 0 is the start; ``None`` is the
    dead state."""

    def __init__(self, nfa: _NFA, start: int, accept: int):
        self._nfa = nfa
        self._accept = accept
        self._ids: dict[frozenset, int] = {}
        self._sets: list[frozenset] = []
        self._trans: dict[tuple[int, str], int | None] = {}
        self.start = self._intern(self._closure({start}))

    def _closure(self, states: set) -> frozenset:
        stack, seen = list(states), set(states)
        eps = self._nfa.eps
        while stack:
            s = stack.pop()
            for t in eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    def _intern(self, sset: frozenset) -> int:
        sid = self._ids.get(sset)
        if sid is None:
            if len(self._sets) >= _MAX_DFA_STATES:
                raise ConstraintError(
                    f"schema too complex: > {_MAX_DFA_STATES} DFA states"
                )
            sid = len(self._sets)
            self._ids[sset] = sid
            self._sets.append(sset)
        return sid

    def step(self, sid: int, ch: str) -> int | None:
        key = (sid, ch)
        hit = self._trans.get(key, _MISS)
        if hit is not _MISS:
            return hit
        nxt: set[int] = set()
        edges = self._nfa.edges
        for s in self._sets[sid]:
            for (negate, chars), t in edges[s]:
                if (ch in chars) != negate:
                    nxt.add(t)
        out = self._intern(self._closure(nxt)) if nxt else None
        self._trans[key] = out
        return out

    def accepting(self, sid: int) -> bool:
        return self._accept in self._sets[sid]


_MISS = object()


# -- vocabulary surface strings ----------------------------------------------


def token_strings(tokenizer, vocab_size: int) -> list:
    """Per-token-id surface string, or None for tokens a constrained stream
    must never emit (control tokens, partial-UTF-8 byte fallbacks). Handles
    the GGUF llama/gpt2 families precisely and falls back to per-id
    ``decode`` for anything else (test fakes, external tokenizers)."""
    model = getattr(tokenizer, "model", None)
    tokens = getattr(tokenizer, "tokens", None)
    out: list = [None] * vocab_size
    n = min(vocab_size, len(tokens) if tokens is not None else vocab_size)
    control = getattr(tokenizer, "_control_ids", frozenset())
    if tokens is not None and model == "llama":
        for i in range(n):
            if i in control:
                continue
            t = tokens[i]
            if t.startswith("<0x") and t.endswith(">") and len(t) == 6:
                b = int(t[3:-1], 16)
                out[i] = chr(b) if 0x20 <= b < 0x7F else None
            else:
                out[i] = t.replace("▁", " ")
        return out
    if tokens is not None and model == "gpt2":
        u2b = getattr(tokenizer, "_u2b", {})
        for i in range(n):
            if i in control:
                continue
            buf = bytearray()
            for ch in tokens[i]:
                b = u2b.get(ch)
                if b is not None:
                    buf.append(b)
                else:
                    buf.extend(ch.encode("utf-8"))
            try:
                out[i] = buf.decode("utf-8")
            except UnicodeDecodeError:
                out[i] = None  # partial multi-byte sequence
        return out
    if tokens is not None:
        for i in range(n):
            out[i] = tokens[i] if i not in control else None
        return out
    dec = getattr(tokenizer, "decode", None)
    if dec is None:
        raise ConstraintError("tokenizer exposes neither .tokens nor .decode")
    for i in range(n):
        try:
            out[i] = dec([i])
        except Exception:  # noqa: BLE001 — odd ids stay banned
            out[i] = None
    return out


# -- token-level DFA ----------------------------------------------------------


class TokenDFA:
    """Character DFA lifted to the token vocabulary.

    ``mask(state)`` is a cached [vocab] bool array: token allowed iff its
    whole surface string transitions without hitting the dead state (ending
    mid-pattern is fine — later tokens continue the walk). EOS/stop ids are
    allowed exactly at accepting states, so generation can only end on a
    complete schema-valid document."""

    def __init__(self, dfa: _DFA, strings: list, vocab_size: int,
                 eos_ids: frozenset):
        self._dfa = dfa
        self._strings = strings
        self.vocab_size = vocab_size
        self.eos_ids = frozenset(i for i in eos_ids if 0 <= i < vocab_size)
        self.start = dfa.start
        self._masks: dict[int, np.ndarray] = {}
        # token walk memo: (state, token_id) -> end state (None = banned)
        self._walk: dict[tuple[int, int], int | None] = {}

    def _walk_token(self, state: int, tid: int) -> int | None:
        key = (state, tid)
        hit = self._walk.get(key, _MISS)
        if hit is not _MISS:
            return hit
        s = self._strings[tid]
        out: int | None
        if s is None or s == "":
            out = None
        else:
            cur: int | None = state
            for ch in s:
                cur = self._dfa.step(cur, ch)
                if cur is None:
                    break
            out = cur
        self._walk[key] = out
        return out

    def mask(self, state: int) -> np.ndarray:
        m = self._masks.get(state)
        if m is not None:
            return m
        m = np.zeros(self.vocab_size, dtype=bool)
        for tid in range(self.vocab_size):
            if self._walk_token(state, tid) is not None:
                m[tid] = True
        if self._dfa.accepting(state):
            for e in self.eos_ids:
                m[e] = True
        self._masks[state] = m
        return m

    def advance(self, state: int, tid: int) -> int | None:
        """Next DFA state after emitting token ``tid`` (None = the token was
        not allowed — callers treat this as a terminal condition)."""
        if tid in self.eos_ids:
            return state if self._dfa.accepting(state) else None
        return self._walk_token(state, tid)

    def accepting(self, state: int) -> bool:
        return self._dfa.accepting(state)

    def live(self, state: int) -> bool:
        """Any token (or EOS) allowed from here? False = the stream must
        end now with whatever finish reason the caller chooses."""
        return bool(self.mask(state).any())


# compile cache: the vocab walk is the expensive part (O(vocab x token_len)
# per visited DFA state), and agents re-send the same schema every call
_CACHE: dict[tuple[int, str, int], TokenDFA] = {}
_CACHE_MAX = 32


def compile_token_dfa(schema, tokenizer, vocab_size: int,
                      eos_ids: Iterable[int] = ()) -> TokenDFA:
    """Compile a JSON schema into a :class:`TokenDFA` for ``tokenizer``.

    Raises :class:`ConstraintError` for unsupported/over-complex schemas —
    callers map that to a 400, never a retryable envelope."""
    try:
        canon = json.dumps(schema, sort_keys=True, separators=(",", ":"))
    except TypeError as e:
        raise ConstraintError(f"schema is not JSON-serializable: {e}") from e
    key = (id(tokenizer), canon, int(vocab_size))
    hit = _CACHE.get(key)
    if hit is not None:
        return hit
    nfa = _NFA()
    start, end = _schema_frag(nfa, schema if isinstance(schema, dict) else {})
    dfa = _DFA(nfa, start, end)
    strings = token_strings(tokenizer, vocab_size)
    tdfa = TokenDFA(dfa, strings, vocab_size, frozenset(eos_ids))
    # smoke-check: a schema whose start state allows nothing can never
    # produce a token — reject at compile time, not mid-decode
    if not tdfa.live(tdfa.start):
        raise ConstraintError(
            "schema compiles to an empty language for this vocabulary"
        )
    if len(_CACHE) >= _CACHE_MAX:
        _CACHE.pop(next(iter(_CACHE)))
    _CACHE[key] = tdfa
    return tdfa


# -- response_format validation (shared by gateway and engine) ---------------


def validate_response_format(rf) -> dict | None:
    """Structural check of an OpenAI ``response_format`` value. Returns the
    schema dict for constrained modes (``{}`` means "any JSON object"),
    None when no constraint applies. Raises ValueError with a client-facing
    message for garbled values — the gateway turns that into a 400 WITHOUT
    touching the batcher."""
    if rf is None:
        return None
    if not isinstance(rf, dict):
        raise ValueError("response_format must be an object")
    t = rf.get("type")
    if t in (None, "text"):
        return None
    if t == "json_object":
        return {}
    if t != "json_schema":
        raise ValueError(
            f"response_format.type must be 'text', 'json_object' or "
            f"'json_schema', got {t!r}"
        )
    js = rf.get("json_schema")
    if not isinstance(js, dict):
        raise ValueError("response_format.json_schema must be an object")
    schema = js.get("schema")
    if not isinstance(schema, dict):
        raise ValueError("response_format.json_schema.schema must be an object")
    return schema

"""Continuous batcher: concurrent requests share one fixed-width decode step.

SURVEY.md §7 puts this on the critical perf path (hard part #5): single-stream
decode is HBM-bound on reading the weights once *per token*; batching B
requests reads them once per B tokens. Design:

* one decode program compiled at a fixed ``[B, 1]`` batch width (no shape
  churn); empty slots run masked (token 0, pos 0, greedy) and are ignored
* requests prefill into a single-row cache (bucketed lengths) and are
  scattered into the shared ``[B, L, Hkv, S, D]`` cache at their slot index —
  joining and leaving never recompiles the decode step
* one dedicated owner thread drives the device (the decode loop is the one
  shared-mutable structure — SURVEY.md §5); asyncio callers talk to it
  through thread-safe queues
"""

from __future__ import annotations

import asyncio
import logging
import queue as _queue
import random
import threading
from dataclasses import dataclass, field
from functools import partial
from typing import AsyncIterator

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.generator import SamplingParams, default_buckets
from ..models.config import ModelConfig
from ..models.llama import forward, make_cache
from ..engine.sampling import sample_rows

log = logging.getLogger(__name__)


@dataclass
class _Request:
    prompt_ids: list[int]
    sp: SamplingParams
    loop: asyncio.AbstractEventLoop
    out: asyncio.Queue  # (kind, value): ("tok", id) | ("end", reason) | ("err", exc)
    slot: int = -1
    pos: int = 0
    generated: int = 0

    def emit(self, kind: str, value) -> None:
        self.loop.call_soon_threadsafe(self.out.put_nowait, (kind, value))


@dataclass
class BatcherStats:
    requests: int = 0
    tokens: int = 0
    steps: int = 0
    peak_active: int = 0
    grouped_admits: int = 0  # requests admitted via the batched-admit path

    def snapshot(self) -> dict:
        return {
            "requests": self.requests,
            "tokens": self.tokens,
            "decode_steps": self.steps,
            "peak_active_slots": self.peak_active,
            "grouped_admits": self.grouped_admits,
            "tokens_per_step_avg": round(self.tokens / self.steps, 2) if self.steps else 0.0,
        }


class ContinuousBatcher:
    """Owns the device loop for one loaded model."""

    def __init__(
        self,
        params,
        cfg: ModelConfig,
        max_slots: int = 8,
        max_seq_len: int | None = None,
        buckets: list[int] | None = None,
        mesh=None,
        prefill_chunk: int = 256,
        decode_burst: int = 8,
    ):
        from ..models.llama import ensure_lm_head

        self.params = ensure_lm_head(params)
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_seq = min(max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
        self.buckets = buckets or default_buckets(self.max_seq)
        self.mesh = mesh
        # prompts longer than this prefill in chunks, with one shared decode
        # step interleaved between chunks so active streams' inter-token gap
        # is bounded by ~one chunk's prefill, not the whole prompt's
        # (VERDICT round-1 weak #4: head-of-line blocking on admit).
        # The chunk must divide max_seq: the final zero-padded [1, C] chunk
        # would otherwise write past the cache end, where dynamic-update-
        # slice clamps the start and corrupts earlier prefix slots.
        self.prefill_chunk = max(8, prefill_chunk)
        while self.max_seq % self.prefill_chunk and self.prefill_chunk > 8:
            self.prefill_chunk //= 2
        if self.max_seq % self.prefill_chunk:
            raise ValueError(
                f"max_seq_len={self.max_seq} must be divisible by a prefill "
                f"chunk >= 8; use a power-of-two max_seq_len"
            )
        # decode runs ``decode_burst`` steps per dispatch (one on-device
        # lax.scan): host<->device round trips dominate per-step cost on a
        # tunneled chip (~50-100 ms each vs a ~3 ms device step), so tokens
        # stream in bursts of N. 1 = token-by-token.
        self.decode_burst = max(1, decode_burst)
        self.stats = BatcherStats()

        fwd = partial(forward, cfg=cfg, mesh=mesh)

        @jax.jit
        def prefill1(params, tokens, k1, v1, start):
            logits, k1, v1 = fwd(
                params, tokens=tokens, k_cache=k1, v_cache=v1, start_pos=start,
            )
            return logits, k1, v1

        def _insert_and_sample(params, K, V, k1, v1, logits, n, slot, shift,
                               seed, temp, topk, topp):
            """Roll the prefilled row onto the ring, write it, sample token 0.

            The prefix (tokens at [0, n) of k1) must land on the ring slots
            ending at the current ring head, so the whole row is rolled by
            ``shift`` = (ring_next - n) mod S before the row write — decode
            validity is "the start_pos+1 most recent ring slots" and relies
            on every row's tokens being slot-contiguous there.
            """
            zero = jnp.zeros((), jnp.int32)
            k1 = jnp.roll(k1, shift, axis=3)
            v1 = jnp.roll(v1, shift, axis=3)
            K = jax.lax.dynamic_update_slice(K, k1, (slot, zero, zero, zero, zero))
            V = jax.lax.dynamic_update_slice(V, v1, (slot, zero, zero, zero, zero))
            last = jnp.take(logits, n - 1, axis=1)  # [1, vocab]
            first = sample_rows(
                last, seed[None], jnp.zeros((1,), jnp.int32),
                temp[None], topk[None], topp[None],
            )
            return first, K, V

        @partial(jax.jit, donate_argnums=(1, 2))
        def admit_fused(params, K, V, tokens, n, slot, shift, seed, temp, topk, topp):
            """Whole short-prompt admit in ONE dispatch: fresh row cache is
            created on device, prefilled, ring-aligned, written, and the
            first token sampled — host round trips per admit drop from ~5 to
            2 (tokens in, first token out), which directly bounds TTFT under
            concurrent load on a tunneled chip."""
            from ..models.llama import make_cache as _mk

            k1, v1 = _mk(cfg, 1, self.max_seq)
            logits, k1, v1 = fwd(
                params, tokens=tokens, k_cache=k1, v_cache=v1,
                start_pos=jnp.zeros((1,), jnp.int32),
            )
            return _insert_and_sample(
                params, K, V, k1, v1, logits, n, slot, shift, seed, temp, topk, topp
            )

        @partial(jax.jit, donate_argnums=(1, 2))
        def admit_many_fused(params, K, V, tokens, ns, slots, offsets,
                             seeds, temps, topks, topps):
            """Admit m short prompts in ONE dispatch: a single batched
            prefill over [m, bucket] plus per-row insert/sample — concurrent
            arrivals pay one prefill's latency instead of m (the dominant
            term in TTFT p95 under bursty load).

            The transient prefill cache is [m, ..., bucket] long, not
            max_seq (which at m = max_slots would duplicate the whole
            serving cache's HBM). Each bucket-length block lands at
            ``offsets[i]`` = ring_next - n_i so the prefix ends at the ring
            head; the caller guarantees no block wraps (falls back to
            per-request admits otherwise)."""
            from ..models.llama import make_cache as _mk

            m, bucket = tokens.shape
            km, vm = _mk(cfg, m, bucket)
            logits, km, vm = fwd(
                params, tokens=tokens, k_cache=km, v_cache=vm,
                start_pos=jnp.zeros((m,), jnp.int32),
            )
            zero = jnp.zeros((), jnp.int32)

            def body(carry, i):
                K, V = carry
                k1 = jax.lax.dynamic_slice_in_dim(km, i, 1, axis=0)
                v1 = jax.lax.dynamic_slice_in_dim(vm, i, 1, axis=0)
                K = jax.lax.dynamic_update_slice(
                    K, k1, (slots[i], zero, zero, offsets[i], zero)
                )
                V = jax.lax.dynamic_update_slice(
                    V, v1, (slots[i], zero, zero, offsets[i], zero)
                )
                return (K, V), None

            (K, V), _ = jax.lax.scan(body, (K, V), jnp.arange(m, dtype=jnp.int32))
            last = jnp.take_along_axis(
                logits, (ns - 1)[:, None, None], axis=1
            )[:, 0]  # [m, vocab]
            firsts = sample_rows(
                last, seeds, jnp.zeros((m,), jnp.int32), temps, topks, topps
            )
            return firsts, K, V

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4))
        def finish_admit(params, K, V, k1, v1, logits, n_idx, slot, shift,
                         seed, temp, topk, topp):
            """Chunked-prefill tail: ring-align + write + sample, one dispatch."""
            return _insert_and_sample(
                params, K, V, k1, v1, logits, n_idx + 1, slot, shift,
                seed, temp, topk, topp,
            )

        max_seq = self.max_seq

        @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(11, 12))
        def decode(params, tok, K, V, pos, ring, seeds, steps, temp, topk, topp,
                   n, window):
            """n decode steps in one dispatch (device-side scan): the host
            sees one transfer in and one [B, n] token readback. ``window``
            (static) bounds attention reads to the live ring prefix while
            the ring has not wrapped — the dominant HBM saving at partial
            cache occupancy (~35% step time at half-full, granite-2b b32)."""

            def body(carry, i):
                tok, K, V = carry
                logits, K, V = fwd(
                    params, tokens=tok[:, None], k_cache=K, v_cache=V,
                    start_pos=pos + i, ring_slot=(ring + i) % max_seq,
                    attn_window=window,
                )
                nxt = sample_rows(logits[:, -1, :], seeds, steps + i, temp, topk, topp)
                return (nxt, K, V), nxt

            (tok, K, V), toks = jax.lax.scan(
                body, (tok, K, V), jnp.arange(n, dtype=jnp.int32)
            )
            return toks.T, K, V  # [B, n]

        self._prefill1 = prefill1
        self._admit_fused = admit_fused
        self._admit_many_fused = admit_many_fused
        self._finish_admit = finish_admit
        self._decode = decode

        self._inbox: _queue.Queue[_Request | None] = _queue.Queue()
        self._slots: list[_Request | None] = [None] * max_slots
        self._thread: threading.Thread | None = None
        self._started = False
        self._stopping = False
        # serializes submit's stopped-check+enqueue against stop's
        # stopping-flag+sentinel so no request can slip into the inbox after
        # the final drain (submit would otherwise hang forever)
        self._submit_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(target=self._run, name="batcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if not self._started or self._stopping:
            return
        with self._submit_lock:
            self._stopping = True
            self._inbox.put(None)
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        # anything enqueued between the owner thread's final drain and here
        self._drain_all("shutdown")

    # -- client API ----------------------------------------------------------

    async def submit(
        self, prompt_ids: list[int], sp: SamplingParams, info: dict | None = None
    ) -> AsyncIterator[int]:
        """Yield generated token ids for one request.

        When ``info`` is given, the batcher's end reason ("stop" / "length" /
        "shutdown") is recorded in ``info["finish_reason"]`` so callers report
        cache-capacity terminations truthfully instead of re-deriving from
        token counts."""
        if not self._started:
            self.start()
        if not prompt_ids:
            return
        if len(prompt_ids) >= self.max_seq:
            raise ValueError(f"prompt of {len(prompt_ids)} tokens >= max_seq {self.max_seq}")
        req = _Request(
            prompt_ids=list(prompt_ids),
            sp=sp,
            loop=asyncio.get_running_loop(),
            out=asyncio.Queue(),
        )
        with self._submit_lock:
            if self._stopping:
                raise RuntimeError("batcher is stopped")
            self._inbox.put(req)
        while True:
            kind, value = await req.out.get()
            if kind == "tok":
                yield value
            elif kind == "end":
                if info is not None:
                    info["finish_reason"] = value
                return
            else:
                raise value

    # -- device loop (owner thread) ------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_seq

    def _run(self) -> None:
        cfg = self.cfg
        B = self.max_slots
        # ring head: the shared cache slot the next decode step writes; rows'
        # validity is "my last pos+1 ring slots", see models.llama.forward
        self._ring_next = 0
        self._ring_wrapped = False  # once True, windowed reads are unsafe
        K, V = make_cache(cfg, B, self.max_seq)
        if self.mesh is not None:
            from ..parallel.sharding import shard_cache

            K, V = shard_cache(K, V, self.mesh)
        tok = jnp.zeros((B,), jnp.int32)
        # per-slot sampling tensors, rebuilt only when membership changes
        temp = jnp.zeros((B,), jnp.float32)
        topk = jnp.zeros((B,), jnp.int32)
        topp = jnp.ones((B,), jnp.float32)
        pos = jnp.zeros((B,), jnp.int32)
        dirty = False

        host_tok = [0] * B
        host_pos = [0] * B
        host_seed = [0] * B

        def active() -> list[int]:
            return [i for i, r in enumerate(self._slots) if r is not None]

        def decode_once() -> None:
            """One decode burst (decode_burst steps) for every active slot."""
            nonlocal K, V, tok, temp, topk, topp, dirty
            act = active()
            if not act:
                return
            if dirty:
                temp = jnp.asarray(
                    [r.sp.temperature if r else 0.0 for r in self._slots], jnp.float32
                )
                topk = jnp.asarray([r.sp.top_k if r else 0 for r in self._slots], jnp.int32)
                topp = jnp.asarray([r.sp.top_p if r else 1.0 for r in self._slots], jnp.float32)
                dirty = False
            # cap the burst so no active row can run past the cache capacity.
            # n is a static jit arg: snap to single steps near capacity
            # instead of counting down through n-1 fresh compiles
            headroom = self.max_seq - 1 - max(host_pos[i] for i in act)
            n = self.decode_burst if headroom >= self.decode_burst else 1
            # until the ring wraps, every live slot index is < ring_next:
            # attention can read just a bucket covering the head (static
            # windows come from self.buckets, so compiles stay bounded)
            window = None
            if not self._ring_wrapped:
                w = self._bucket(self._ring_next + n)
                if w < self.max_seq:
                    window = w
            tok = jnp.asarray(host_tok, jnp.int32)
            pos = jnp.asarray(host_pos, jnp.int32)
            seeds = jnp.asarray(host_seed, jnp.int32)
            steps = jnp.asarray(
                [r.generated if r else 0 for r in self._slots], jnp.int32
            )
            toks, K, V = self._decode(
                self.params, tok, K, V, pos, jnp.int32(self._ring_next),
                seeds, steps, temp, topk, topp, n, window,
            )
            if self._ring_next + n >= self.max_seq:
                self._ring_wrapped = True
            self._ring_next = (self._ring_next + n) % self.max_seq
            ids = np.asarray(toks)  # ONE [B, n] readback per burst
            self.stats.steps += n
            for i in act:
                req = self._slots[i]
                for j in range(n):
                    if req is None:
                        break
                    req.pos += 1
                    host_pos[i] = req.pos
                    host_tok[i] = int(ids[i, j])
                    if not self._deliver(req, int(ids[i, j])):
                        self._slots[i] = None
                        req = None
                        host_tok[i] = 0
                        host_pos[i] = 0
                        dirty = True

        def admit_one(req: _Request) -> None:
            nonlocal K, V, tok, dirty
            slot = self._slots.index(None)
            n = len(req.prompt_ids)
            C = self.prefill_chunk
            sp = req.sp
            seed = sp.seed if sp.seed is not None else random.getrandbits(31)
            samp = (
                jnp.int32(seed), jnp.float32(sp.temperature),
                jnp.int32(sp.top_k), jnp.float32(sp.top_p),
            )
            note_admit(n)
            if n <= C:
                # short prompt: the whole admit is one fused dispatch
                bucket = self._bucket(n)
                tokens = jnp.asarray([req.prompt_ids + [0] * (bucket - n)], jnp.int32)
                shift = jnp.int32((self._ring_next - n) % self.max_seq)
                first, K, V = self._admit_fused(
                    self.params, K, V, tokens, jnp.int32(n), jnp.int32(slot),
                    shift, *samp,
                )
            else:
                # chunked prefill: fixed [1, C] chunks (one compile) with a
                # shared decode step between chunks, so concurrent streams
                # stall at most ~one chunk's latency, not the whole prompt's
                k1, v1 = make_cache(cfg, 1, self.max_seq)
                for start in range(0, n, C):
                    chunk = req.prompt_ids[start : start + C]
                    chunk = chunk + [0] * (C - len(chunk))
                    logits, k1, v1 = self._prefill1(
                        self.params, jnp.asarray([chunk], jnp.int32), k1, v1,
                        jnp.full((1,), start, jnp.int32),
                    )
                    if start + C < n:
                        decode_once()
                last_idx = (n - 1) % C  # within the final chunk's logits
                # shift MUST be computed here, after the chunk loop: the
                # interleaved decode_once() calls advanced the ring head,
                # and the prefix has to end at the CURRENT head for the
                # ring-validity mask to see it
                shift = jnp.int32((self._ring_next - n) % self.max_seq)
                first, K, V = self._finish_admit(
                    self.params, K, V, k1, v1, logits, jnp.int32(last_idx),
                    jnp.int32(slot), shift, *samp,
                )
            first_id = int(first[0])
            req.slot = slot
            req.pos = n
            self._slots[slot] = req
            self.stats.requests += 1
            dirty = True
            host_pos[slot] = n
            host_tok[slot] = first_id
            host_seed[slot] = seed
            if not self._deliver(req, first_id):
                self._slots[slot] = None  # stopped on the very first token

        def note_admit(n: int) -> None:
            """Shared cold-ring / wrap bookkeeping for an admit of length n
            (the ring-validity invariant lives in exactly one place)."""
            if not any(r is not None for r in self._slots):
                self._ring_next = n  # cold ring: the prefix fits below
                self._ring_wrapped = False
            elif self._ring_next < n:
                # the prefix placement wraps to the high slots: windowed
                # reads would miss it from here on
                self._ring_wrapped = True

        def admit_group(reqs: list[_Request], bucket: int) -> bool:
            """Admit m same-bucket short prompts in one fused dispatch.
            Returns False (caller admits individually) when any block would
            wrap around the ring."""
            nonlocal K, V, dirty
            ns = [len(r.prompt_ids) for r in reqs]
            max_n = max(ns)
            note_admit(max_n)
            # every [bucket]-length block [ring_next - n_i, ring_next - n_i
            # + bucket) must lie inside [0, max_seq)
            if (
                self._ring_next < max_n
                or self._ring_next - min(ns) + bucket > self.max_seq
            ):
                return False
            slots: list[int] = []
            try:
                for r in reqs:
                    s = self._slots.index(None)
                    self._slots[s] = r  # reserve so index(None) advances
                    slots.append(s)
                m = len(reqs)
                mpad = 1 << (m - 1).bit_length()  # bound compiles: m in {2,4,8,..}
                idx = list(range(m)) + [0] * (mpad - m)  # pad rows repeat row 0
                seeds = [
                    r.sp.seed if r.sp.seed is not None else random.getrandbits(31)
                    for r in reqs
                ]
                tokens = [
                    reqs[i].prompt_ids + [0] * (bucket - ns[i]) for i in idx
                ]
                firsts, K, V = self._admit_many_fused(
                    self.params, K, V,
                    jnp.asarray(tokens, jnp.int32),
                    jnp.asarray([ns[i] for i in idx], jnp.int32),
                    jnp.asarray([slots[i] for i in idx], jnp.int32),
                    jnp.asarray(
                        [self._ring_next - ns[i] for i in idx], jnp.int32
                    ),
                    jnp.asarray([seeds[i] for i in idx], jnp.int32),
                    jnp.asarray([reqs[i].sp.temperature for i in idx], jnp.float32),
                    jnp.asarray([reqs[i].sp.top_k for i in idx], jnp.int32),
                    jnp.asarray([reqs[i].sp.top_p for i in idx], jnp.float32),
                )
                ids = np.asarray(firsts)
            except BaseException:
                for s in slots:  # release reservations; caller emits the error
                    self._slots[s] = None
                raise
            dirty = True
            self.stats.grouped_admits += len(reqs)
            for j, r in enumerate(reqs):
                s = slots[j]
                r.slot = s
                r.pos = ns[j]
                self.stats.requests += 1
                host_pos[s] = ns[j]
                host_tok[s] = int(ids[j])
                host_seed[s] = seeds[j]
                if not self._deliver(r, int(ids[j])):
                    self._slots[s] = None
                    host_tok[s] = 0
                    host_pos[s] = 0
            return True

        def reset_after_failed_dispatch() -> None:
            """A failed admit/decode dispatch may have consumed the donated
            K/V buffers (e.g. device OOM raised after donation); continuing
            would wedge every subsequent dispatch against invalidated
            buffers (round-2 advisor). Fail the active streams honestly and
            rebuild a fresh cache."""
            nonlocal K, V, dirty
            err = RuntimeError("batcher cache reset after a failed device dispatch")
            for i, r in enumerate(self._slots):
                if r is not None:
                    r.emit("err", err)
                    self._slots[i] = None
                    host_tok[i] = 0
                    host_pos[i] = 0
            self._ring_next = 0
            self._ring_wrapped = False
            dirty = True
            K, V = make_cache(cfg, B, self.max_seq)
            if self.mesh is not None:
                from ..parallel.sharding import shard_cache

                K, V = shard_cache(K, V, self.mesh)

        waitlist: list[_Request] = []
        while True:
            act = active()
            self.stats.peak_active = max(self.stats.peak_active, len(act))
            # intake: block when fully idle, otherwise just drain what's queued
            block = not act and not waitlist
            while True:
                try:
                    item = self._inbox.get(block=block)
                except _queue.Empty:
                    break
                block = False
                if item is None:
                    self._drain_all("shutdown", waitlist)
                    return
                waitlist.append(item)
            # admit waiters: bursts of short same-bucket prompts go through
            # one batched dispatch; long/odd ones admit individually
            while waitlist and None in self._slots:
                free = self._slots.count(None)
                head_bucket = (
                    self._bucket(len(waitlist[0].prompt_ids))
                    if len(waitlist[0].prompt_ids) <= self.prefill_chunk
                    else None
                )
                group: list[_Request] = []
                if head_bucket is not None:
                    while (
                        waitlist
                        and len(group) < free
                        and len(waitlist[0].prompt_ids) <= self.prefill_chunk
                        and self._bucket(len(waitlist[0].prompt_ids)) == head_bucket
                    ):
                        group.append(waitlist.pop(0))
                if len(group) > 1:
                    try:
                        handled = admit_group(group, head_bucket)
                    except Exception as e:  # noqa: BLE001 — surface to callers
                        for req in group:
                            req.emit("err", e)
                        reset_after_failed_dispatch()
                        continue
                    if handled:
                        continue
                    # group placement would wrap the ring: admit one by one
                for req in group or [waitlist.pop(0)]:
                    try:
                        admit_one(req)
                    except Exception as e:  # noqa: BLE001 — surface to the caller
                        req.emit("err", e)
                        reset_after_failed_dispatch()
            try:
                decode_once()
            except Exception:  # noqa: BLE001 — K/V were donated; must reset
                reset_after_failed_dispatch()

    def _deliver(self, req: _Request, tok_id: int) -> bool:
        """Push one token; returns False when the request just finished."""
        if tok_id in req.sp.stop_ids:
            req.emit("end", "stop")
            return False
        req.generated += 1
        self.stats.tokens += 1
        req.emit("tok", tok_id)
        if req.generated >= req.sp.max_tokens or req.pos + 1 >= self.max_seq:
            req.emit("end", "length")
            return False
        return True

    def _drain_all(self, reason: str, waitlist: list[_Request] = ()) -> None:
        for req in waitlist:
            req.emit("end", reason)
        for i, req in enumerate(self._slots):
            if req is not None:
                req.emit("end", reason)
                self._slots[i] = None
        while True:
            try:
                req = self._inbox.get_nowait()
            except _queue.Empty:
                return
            if req is not None:
                req.emit("end", reason)

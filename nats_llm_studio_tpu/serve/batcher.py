"""Continuous batcher: concurrent requests share one fixed-width decode step.

SURVEY.md §7 puts this on the critical perf path (hard part #5): single-stream
decode is HBM-bound on reading the weights once *per token*; batching B
requests reads them once per B tokens. Design:

* one decode program compiled at a fixed ``[B, 1]`` batch width (no shape
  churn); empty slots run masked (token 0, pos 0, greedy) and are ignored
* requests prefill into a single-row cache (bucketed lengths) and are
  scattered into the shared ``[B, L, Hkv, S, D]`` cache at their slot index —
  joining and leaving never recompiles the decode step
* one dedicated owner thread drives the device (the decode loop is the one
  shared-mutable structure — SURVEY.md §5); asyncio callers talk to it
  through thread-safe queues
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import queue as _queue
import random
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import AsyncIterator

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.generator import SamplingParams, default_buckets
from ..models.config import ModelConfig
from ..models.llama import forward, forward_decode_paged, make_cache
from ..engine.sampling import sample_rows, spec_accept_rows
from ..obs import LogHistogram, Trace
from ..obs import emit as obs_emit
from ..obs.roofline import (
    SPEC_PROGRAMS,
    WASTE_CATEGORIES,
    RollingUtilization,
    classify_program,
    dispatch_shape_key,
    efficiency_enabled,
    extract_dispatch_cost,
    program_base,
)
from ..transport import faults as _faults
from ..ops.kvcache import (
    KVQ,
    is_quantized,
    kv_copy_slice,
    kv_gather_block,
    kv_pool_copy_block,
    kv_pool_gather_view,
    kv_pool_read_blocks,
    kv_pool_scatter_view,
    kv_pool_write_row,
    kv_pool_zeros,
    kv_roll_s,
    kv_slice,
)
from .block_pool import BlockPool
from .brownout import LEVEL_NAMES, SHED_ONLY, BrownoutConfig, BrownoutController
from .prefix_cache import PrefixCache
from .qos import (
    ANON_TENANT,
    DEFAULT_PRIORITY,
    DrrScheduler,
    TenantStats,
    class_rank,
    class_weight,
)
from .spec import SpecConfig, SpecSlot, make_slot

log = logging.getLogger(__name__)

# placeholder occupying a slot that a batched chunked admit has reserved but
# not yet written: decode steps during the chunk loop must neither deliver
# tokens for it nor let another admit claim the slot
_RESERVED = object()

# how many top-logprob (id, logprob) pairs the ext decode programs read back
# per step; OpenAI caps top_logprobs requests well below this
LOGPROBS_K = 8

# forward-bearing programs that record under a "_moe" name suffix when the
# model runs capacity-factor routed experts (roofline.program_family) —
# sampling/bookkeeping programs (finish_admit, select_end, pool copies)
# never touch the FFN and keep their plain names
_MOE_TAGGED_PROGRAMS = frozenset({
    "prefill1", "prefill_full", "prefill_chunk_group",
    "admit_fused", "admit_many_fused",
    "admit_fused_paged", "admit_many_fused_paged",
    "decode", "decode_pos", "decode_pos_ext",
    "decode_pos_paged", "decode_pos_paged_ext",
    "decode_pallas", "decode_pallas_ext",
    "spec_verify", "spec_verify_paged", "spec_verify_pallas",
})


class BatcherStopped(RuntimeError):
    """Submit raced a shutdown (drain, or idle-eviction by the registry's
    HBM admission): the request was never queued. Callers map this to a
    retry-on-another-worker error envelope, same as a shed."""


class BatcherOverloaded(RuntimeError):
    """The admit queue is past its configured depth/age bound. Raised (or
    emitted) instead of queueing silently so NATS queue-group peers can
    absorb the overflow — a worker that hoards requests defeats the bus's
    load balancing (/root/reference/README.md:478-484). The r4 bench
    measured a silent 38.6 s p95 admit delay without this."""


class _PoolExhausted(BatcherOverloaded):
    """The paged-KV block pool ran dry (after reclaiming unpinned prefix
    cache blocks). Raised BEFORE any device dispatch touches the donated
    pool arrays, so the owner loop sheds just the one request instead of
    resetting the whole cache."""


class _ControlOp:
    """An owner-thread errand riding the request inbox.

    Disaggregated serving needs to read (export) and write (import) the
    paged KV pool and prefix cache, but those live as ``_run()`` locals
    owned by the batcher thread — the inbox is the only thread-safe way
    in. A control op is executed inline at intake (it never occupies a
    slot and never enters the waitlist); the submitting thread blocks on
    ``done`` and reads ``result``/``error``."""

    __slots__ = ("kind", "args", "done", "result", "error", "cancelled")

    def __init__(self, kind: str, args: dict):
        self.kind = kind  # "export" | "import" | "suspend_harvest"
        self.args = args
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        # set by a timed-out submitter: the owner skips the work and the
        # (already-gone) caller never reads the result
        self.cancelled = False

    def finish(self, result=None, error: BaseException | None = None) -> None:
        self.result = result
        self.error = error
        self.done.set()

    def emit(self, kind: str, value) -> None:
        """Duck-typed with _Request so the shutdown/crash drain paths
        (_drain_all, _fail_inflight_retryable) fail a queued control op
        instead of stranding its waiter until timeout."""
        if kind == "err":
            self.finish(error=value)
        else:
            self.finish(error=BatcherStopped(
                f"batcher stopped ({value}) before kv {self.kind} ran; "
                f"retry on another worker"
            ))


class _Suspended:
    """A slot parked on the host tier (swap-don't-shed). Holds the host
    copies of the slot's KV blocks plus everything resume needs to be
    bit-identical under greedy: position, rng step/seed, the spec-decode
    n-gram state (by reference — its history already includes every
    delivered token), and the request itself (whose ``emitted`` tail
    re-seeds the device carry token). Owner thread only."""

    __slots__ = ("req", "k", "v", "n_blocks", "min_blocks", "pos", "steps",
                 "seed", "spec", "t_suspend", "reason")

    def __init__(self, req, k, v, n_blocks, pos, steps, seed, spec,
                 t_suspend, reason, min_blocks=None):
        self.req = req
        self.k = k
        self.v = v
        self.n_blocks = n_blocks
        # resume gate: don't re-admit until this many blocks are free. For
        # a slot parked by a FAILED mid-decode growth this covers n_blocks
        # plus the growth it could not take — resuming at exactly n_blocks
        # would re-fail the same growth and park again, a livelock that
        # starves the slots the parking was meant to unblock.
        self.min_blocks = n_blocks if min_blocks is None else min_blocks
        self.pos = pos
        self.steps = steps
        self.seed = seed
        self.spec = spec
        self.t_suspend = t_suspend
        self.reason = reason


@dataclass
class _Request:
    prompt_ids: list[int]
    sp: SamplingParams
    loop: asyncio.AbstractEventLoop
    out: asyncio.Queue  # (kind, value): ("tok", id) | ("end", reason) | ("err", exc)
    slot: int = -1
    pos: int = 0
    generated: int = 0
    t_enq: float = 0.0  # monotonic enqueue time (queue-delay metric)
    t_admit: float = 0.0  # monotonic admit-dispatch time (prefill metric)
    trace: Trace | None = None  # per-request span record (obs/trace.py)
    # set (from any thread; plain bool is GIL-safe) when the consumer is
    # gone — the owner thread frees the slot/queue entry at its next check
    # instead of decoding to max_tokens for nobody (VERDICT r4 missing #1)
    cancelled: bool = False
    # absolute monotonic deadline propagated from the client's budget
    # (None = no deadline); past it the request is shed before prefill or
    # cooperatively aborted mid-decode instead of burning device time for
    # a caller that has already given up
    deadline: float | None = None
    # distinguishes a deadline abort from a consumer-gone cancel when the
    # owner thread frees the slot (cause tag in cancel_causes/prometheus)
    deadline_hit: bool = False
    # -- constrained decoding / logprobs (the "ext" regime) ---------------
    # TokenDFA (serve/constrain.py) when response_format demands schema-
    # constrained output; cstate is the current DFA state, advanced on the
    # host at readback (the device only sees the per-state vocab mask)
    constrain: object | None = None
    cstate: int = 0
    want_logprobs: bool = False
    top_logprobs: int = 0
    # the rewind trick: an ext admit suppresses the fused-admit first token,
    # steps pos back one, and re-processes prompt[-1] through the masked ext
    # program — so token 0 obeys the mask and carries logprobs like every
    # later token, without a separate masked-prefill program family
    rewound: bool = False
    # -- device-time ledger (obs/roofline.py) -----------------------------
    # dispatch ms accrued on behalf of this request, split by program class;
    # finalized into BatcherStats.device_ms under an outcome category when
    # the request leaves (served / cancelled / deadline_abort / shed / ...)
    dev_prefill_ms: float = 0.0
    dev_decode_ms: float = 0.0
    # this request's share of its most recent spec-verify dispatch, so the
    # readback can move the rejected-draft fraction to "spec_rejected"
    dev_spec_ms: float = 0.0
    # outcome tag for prefill work that only exists because an upstream step
    # failed (disaggregated KV pull fell back to a local re-prefill): the
    # prefill share of a served request lands here instead of "served"
    waste_tag: str | None = None
    # token ids actually delivered to the consumer, in order. prompt_ids +
    # emitted is the slot's exact token history; slot suspend relies on it
    # (resume re-seeds the device carry token from the tail, and suspend
    # refuses a slot whose history length disagrees with its position)
    emitted: list = field(default_factory=list)
    # -- multi-tenant QoS (serve/qos.py) ----------------------------------
    # identity resolved by the gateway's API-key auth and carried on the
    # X-Tenant/X-Priority bus headers; raw-NATS callers default to the
    # anonymous standard tenant, so pre-QoS traffic schedules exactly as
    # before. ``weight`` overrides the class weight in DRR when the key
    # spec sets one (0 = derive from class).
    tenant: str = ANON_TENANT
    priority: str = DEFAULT_PRIORITY
    weight: float = 0.0

    @property
    def rank(self) -> int:
        """0 = batch (shed/preempt first) .. 2 = premium (shed last)."""
        return class_rank(self.priority)

    @property
    def drr_weight(self) -> float:
        return self.weight if self.weight > 0 else float(class_weight(self.priority))

    @property
    def is_ext(self) -> bool:
        return self.constrain is not None or self.want_logprobs

    def emit(self, kind: str, value) -> None:
        self.loop.call_soon_threadsafe(self.out.put_nowait, (kind, value))


@dataclass
class BatcherStats:
    requests: int = 0
    tokens: int = 0
    steps: int = 0
    peak_active: int = 0
    grouped_admits: int = 0  # requests admitted via the batched-admit path
    chunked_group_admits: int = 0  # long prompts admitted via batched chunking
    ring_compactions: int = 0  # wrapped ring re-rolled to restore windows
    cancelled: int = 0  # consumer-gone requests whose slot/queue entry was freed
    shed: int = 0  # requests rejected at the depth bound or dropped at the age bound
    # in-flight requests failed with a retryable envelope by a pump-loop
    # crash (the supervisor's restart path harvests this into the registry
    # accumulator behind lmstudio_inflight_failed_retryable_total)
    inflight_failed_retryable: int = 0
    # first-seen (program, static-args) combos on the decode/verify paths —
    # each one is a fresh XLA compile (the pow2 window ladder is the
    # classic source; the Pallas decode kernel's whole-table grid keeps
    # this flat). Exposed as lmstudio_decode_recompiles_total.
    decode_recompiles: int = 0
    # speculative decoding (serve/spec.py): drafted = n-gram tokens sent to
    # verify dispatches, accepted = drafts the model's own distribution kept
    spec_verifies: int = 0  # width-(k+1) verify dispatches
    spec_drafted: int = 0
    spec_accepted: int = 0
    # bounded log-bucket histograms (obs/histogram.py): O(1) record on the
    # batcher owner thread, O(buckets) snapshot from the asyncio metrics
    # handlers, fixed memory for the life of the worker. Phase deltas come
    # from snapshot subtraction (bench.py), not index bookkeeping.
    admit_delay_ms: LogHistogram = field(default_factory=LogHistogram)
    ttft_ms: LogHistogram = field(default_factory=LogHistogram)  # enqueue -> first token
    prefill_ms: LogHistogram = field(default_factory=LogHistogram)  # admit -> first token
    decode_step_ms: LogHistogram = field(default_factory=LogHistogram)  # per burst step
    tokens_per_step: LogHistogram = field(
        default_factory=lambda: LogHistogram(lo=1.0, hi=4096.0, growth=1.25)
    )
    # per-verify fraction of drafted tokens accepted; 0 is clamped to the
    # bottom bucket (LogHistogram needs lo > 0)
    spec_accept_rate: LogHistogram = field(
        default_factory=lambda: LogHistogram(lo=0.01, hi=1.0, growth=1.25)
    )
    # "depth" | "age" | "deadline" | "brownout" -> count
    shed_causes: dict = field(default_factory=dict)
    cancel_causes: dict = field(default_factory=dict)  # where the cancel landed
    # per-program device telemetry: one histogram per jit-grid program
    # (prefill1, decode_pos_paged, spec_verify, ...) of host dispatch wall
    # ms, plus tokens moved per dispatch. decode_step_ms stays the
    # readback-inclusive stream-experienced number; these decompose WHERE
    # the device time goes (a first call's entry includes its XLA compile,
    # which is exactly the spike worth seeing). Keys materialize on first
    # record; exposition copies the dict under the lock.
    program_ms: dict = field(default_factory=dict)  # name -> LogHistogram
    program_tokens: dict = field(default_factory=dict)  # name -> LogHistogram
    # -- compute-efficiency plane (obs/roofline.py) -----------------------
    # cumulative per-program flops / bytes-accessed from XLA cost analysis;
    # keys materialize on the first costed dispatch of each program
    program_flops: dict = field(default_factory=dict)  # name -> float
    program_bytes: dict = field(default_factory=dict)  # name -> float
    # device-time ledger: outcome category -> accumulated dispatch ms, and
    # tokens delivered (tokens accrue only under "served")
    device_ms: dict = field(default_factory=dict)
    device_tokens: dict = field(default_factory=dict)
    # exact sum of every dispatch's ms (the same samples program_ms buckets
    # approximately): reconciliation denominator for the ledger — the bench
    # `efficiency` phase asserts category sums match this within 10%
    dispatch_ms_total: float = 0.0
    # rolling flops/bytes windows per program class -> MFU/MBU gauges
    util_prefill: RollingUtilization = field(default_factory=RollingUtilization)
    util_decode: RollingUtilization = field(default_factory=RollingUtilization)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def record_program(self, name: str, ms: float, tokens: float | None = None) -> None:
        """One jit-grid dispatch of ``name`` took ``ms`` (host wall: on an
        async backend this is dispatch time — execution may still be in
        flight — but a cold call's trace+compile is fully in here)."""
        self.dispatch_ms_total += ms  # owner-thread single writer
        h = self.program_ms.get(name)
        if h is None:
            with self._lock:
                h = self.program_ms.setdefault(name, LogHistogram())
        h.record(ms)
        if tokens is not None and tokens > 0:
            ht = self.program_tokens.get(name)
            if ht is None:
                with self._lock:
                    ht = self.program_tokens.setdefault(
                        name, LogHistogram(lo=1.0, hi=1e6, growth=1.25)
                    )
            ht.record(float(tokens))

    def program_histograms(self) -> dict[str, LogHistogram]:
        with self._lock:
            return dict(self.program_ms)

    def program_token_histograms(self) -> dict[str, LogHistogram]:
        with self._lock:
            return dict(self.program_tokens)

    def record_dispatch_cost(self, name: str, cost: tuple | None) -> None:
        """Fold one dispatch's (flops, bytes) into the per-program totals and
        the rolling roofline windows. ``cost`` is None when XLA cost analysis
        was unavailable for the program — the dispatch simply isn't costed."""
        if not cost:
            return
        fl, by = cost
        with self._lock:
            self.program_flops[name] = self.program_flops.get(name, 0.0) + fl
            self.program_bytes[name] = self.program_bytes.get(name, 0.0) + by
        cls = classify_program(name)
        if cls == "prefill":
            self.util_prefill.add(fl, by)
        elif cls == "decode":
            self.util_decode.add(fl, by)

    def attribute_device_time(self, category: str, ms: float, tokens: int = 0) -> None:
        """Ledger entry: ``ms`` of device dispatch time resolved to an outcome
        ``category`` (roofline.WASTE_CATEGORIES, plus "failed" for crash
        paths). Tokens count only toward goodput ("served")."""
        with self._lock:
            self.device_ms[category] = self.device_ms.get(category, 0.0) + ms
            if tokens:
                self.device_tokens[category] = self.device_tokens.get(category, 0) + tokens

    def device_time_snapshot(self) -> dict:
        """{"ms": {category: ms}, "tokens": {category: n}} — the standard
        categories are always present (zero-filled) so exposition and the
        cluster rollup see stable families."""
        with self._lock:
            ms = {c: 0.0 for c in WASTE_CATEGORIES}
            ms.update(self.device_ms)
            tok = {c: 0 for c in WASTE_CATEGORIES}
            tok.update(self.device_tokens)
        return {"ms": ms, "tokens": tok}

    def goodput_tokens_per_device_s(self) -> float:
        """Served tokens per second of TOTAL attributed device time — waste
        in any category drags this below raw decode throughput."""
        with self._lock:
            total_ms = sum(self.device_ms.values())
            served = self.device_tokens.get("served", 0)
        return served / (total_ms / 1e3) if total_ms > 0 else 0.0

    def cost_counters(self) -> tuple[dict, dict]:
        """(program_flops, program_bytes) copies for exposition."""
        with self._lock:
            return dict(self.program_flops), dict(self.program_bytes)

    def utilization(self, peaks: tuple | None = None) -> dict:
        """Rolling MFU/MBU per program class against chip peaks."""
        pf_mfu, pf_mbu = self.util_prefill.utilization(peaks)
        dc_mfu, dc_mbu = self.util_decode.utilization(peaks)
        return {
            "prefill": {"mfu": pf_mfu, "mbu": pf_mbu},
            "decode": {"mfu": dc_mfu, "mbu": dc_mbu},
        }

    def record_admit_delay(self, ms: float) -> None:
        """Queue delay (enqueue -> admit DISPATCH), ms — the scheduling
        half of TTFT the worker controls (the other half is the prefill
        itself, tracked separately in prefill_ms)."""
        self.admit_delay_ms.record(ms)

    def record_shed(self, cause: str = "depth", waited_ms: float | None = None) -> None:
        """Sheds happen on TWO threads (depth bound: submitter's event
        loop; age bound: batcher owner) — a bare ``+= 1`` can lose counts
        between them, and the bench asserts exact shed totals."""
        with self._lock:
            self.shed += 1
            self.shed_causes[cause] = self.shed_causes.get(cause, 0) + 1
        ev = {"cause": cause}
        if waited_ms is not None:
            ev["waited_ms"] = round(waited_ms, 1)
        obs_emit("shed", **ev)

    def record_cancel(self, where: str = "active") -> None:
        """Consumer-gone request reclaimed; all sites run on the owner
        thread, but the event ring wants the *where* for diagnosis."""
        self.cancelled += 1
        self.cancel_causes[where] = self.cancel_causes.get(where, 0) + 1
        obs_emit("cancel", where=where)

    def shed_cause_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self.shed_causes)

    def histograms(self) -> dict[str, LogHistogram]:
        """Name -> histogram, for Prometheus exposition (serve/worker.py)."""
        return {
            "admit_queue_delay_ms": self.admit_delay_ms,
            "ttft_ms": self.ttft_ms,
            "prefill_ms": self.prefill_ms,
            "decode_step_ms": self.decode_step_ms,
            "tokens_per_step": self.tokens_per_step,
            "spec_accept_rate": self.spec_accept_rate,
        }

    def spec_counters(self) -> dict[str, int]:
        """Speculative-decoding counters, exposed by serve/worker.py as the
        dedicated lmstudio_spec_*_total metric families."""
        return {
            "verifies": self.spec_verifies,
            "drafted": self.spec_drafted,
            "accepted": self.spec_accepted,
        }

    def counters(self) -> dict[str, int]:
        """Monotonic counters, for Prometheus exposition."""
        return {
            "requests": self.requests,
            "tokens": self.tokens,
            "decode_steps": self.steps,
            "grouped_admits": self.grouped_admits,
            "chunked_group_admits": self.chunked_group_admits,
            "ring_compactions": self.ring_compactions,
            "cancelled": self.cancelled,
            "shed": self.shed,
            "inflight_failed_retryable": self.inflight_failed_retryable,
            "decode_recompiles": self.decode_recompiles,
        }

    def snapshot(self) -> dict:
        adm = self.admit_delay_ms.snapshot()
        ttft = self.ttft_ms.snapshot()
        pre = self.prefill_ms.snapshot()
        dec = self.decode_step_ms.snapshot()
        with self._lock:
            shed_causes = dict(self.shed_causes)
        return {
            "requests": self.requests,
            "tokens": self.tokens,
            "decode_steps": self.steps,
            "peak_active_slots": self.peak_active,
            "grouped_admits": self.grouped_admits,
            "chunked_group_admits": self.chunked_group_admits,
            "ring_compactions": self.ring_compactions,
            "cancelled": self.cancelled,
            "shed": self.shed,
            "inflight_failed_retryable": self.inflight_failed_retryable,
            "decode_recompiles": self.decode_recompiles,
            "spec_verifies": self.spec_verifies,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "shed_causes": shed_causes,
            "tokens_per_step_avg": round(self.tokens / self.steps, 2) if self.steps else 0.0,
            "admit_queue_delay_p50_ms": round(adm.percentile(0.5), 1),
            "admit_queue_delay_p95_ms": round(adm.percentile(0.95), 1),
            "admit_queue_delay_max_ms": round(adm.vmax or 0.0, 1),
            "ttft_p50_ms": round(ttft.percentile(0.5), 1),
            "ttft_p95_ms": round(ttft.percentile(0.95), 1),
            "prefill_p50_ms": round(pre.percentile(0.5), 1),
            "prefill_p95_ms": round(pre.percentile(0.95), 1),
            "decode_step_p50_ms": round(dec.percentile(0.5), 1),
            "decode_step_p95_ms": round(dec.percentile(0.95), 1),
            "goodput_tokens_per_device_s": round(self.goodput_tokens_per_device_s(), 2),
            "device_ms": {
                k: round(v, 1) for k, v in self.device_time_snapshot()["ms"].items()
            },
        }


class ContinuousBatcher:
    """Owns the device loop for one loaded model."""

    def __init__(
        self,
        params,
        cfg: ModelConfig,
        max_slots: int = 8,
        max_seq_len: int | None = None,
        buckets: list[int] | None = None,
        mesh=None,
        prefill_chunk: int = 256,
        decode_burst: int = 8,
        admit_coalesce_ms: float = 3.0,
        max_group_admit: int = 8,
        max_group_long: int = 4,
        max_queue: int = 0,
        max_queue_age_ms: float = 0.0,
        prefix_cache_blocks: int = 0,
        spec_decode_k: int = 0,
        spec_max_active: int = 4,
        brownout: BrownoutConfig | None = None,
        hbm_headroom_fn=None,
        deadline_min_tokens: int = 1,
        paged: bool | None = None,
        kv_block_tokens: int = 16,
        kv_pool_blocks: int = 0,
        recorder=None,
        kv_tiers=None,
        kv_suspend: bool | None = None,
        qos_quantum_tokens: int = 256,
        qos_preempt: bool | None = None,
    ):
        from ..models.llama import ensure_lm_head

        self.params = ensure_lm_head(params)
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_seq = min(max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
        self.buckets = buckets or default_buckets(self.max_seq)
        self.mesh = mesh
        # prompts longer than this prefill in chunks, with one shared decode
        # step interleaved between chunks so active streams' inter-token gap
        # is bounded by ~one chunk's prefill, not the whole prompt's
        # (VERDICT round-1 weak #4: head-of-line blocking on admit).
        # The chunk must divide max_seq: the final zero-padded [1, C] chunk
        # would otherwise write past the cache end, where dynamic-update-
        # slice clamps the start and corrupts earlier prefix slots.
        self.prefill_chunk = max(8, prefill_chunk)
        while self.max_seq % self.prefill_chunk and self.prefill_chunk > 8:
            self.prefill_chunk //= 2
        if self.max_seq % self.prefill_chunk:
            raise ValueError(
                f"max_seq_len={self.max_seq} must be divisible by a prefill "
                f"chunk >= 8; use a power-of-two max_seq_len"
            )
        # decode runs ``decode_burst`` steps per dispatch (one on-device
        # lax.scan): host<->device round trips dominate per-step cost on a
        # tunneled chip (~50-100 ms each vs a ~3 ms device step), so tokens
        # stream in bursts of N. 1 = token-by-token.
        self.decode_burst = max(1, decode_burst)
        # how long an idle worker waits after the FIRST arrival for more
        # requests before admitting: a few ms turns a concurrent burst into
        # one batched admit dispatch instead of 1 + (m-1)
        self.admit_coalesce_ms = max(0.0, admit_coalesce_ms)
        # cap on one batched admit: bounds the set of compiled admit widths
        # (mpad in powers of two up to this) and one admit dispatch's
        # latency. Default 8 favors TTFT at light load; throughput-tuned
        # deployments raise it (a 96-client wave at 32 is 3 pipelined
        # [32, bucket] prefills instead of 12 [8, bucket] — bigger MXU
        # tiles, ~the dominant term in wave ramp time).
        self.max_group_admit = max(1, max_group_admit)
        # cap on one batched CHUNKED admit (long prompts): bounds the
        # [m, L, Hkv, S, D] transient row-cache pair the group prefills
        # into (HBM: m x 2 full-length rows) and the compiled widths.
        # Concurrent long prompts otherwise serialize one full chunked
        # prefill each — B=1 chunks at poor MXU utilization, measured ~4x
        # the wall time of one [4, C]-chunked pass in the r4 bench.
        self.max_group_long = max(1, max_group_long)
        # overload bounds (0 = off). Depth: submit fails fast past this many
        # queued-not-yet-admitted requests. Age: the owner thread sheds
        # waiters older than this at admit time. Either bound turns silent
        # queueing into an immediate BatcherOverloaded the caller can route
        # to a queue-group peer (VERDICT r4 missing #2).
        self.max_queue = max(0, max_queue)
        self.max_queue_age_ms = max(0.0, max_queue_age_ms)
        # paged KV: one refcounted fixed-size-block pool replaces the
        # contiguous per-slot rings — live decode slots, the radix prefix
        # cache, and spec decode's positional layout all read/write through
        # per-slot block tables (vLLM PagedAttention + RadixAttention
        # sharing). Default ON; KV_PAGED=0 keeps the pre-paged contiguous
        # paths byte-for-byte (the equivalence baseline).
        if paged is None:
            paged = os.environ.get("KV_PAGED", "1").strip().lower() not in (
                "0", "false", "off"
            )
        self.paged = bool(paged)
        self._pool: BlockPool | None = None
        if self.paged:
            # block size: the requested tokens-per-block snapped down (pow2
            # halving) until it divides the prefill chunk — cached chunks
            # are then whole blocks, so a prefix-cache hit is a refcount
            # bump with no re-blocking. T | C | max_seq by construction.
            T = max(1, int(kv_block_tokens))
            while T > 1 and self.prefill_chunk % T:
                T //= 2
            self.kv_block_tokens = T
            self.blocks_per_row = self.max_seq // T
            # pool population (usable blocks; +1 for the permanently-
            # referenced null block 0). The default sizes for zero
            # starvation — every slot at max_seq plus the whole prefix
            # cache budget; serving deployments under-provision via
            # KV_POOL_BLOCKS to pack more slots in the same HBM (blocks
            # only materialize per-token, the whole point of paging).
            usable = (
                int(kv_pool_blocks)
                if kv_pool_blocks > 0
                else max_slots * self.blocks_per_row + max(0, prefix_cache_blocks)
            )
            self._pool = BlockPool(usable + 1, T)
        else:
            self.kv_block_tokens = 0
            self.blocks_per_row = 0
        # pow2 window-ladder cap: every distinct (program, window) pair on
        # the XLA decode path is a fresh jit compile. Bounding the ladder to
        # DECODE_LADDER_RUNGS rungs (max_seq halved rung-1 times, floor 8)
        # caps compiles per program; short contexts just read a larger
        # masked window (position masking keeps numerics identical).
        rungs = max(1, int(os.environ.get("DECODE_LADDER_RUNGS", "6")))
        f = max(8, self.max_seq >> (rungs - 1))
        self._win_floor = 1 << max(0, f - 1).bit_length()
        # first-seen static-arg combos per decode-path program (owner thread
        # only) — the proxy behind stats.decode_recompiles
        self._compiled_keys: set[tuple] = set()
        # decode-kernel selection (ops/paged_attention.py): "pallas" streams
        # pool blocks straight through each slot's table inside the
        # attention kernel; "xla" is the gather-view fallback; "auto"
        # (default) picks pallas only where Mosaic can tile the pool layout
        # AND a real TPU backend is attached (off-TPU the kernel runs under
        # the Pallas interpreter — right for equivalence tests, far too
        # slow for serving).
        self.decode_kernel = self._resolve_decode_kernel()
        # automatic prefix KV cache (serve/prefix_cache.py): chunk size IS
        # the (possibly halved) prefill chunk, so every cached block is a
        # boundary the chunked-prefill program can resume from. 0 = off,
        # and the admit paths are then byte-for-byte the uncached ones.
        # Paged mode: capacity is denominated in POOL BLOCKS, nodes hold
        # (epoch, block-id) payloads, and harvest/eviction are refcount
        # bumps/drops on the shared pool instead of block copies.
        if prefix_cache_blocks > 0 and self.paged:
            _pool = self._pool

            def _pc_acquire(payload):
                ep, ids = payload
                if ep == _pool.epoch:
                    _pool.incref(ids)

            def _pc_release(payload):
                ep, ids = payload
                _pool.decref(ids, epoch=ep)

            self.prefix_cache: PrefixCache | None = PrefixCache(
                self.prefill_chunk, prefix_cache_blocks,
                node_blocks=self.prefill_chunk // self.kv_block_tokens,
                acquire_fn=_pc_acquire, free_fn=_pc_release,
            )
        else:
            self.prefix_cache = (
                PrefixCache(self.prefill_chunk, prefix_cache_blocks)
                if prefix_cache_blocks > 0
                else None
            )
        # speculative decoding (serve/spec.py): k > 0 turns it on AND flips
        # the whole cache to POSITIONAL layout (slot = sequence position,
        # the ring_slot=None path of models.llama.forward). Per-slot
        # acceptance counts differ, so the shared-ring invariant ("every
        # row's history ends at one common head") cannot survive a verify;
        # positional layout has no shared head, and a rejected draft needs
        # no KV rollback — stale entries above the accepted length are
        # masked by position and overwritten by that row's next writes.
        # Tradeoff: positional decode writes via a per-row scatter (the
        # serialized-row cost the ring path exists to avoid), which is why
        # spec is worth it at LOW occupancy (the memory-bound regime) and
        # verify dispatches auto-disable above ``spec_max_active`` live
        # slots. 0 keeps the ring hot path byte-for-byte unchanged.
        self.spec_cfg: SpecConfig | None = (
            SpecConfig(k=spec_decode_k, max_active=max(1, spec_max_active))
            if spec_decode_k > 0
            else None
        )
        # adaptive brownout (serve/brownout.py): ticked by the owner thread
        # each main-loop iteration; None = off (every lever stays nominal).
        # ``hbm_headroom_fn`` is injected by the registry (the batcher has
        # no handle on HBM accounting) and returns the free-fraction of the
        # HBM budget, or None when no budget is configured.
        self.brownout: BrownoutController | None = (
            BrownoutController(brownout) if brownout is not None else None
        )
        self.hbm_headroom_fn = hbm_headroom_fn
        # deadline feasibility floor: a request that cannot produce at least
        # min(deadline_min_tokens, its max_tokens) before its deadline —
        # estimated from the live prefill/decode rate EWMAs — is shed before
        # prefill instead of admitted to be aborted mid-stream
        self.deadline_min_tokens = max(1, deadline_min_tokens)
        # live rate EWMAs (owner thread only): prefill tokens/s measured at
        # first token, decode seconds/token measured per burst readback.
        # 0.0 = no sample yet (feasibility then only sheds the already-expired)
        self._prefill_rate_ewma = 0.0
        self._decode_spt_ewma = 0.0
        # per-verify draft acceptance EWMA (owner thread only) — the
        # recorder frame's one-number answer to "is spec still paying?"
        self._spec_accept_ewma = 0.0
        self.stats = BatcherStats()
        # compute-efficiency plane (obs/roofline.py): per-dispatch cost
        # extraction + the device-time ledger. EFFICIENCY=0 disables both
        # (the _timed wrapper then degrades to the plain timer).
        self._efficiency = efficiency_enabled()
        # the requests the in-progress dispatch works for (owner thread
        # only); _timed splits each dispatch's ms across this context, and
        # dispatches with no context are ledgered as "other" (warmup,
        # compaction, CoW copies)
        self._charge_ctx: tuple | None = None
        # flight recorder (obs/recorder.py): the owner loop samples one
        # frame per interval and the anomaly paths (crash, pool
        # exhaustion, SHED_ONLY entry) dump through it; None = off
        self.recorder = recorder
        # hierarchical KV tiers (serve/kv_tiers.py KVTierManager): host-RAM
        # spill + Object Store behind the paged prefix cache. Only
        # meaningful with paged KV AND a radix cache — the cache is both
        # the demotion source (evicted-not-discarded chunks) and the
        # promotion target. The manager holds host/Object-Store bytes only;
        # every device transfer stays on the owner thread.
        self.kv_tiers = (
            kv_tiers if (self.paged and self.prefix_cache is not None) else None
        )
        # slot suspend/resume (swap-don't-shed): on pool exhaustion or the
        # SHED_ONLY edge a victim slot's blocks + full resume state move to
        # host RAM and the slot resumes later, bit-identical under greedy.
        # None → KV_SUSPEND env; "0" is the kill switch that restores the
        # pre-tier shed-on-exhaustion behavior exactly.
        if kv_suspend is None:
            kv_suspend = os.environ.get("KV_SUSPEND", "1").strip().lower() not in (
                "0", "false", "off"
            )
        self.kv_suspend = bool(kv_suspend) and self.paged
        # suspended-slot records (owner thread mutates; len() is read
        # cross-thread for metrics/adverts — list ref swap + len are
        # GIL-safe) and lifetime suspend counters, kept off BatcherStats so
        # the stats snapshot shape stays a stable contract
        self._suspended: list = []
        self._suspend_stats = {
            "suspended_total": 0,
            "resumed_total": 0,
            "suspend_failures": 0,
            "suspended_deadline_expired": 0,
        }
        # multi-tenant QoS (serve/qos.py): admission is deficit round-robin
        # over per-tenant queues weighted by priority class — the owner loop
        # re-orders the waitlist through the scheduler before each admission
        # pass (single-tenant traffic degenerates to exact FIFO), brownout
        # sheds strictly batch < standard < premium at _enqueue, and with
        # ``qos_preempt`` a higher-class admit that finds the pool full
        # parks the lowest strictly-lower-class victim via the suspend path
        # (resumed bit-identically when pressure clears) before ever
        # shedding. QOS_PREEMPT=0 restores class-blind victim selection;
        # preemption rides the suspend machinery, so it needs paged KV.
        if qos_preempt is None:
            qos_preempt = os.environ.get("QOS_PREEMPT", "1").strip().lower() not in (
                "0", "false", "off"
            )
        self.qos_preempt = bool(qos_preempt) and self.kv_suspend
        self._drr = DrrScheduler(quantum=max(1, int(qos_quantum_tokens)))
        self.tenant_stats = TenantStats()
        # owner-maintained snapshot of the live slots for debug_snapshot()
        # (the real tables/host_pos are _run locals): slot -> {pos,
        # generated, blocks, ...}. Replaced wholesale each loop iteration
        # and entries popped at finish_slot, so an idle (inbox-blocked)
        # owner never leaves freed slots visible. Read from any thread —
        # plain dict ref swap is atomic under the GIL.
        self._slot_view: dict[int, dict] = {}

        fwd = partial(forward, cfg=cfg, mesh=mesh)

        # -- explicit cache shardings (tensor-parallel serving) --------------
        # With a mesh, the serving K/V ring arrives in every jit already
        # sharded (heads on tp — shard_cache in _run), but values *created
        # inside* a jit (the fused admits' fresh row caches) and the cache
        # write boundaries would otherwise be left to the partitioner's
        # guess — worst case a replicated transient per chip plus an
        # all-gather at the serving-cache write. ``pin_cache``/``pin_row``
        # pin the KV head axis to tp at creation and at every read/write
        # boundary; the constraint matches the donated inputs' shardings
        # exactly, so buffer donation survives. Both are identity with no
        # mesh — the tp=1 path compiles byte-for-byte unchanged.
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel.sharding import (
                cache_spec,
                row_cache_spec,
                validate_mesh_for_config,
            )

            validate_mesh_for_config(mesh, cfg)
            cache_sh = NamedSharding(mesh, cache_spec(mesh, cfg))
            row_sh = NamedSharding(mesh, row_cache_spec(mesh, cfg))

            def _pin_with(c, sh):
                if is_quantized(c):
                    s_sh = NamedSharding(mesh, PartitionSpec(*list(sh.spec)[:-1]))
                    return KVQ(
                        q=jax.lax.with_sharding_constraint(c.q, sh),
                        s=jax.lax.with_sharding_constraint(c.s, s_sh),
                    )
                return jax.lax.with_sharding_constraint(c, sh)

            def pin_cache(c):
                return _pin_with(c, cache_sh)

            def pin_row(c):
                return _pin_with(c, row_sh)
        else:

            def pin_cache(c):
                return c

            pin_row = pin_cache

        @partial(jax.jit, static_argnums=(6,))
        def prefill1(params, tokens, k1, v1, start, last_pos, window):
            # lm_head at one position only ([1,1,vocab]); non-final chunks
            # ignore the logits, the final chunk's last_pos is the prompt end.
            # uniform_start: all rows share `start`, so chunk continuations
            # ride the cache-backed flash kernel, not the dense fallback.
            # window (static, bucketed >= start + C): each chunk reads only
            # the live cache prefix instead of the full max_seq slab — the
            # r4 bench measured 16k chunked prefill at 43% of the
            # single-dispatch kernel from the O(T^2) full-window reads
            # (and KVQ dequant transients) this removes.
            logits, k1, v1 = fwd(
                params, tokens=tokens, k_cache=pin_row(k1), v_cache=pin_row(v1),
                start_pos=start,
                logit_positions=last_pos, uniform_start=True, attn_window=window,
            )
            return logits, pin_row(k1), pin_row(v1)

        def _insert_and_sample(params, K, V, tok, k1, v1, logits, slot, shift,
                               seed, temp, topk, topp):
            """Roll the prefilled row onto the ring, write it, sample token 0,
            and write it into the device-resident next-token carry ``tok``.

            The prefix (tokens at [0, n) of k1) must land on the ring slots
            ending at the current ring head, so the whole row is rolled by
            ``shift`` = (ring_next - n) mod S before the row write — decode
            validity is "the start_pos+1 most recent ring slots" and relies
            on every row's tokens being slot-contiguous there.
            """
            zero = jnp.zeros((), jnp.int32)
            k1 = kv_roll_s(k1, shift, s_axis=3)
            v1 = kv_roll_s(v1, shift, s_axis=3)
            K = pin_cache(kv_copy_slice(K, k1, (slot, zero, zero, zero, zero)))
            V = pin_cache(kv_copy_slice(V, v1, (slot, zero, zero, zero, zero)))
            first = sample_rows(
                logits[:, 0], seed[None], jnp.zeros((1,), jnp.int32),
                temp[None], topk[None], topp[None],
            )
            tok = jax.lax.dynamic_update_slice(tok, first, (slot,))
            return first, K, V, tok

        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def admit_fused(params, K, V, tok, tokens, n, slot, shift, seed, temp,
                        topk, topp):
            """Whole short-prompt admit in ONE dispatch: fresh row cache is
            created on device, prefilled, ring-aligned, written, and the
            first token sampled — host round trips per admit drop from ~5 to
            2 (tokens in, first token out), which directly bounds TTFT under
            concurrent load on a tunneled chip."""
            from ..models.llama import make_cache as _mk

            k1, v1 = _mk(cfg, 1, self.max_seq)
            k1, v1 = pin_row(k1), pin_row(v1)
            # logit_positions: lm_head at the prompt end only — skips
            # bucket× the lm_head FLOPs and the [1, bucket, vocab] f32
            logits, k1, v1 = fwd(
                params, tokens=tokens, k_cache=k1, v_cache=v1,
                start_pos=jnp.zeros((1,), jnp.int32),
                logit_positions=jnp.reshape(n - 1, (1,)),
                fresh_prefill=True,
            )
            return _insert_and_sample(
                params, K, V, tok, k1, v1, logits, slot, shift, seed, temp,
                topk, topp,
            )

        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def admit_many_fused(params, K, V, tok, tokens, ns, slots, offsets,
                             seeds, temps, topks, topps):
            """Admit m short prompts in ONE dispatch: a single batched
            prefill over [m, bucket] plus per-row insert/sample — concurrent
            arrivals pay one prefill's latency instead of m (the dominant
            term in TTFT p95 under bursty load).

            The transient prefill cache is [m, ..., bucket] long, not
            max_seq (which at m = max_slots would duplicate the whole
            serving cache's HBM). Each bucket-length block lands at
            ``offsets[i]`` = ring_next - n_i so the prefix ends at the ring
            head; the caller guarantees no block wraps (falls back to
            per-request admits otherwise)."""
            from ..models.llama import make_cache as _mk

            m, bucket = tokens.shape
            km, vm = _mk(cfg, m, bucket)
            km, vm = pin_row(km), pin_row(vm)
            logits, km, vm = fwd(
                params, tokens=tokens, k_cache=km, v_cache=vm,
                start_pos=jnp.zeros((m,), jnp.int32),
                logit_positions=ns - 1,  # [m,1,vocab]: prompt-end rows only
                fresh_prefill=True,
            )
            zero = jnp.zeros((), jnp.int32)
            firsts = sample_rows(
                logits[:, 0], seeds, jnp.zeros((m,), jnp.int32), temps, topks, topps
            )

            lkv, hkv, hd = km.shape[1], km.shape[2], km.shape[4]

            def body(carry, i):
                K, V, tok = carry
                src_idx = (i, zero, zero, zero, zero)
                size = (1, lkv, hkv, bucket, hd)
                k1 = kv_slice(km, src_idx, size)
                v1 = kv_slice(vm, src_idx, size)
                K = kv_copy_slice(K, k1, (slots[i], zero, zero, offsets[i], zero))
                V = kv_copy_slice(V, v1, (slots[i], zero, zero, offsets[i], zero))
                tok = jax.lax.dynamic_update_slice(
                    tok, jax.lax.dynamic_slice_in_dim(firsts, i, 1), (slots[i],)
                )
                return (K, V, tok), None

            (K, V, tok), _ = jax.lax.scan(
                body, (K, V, tok), jnp.arange(m, dtype=jnp.int32)
            )
            return firsts, pin_cache(K), pin_cache(V), tok

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4, 5))
        def finish_admit(params, K, V, tok, k1, v1, logits, slot, shift,
                         seed, temp, topk, topp):
            """Chunked-prefill tail: ring-align + write + sample, one dispatch."""
            return _insert_and_sample(
                params, K, V, tok, k1, v1, logits, slot, shift,
                seed, temp, topk, topp,
            )

        @partial(jax.jit, donate_argnums=(0, 1))
        def write_prefix_block(k1, v1, kb, vb, start):
            """Write one CACHED prefix block into a transient row cache at
            S-offset ``start`` (hit-path admit): the block lands exactly
            where the chunked prefill would have written it, so the suffix
            chunks resume through prefill1 unchanged. kb/vb are NOT donated
            — they stay resident in the prefix cache for the next hit."""
            zero = jnp.zeros((), jnp.int32)
            k1 = kv_copy_slice(k1, kb, (zero, zero, zero, start, zero))
            v1 = kv_copy_slice(v1, vb, (zero, zero, zero, start, zero))
            return pin_row(k1), pin_row(v1)

        @jax.jit
        def prefill_full(params, tokens, k1, v1, n):
            """A whole LONG prompt in ONE fresh flash dispatch (idle-engine
            admits). Chunking exists to bound live streams' inter-token
            gap; with nothing else decoding it is pure overhead — measured
            on-chip at 16k: ~110-180 ms per chunk of structural cost
            beyond the matmuls (scripts/ablate_chunk_one.py), 5.2 s
            chunked vs 2.3 s for this path. Tokens are right-padded to a
            pow2 bucket (pad keys sit at positions only pad queries can
            see; the rolled-in junk above ``n`` lands on future ring slots
            that decode overwrites before they can become valid)."""
            logits, k1, v1 = fwd(
                params, tokens=tokens, k_cache=pin_row(k1), v_cache=pin_row(v1),
                start_pos=jnp.zeros((1,), jnp.int32),
                logit_positions=jnp.reshape(n - 1, (1,)),
                fresh_prefill=True,
            )
            return logits, pin_row(k1), pin_row(v1)

        @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(6,))
        def prefill_chunk_group(params, tokens, km, vm, start, last_pos, window):
            """One [m, C] chunk of a BATCHED chunked admit. Donates the
            m-row transient cache pair (reassigned every iteration; without
            donation each chunk would briefly hold 2x the m-row caches).
            ``window`` (static, bucketed >= start + C) bounds reads to the
            live prefix — see prefill1."""
            logits, km, vm = fwd(
                params, tokens=tokens, k_cache=pin_row(km), v_cache=pin_row(vm),
                start_pos=start,
                logit_positions=last_pos, uniform_start=True, attn_window=window,
            )
            return logits, pin_row(km), pin_row(vm)

        @jax.jit
        def select_end(final, logits, is_end):
            """Keep each row's logits from the chunk its prompt ENDS in."""
            return jnp.where(is_end[:, None, None], logits, final)

        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def finish_admit_group(params, K, V, tok, km, vm, final_logits,
                               slots, shifts, seeds, temps, topks, topps):
            """Batched chunked-prefill tail: per-row ring-align + write +
            first-token sample for m rows in ONE dispatch. km/vm are NOT
            donated: the AOT compile path double-counts donated buffers
            against the HBM budget, and the m-row transients are the
            largest operands here — donating them would spuriously reject
            configs whose real peak fits comfortably."""
            m = final_logits.shape[0]
            lkv, hkv, hd = km.shape[1], km.shape[2], km.shape[4]
            s_full = km.shape[3]
            zero = jnp.zeros((), jnp.int32)
            firsts = sample_rows(
                final_logits[:, 0], seeds, jnp.zeros((m,), jnp.int32),
                temps, topks, topps,
            )

            def body(carry, i):
                K, V, tok = carry
                size = (1, lkv, hkv, s_full, hd)
                k1 = kv_roll_s(kv_slice(km, (i, zero, zero, zero, zero), size),
                               shifts[i], s_axis=3)
                v1 = kv_roll_s(kv_slice(vm, (i, zero, zero, zero, zero), size),
                               shifts[i], s_axis=3)
                K = kv_copy_slice(K, k1, (slots[i], zero, zero, zero, zero))
                V = kv_copy_slice(V, v1, (slots[i], zero, zero, zero, zero))
                tok = jax.lax.dynamic_update_slice(
                    tok, jax.lax.dynamic_slice_in_dim(firsts, i, 1), (slots[i],)
                )
                return (K, V, tok), None

            (K, V, tok), _ = jax.lax.scan(
                body, (K, V, tok), jnp.arange(m, dtype=jnp.int32)
            )
            return firsts, pin_cache(K), pin_cache(V), tok

        max_seq = self.max_seq

        @partial(jax.jit, donate_argnums=(0, 1))
        def compact_ring(K, V, shift):
            """Roll every row's S axis so the shared validity window ends at
            a fresh head below max_seq again — the wrapped ring's recovery
            path (VERDICT r2 weak #7: without this, one wrap costs windowed
            attention reads for the rest of the worker's life)."""
            return (
                pin_cache(kv_roll_s(K, shift, s_axis=3)),
                pin_cache(kv_roll_s(V, shift, s_axis=3)),
            )

        @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(11, 12))
        def decode(params, tok, K, V, pos, ring, seeds, steps, temp, topk, topp,
                   n, window):
            """n decode steps in one dispatch (device-side scan): the host
            sees one transfer in and one [B, n] token readback — and the
            next-token carry stays ON DEVICE (returned as ``tok``), so the
            NEXT burst can be dispatched before this one's tokens are read
            back (the depth-2 pipeline in _run). ``pos``/``steps`` are
            device-resident carries too (returned advanced by n): with them
            re-uploaded every burst, the per-burst host->device transfers
            were a measurable slice of the served/device gap on a tunneled
            chip. ``window`` (static) bounds attention reads to the live
            ring prefix while the ring has not wrapped — the dominant HBM
            saving at partial cache occupancy (~35% step time at half-full,
            granite-2b b32)."""

            def body(carry, i):
                tok, K, V = carry
                logits, K, V = fwd(
                    params, tokens=tok[:, None], k_cache=K, v_cache=V,
                    start_pos=pos + i, ring_slot=(ring + i) % max_seq,
                    attn_window=window,
                )
                nxt = sample_rows(logits[:, -1, :], seeds, steps + i, temp, topk, topp)
                return (nxt, K, V), nxt

            (tok, K, V), toks = jax.lax.scan(
                body, (tok, pin_cache(K), pin_cache(V)), jnp.arange(n, dtype=jnp.int32)
            )
            # [B, n] tokens, caches, device-side carries
            return toks.T, pin_cache(K), pin_cache(V), tok, pos + n, steps + n

        @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(10, 11))
        def decode_pos(params, tok, K, V, pos, seeds, steps, temp, topk, topp,
                       n, window):
            """Positional-layout decode burst: spec mode's fallback when no
            slot has a draft (or occupancy passed spec_max_active). Same
            contract as ``decode`` minus the ring scalar — each row writes
            its fresh KV at its own sequence position ``pos + i`` (per-row
            scatter) and attention masks by ``key_pos <= position``."""

            def body(carry, i):
                tok, K, V = carry
                logits, K, V = fwd(
                    params, tokens=tok[:, None], k_cache=K, v_cache=V,
                    start_pos=pos + i, attn_window=window,
                )
                nxt = sample_rows(logits[:, -1, :], seeds, steps + i, temp, topk, topp)
                return (nxt, K, V), nxt

            (tok, K, V), toks = jax.lax.scan(
                body, (tok, pin_cache(K), pin_cache(V)), jnp.arange(n, dtype=jnp.int32)
            )
            return toks.T, pin_cache(K), pin_cache(V), tok, pos + n, steps + n

        @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(11,))
        def decode_pos_ext(params, tok, K, V, pos, seeds, steps, temp, topk,
                           topp, mask, window):
            """Single masked positional decode step with logprob readback —
            the "ext" regime program, dispatched whenever any live slot
            needs constrained decoding or logprobs. ``mask`` [B, V] bans
            tokens before truncation inside sample_rows; all-True rows are
            a bitwise no-op, so normal slots ride along unchanged. n is
            fixed at 1: the mask for step i+1 depends on the token chosen
            at step i (a host-side DFA walk), so bursts cannot scan."""
            logits, K, V = fwd(
                params, tokens=tok[:, None], k_cache=pin_cache(K),
                v_cache=pin_cache(V), start_pos=pos, attn_window=window,
            )
            raw = logits[:, -1, :]
            nxt = sample_rows(raw, seeds, steps, temp, topk, topp, mask=mask)
            logp = jax.nn.log_softmax(raw.astype(jnp.float32), axis=-1)
            chosen = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
            kk = min(LOGPROBS_K, raw.shape[-1])
            top_lp, top_ids = jax.lax.top_k(logp, kk)
            return (nxt, chosen, top_ids, top_lp, pin_cache(K), pin_cache(V),
                    nxt, pos + 1, steps + 1)

        @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(12,))
        def spec_verify(params, tok, K, V, pos, drafts, dlen, seeds, steps,
                        temp, topk, topp, window):
            """One width-(k+1) VERIFY dispatch: forward the device carry
            token plus k drafted tokens through the positional decode
            cache-write path in a single program (the weight tree is read
            once for k+1 token positions — the bandwidth conversion the
            whole feature exists for), then run the rejection-sampling
            acceptance rule on device. Only the accepted prefix advances
            the carries; KV written for rejected positions is stale by
            construction (see spec.py: masked by position, overwritten by
            this row's own future writes — no rollback)."""
            toks_in = jnp.concatenate([tok[:, None], drafts], axis=1)  # [B,k+1]
            logits, K, V = fwd(
                params, tokens=toks_in, k_cache=pin_cache(K), v_cache=pin_cache(V),
                start_pos=pos, attn_window=window,
            )
            K, V = pin_cache(K), pin_cache(V)
            out, n_emit = spec_accept_rows(
                logits, drafts, dlen, seeds, steps, temp, topk, topp
            )
            new_tok = jnp.take_along_axis(out, (n_emit - 1)[:, None], axis=1)[:, 0]
            width = toks_in.shape[1]
            return out, n_emit, K, V, new_tok, pos + n_emit, steps + width

        # -- paged-KV jit grid ------------------------------------------------
        # Every program below reads/writes the serving cache THROUGH a block
        # table over the shared pool [NB, L, Hkv, T, D] instead of a
        # contiguous per-slot ring. The pool replaces K/V wholesale in _run
        # when self.paged; the legacy programs above stay untouched (and are
        # the KV_PAGED=0 equivalence baseline).
        if self.paged:
            T = self.kv_block_tokens
            pin_pool = pin_row  # pool [NB, L, Hkv, T, D]: heads at index 2

            @partial(jax.jit, donate_argnums=(0,))
            def sample_first(tok, logits, slot, seed, temp, topk, topp):
                """Full-prefix-hit admit: ZERO KV copies — the slot's block
                table already references the cached blocks, so all that is
                left on device is sampling token 0 from the stored
                prompt-end logits into the carry."""
                first = sample_rows(
                    logits[:, 0], seed[None], jnp.zeros((1,), jnp.int32),
                    temp[None], topk[None], topp[None],
                )
                tok = jax.lax.dynamic_update_slice(tok, first, (slot,))
                return first, tok

            def _write_and_sample(KP, VP, tok, k1, v1, logits, bids, slot,
                                  seed, temp, topk, topp):
                KP = pin_pool(kv_pool_write_row(KP, k1, bids))
                VP = pin_pool(kv_pool_write_row(VP, v1, bids))
                first = sample_rows(
                    logits[:, 0], seed[None], jnp.zeros((1,), jnp.int32),
                    temp[None], topk[None], topp[None],
                )
                tok = jax.lax.dynamic_update_slice(tok, first, (slot,))
                return first, KP, VP, tok

            @partial(jax.jit, donate_argnums=(1, 2, 3))
            def admit_fused_paged(params, KP, VP, tok, tokens, n, bids, slot,
                                  seed, temp, topk, topp):
                """Short-prompt admit, paged: prefill a bucket-length
                transient row on device and write its blocks straight into
                the pool at ``bids`` (null-padded — bucket junk past the
                prompt's last block lands in block 0 and is never read
                unmasked). No ring roll: paged mode is positional."""
                from ..models.llama import make_cache as _mk

                k1, v1 = _mk(cfg, 1, tokens.shape[1])
                k1, v1 = pin_row(k1), pin_row(v1)
                logits, k1, v1 = fwd(
                    params, tokens=tokens, k_cache=k1, v_cache=v1,
                    start_pos=jnp.zeros((1,), jnp.int32),
                    logit_positions=jnp.reshape(n - 1, (1,)),
                    fresh_prefill=True,
                )
                return _write_and_sample(
                    KP, VP, tok, k1, v1, logits, bids, slot, seed, temp,
                    topk, topp,
                )

            @partial(jax.jit, donate_argnums=(1, 2, 3))
            def admit_many_fused_paged(params, KP, VP, tok, tokens, ns, bids,
                                       slots, seeds, temps, topks, topps):
                """Batched short admit, paged: one [m, bucket] prefill, then
                a scan writes each row's blocks to its own table entries.
                Pad rows carry all-null bids (junk into block 0)."""
                from ..models.llama import make_cache as _mk

                m, bucket = tokens.shape
                km, vm = _mk(cfg, m, bucket)
                km, vm = pin_row(km), pin_row(vm)
                logits, km, vm = fwd(
                    params, tokens=tokens, k_cache=km, v_cache=vm,
                    start_pos=jnp.zeros((m,), jnp.int32),
                    logit_positions=ns - 1,
                    fresh_prefill=True,
                )
                zero = jnp.zeros((), jnp.int32)
                firsts = sample_rows(
                    logits[:, 0], seeds, jnp.zeros((m,), jnp.int32), temps,
                    topks, topps,
                )
                lkv, hkv, hd = km.shape[1], km.shape[2], km.shape[4]

                def body(carry, i):
                    KP, VP, tok = carry
                    size = (1, lkv, hkv, bucket, hd)
                    k1 = kv_slice(km, (i, zero, zero, zero, zero), size)
                    v1 = kv_slice(vm, (i, zero, zero, zero, zero), size)
                    KP = kv_pool_write_row(KP, k1, bids[i])
                    VP = kv_pool_write_row(VP, v1, bids[i])
                    tok = jax.lax.dynamic_update_slice(
                        tok, jax.lax.dynamic_slice_in_dim(firsts, i, 1),
                        (slots[i],),
                    )
                    return (KP, VP, tok), None

                (KP, VP, tok), _ = jax.lax.scan(
                    body, (KP, VP, tok), jnp.arange(m, dtype=jnp.int32)
                )
                return firsts, pin_pool(KP), pin_pool(VP), tok

            @partial(jax.jit, donate_argnums=(1, 2, 3))
            def finish_admit_paged(params, KP, VP, tok, k1, v1, logits, bids,
                                   slot, seed, temp, topk, topp):
                """Chunked/flash-prefill tail, paged: scatter the transient
                row into the pool and sample token 0. ``bids`` is a full
                [max_seq/T] row with NULL entries for blocks that must not
                be written — shared prefix blocks (the slot references the
                cache's copies directly) and the junk tail past the
                prompt. k1/v1 are NOT donated: the block re-layout cannot
                alias the row buffer, so donation would only warn."""
                return _write_and_sample(
                    KP, VP, tok, k1, v1, logits, bids, slot, seed, temp,
                    topk, topp,
                )

            @partial(jax.jit, donate_argnums=(1, 2, 3))
            def finish_admit_group_paged(params, KP, VP, tok, km, vm,
                                         final_logits, bids, slots, seeds,
                                         temps, topks, topps):
                """Batched chunked tail, paged. km/vm NOT donated — same
                AOT double-count reasoning as finish_admit_group."""
                m = final_logits.shape[0]
                lkv, hkv, hd = km.shape[1], km.shape[2], km.shape[4]
                s_full = km.shape[3]
                zero = jnp.zeros((), jnp.int32)
                firsts = sample_rows(
                    final_logits[:, 0], seeds, jnp.zeros((m,), jnp.int32),
                    temps, topks, topps,
                )

                def body(carry, i):
                    KP, VP, tok = carry
                    size = (1, lkv, hkv, s_full, hd)
                    k1 = kv_slice(km, (i, zero, zero, zero, zero), size)
                    v1 = kv_slice(vm, (i, zero, zero, zero, zero), size)
                    KP = kv_pool_write_row(KP, k1, bids[i])
                    VP = kv_pool_write_row(VP, v1, bids[i])
                    tok = jax.lax.dynamic_update_slice(
                        tok, jax.lax.dynamic_slice_in_dim(firsts, i, 1),
                        (slots[i],),
                    )
                    return (KP, VP, tok), None

                (KP, VP, tok), _ = jax.lax.scan(
                    body, (KP, VP, tok), jnp.arange(m, dtype=jnp.int32)
                )
                return firsts, pin_pool(KP), pin_pool(VP), tok

            @partial(jax.jit, donate_argnums=(0, 1))
            def fill_row_chunk(k1, v1, KP, VP, bids, start):
                """Copy C//T cached pool blocks into a transient row cache
                at S-offset ``start`` (partial-prefix-hit admit): suffix
                chunks then attend over the prefix exactly as if it had
                been prefilled here. KP/VP are read-only — the cached
                blocks stay shared; only the transient gets a copy."""
                kb = kv_pool_read_blocks(KP, bids)
                vb = kv_pool_read_blocks(VP, bids)
                zero = jnp.zeros((), jnp.int32)
                k1 = kv_copy_slice(k1, kb, (zero, zero, zero, start, zero))
                v1 = kv_copy_slice(v1, vb, (zero, zero, zero, start, zero))
                return pin_row(k1), pin_row(v1)

            def _touched(pos, width, nb):
                """View-block positions a ``width``-token write starting at
                ``pos`` can touch, clipped into the view (zombie rows past
                max_seq clamp into their own last block — always private,
                and their tokens are never delivered)."""
                ntb = min(nb, (width - 1) // T + 2)
                return jnp.clip(
                    pos[:, None] // T
                    + jnp.arange(ntb, dtype=jnp.int32)[None, :],
                    0, nb - 1,
                )

            @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(11, 12))
            def decode_pos_paged(params, tok, KP, VP, tbl, pos, seeds, steps,
                                 temp, topk, topp, n, nb):
                """Paged decode burst: gather each slot's first ``nb`` table
                blocks into a contiguous [B, L, Hkv, nb*T, D] view, run the
                same positional scan as decode_pos over it (the view extent
                IS the attention window — nb rides the same pow2 ladder, so
                reduction extents match the contiguous path), then scatter
                back only the blocks this burst could have written."""
                tbl_n = jax.lax.slice_in_dim(tbl, 0, nb, axis=1)
                Kv = pin_row(kv_pool_gather_view(KP, tbl_n))
                Vv = pin_row(kv_pool_gather_view(VP, tbl_n))

                def body(carry, i):
                    tok, Kc, Vc = carry
                    logits, Kc, Vc = fwd(
                        params, tokens=tok[:, None], k_cache=Kc, v_cache=Vc,
                        start_pos=pos + i,
                    )
                    nxt = sample_rows(
                        logits[:, -1, :], seeds, steps + i, temp, topk, topp
                    )
                    return (nxt, Kc, Vc), nxt

                (tok, Kv, Vv), toks = jax.lax.scan(
                    body, (tok, Kv, Vv), jnp.arange(n, dtype=jnp.int32)
                )
                vb = _touched(pos, n, nb)
                KP = pin_pool(kv_pool_scatter_view(KP, Kv, tbl_n, vb))
                VP = pin_pool(kv_pool_scatter_view(VP, Vv, tbl_n, vb))
                return toks.T, KP, VP, tok, pos + n, steps + n

            @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(12,))
            def decode_pos_paged_ext(params, tok, KP, VP, tbl, pos, seeds,
                                     steps, temp, topk, topp, mask, nb):
                """Paged twin of decode_pos_ext: one masked step with
                logprob readback through the gather-view / scatter-back
                frame. Same n=1 constraint (next mask needs this token)."""
                tbl_n = jax.lax.slice_in_dim(tbl, 0, nb, axis=1)
                Kv = pin_row(kv_pool_gather_view(KP, tbl_n))
                Vv = pin_row(kv_pool_gather_view(VP, tbl_n))
                logits, Kv, Vv = fwd(
                    params, tokens=tok[:, None], k_cache=Kv, v_cache=Vv,
                    start_pos=pos,
                )
                raw = logits[:, -1, :]
                nxt = sample_rows(raw, seeds, steps, temp, topk, topp,
                                  mask=mask)
                logp = jax.nn.log_softmax(raw.astype(jnp.float32), axis=-1)
                chosen = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
                kk = min(LOGPROBS_K, raw.shape[-1])
                top_lp, top_ids = jax.lax.top_k(logp, kk)
                vb = _touched(pos, 1, nb)
                KP = pin_pool(kv_pool_scatter_view(KP, Kv, tbl_n, vb))
                VP = pin_pool(kv_pool_scatter_view(VP, Vv, tbl_n, vb))
                return (nxt, chosen, top_ids, top_lp, KP, VP, nxt, pos + 1,
                        steps + 1)

            @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(13,))
            def spec_verify_paged(params, tok, KP, VP, tbl, pos, drafts, dlen,
                                  seeds, steps, temp, topk, topp, nb):
                """Paged spec verify: the same gather-view / scatter-back
                frame as decode_pos_paged around the width-(k+1) verify
                forward — spec decode's positional layout IS the block
                table, no separate positional cache."""
                tbl_n = jax.lax.slice_in_dim(tbl, 0, nb, axis=1)
                Kv = pin_row(kv_pool_gather_view(KP, tbl_n))
                Vv = pin_row(kv_pool_gather_view(VP, tbl_n))
                toks_in = jnp.concatenate([tok[:, None], drafts], axis=1)
                logits, Kv, Vv = fwd(
                    params, tokens=toks_in, k_cache=Kv, v_cache=Vv,
                    start_pos=pos,
                )
                out, n_emit = spec_accept_rows(
                    logits, drafts, dlen, seeds, steps, temp, topk, topp
                )
                new_tok = jnp.take_along_axis(
                    out, (n_emit - 1)[:, None], axis=1
                )[:, 0]
                width = toks_in.shape[1]
                vb = _touched(pos, width, nb)
                KP = pin_pool(kv_pool_scatter_view(KP, Kv, tbl_n, vb))
                VP = pin_pool(kv_pool_scatter_view(VP, Vv, tbl_n, vb))
                return out, n_emit, KP, VP, new_tok, pos + n_emit, steps + width

            @partial(jax.jit, donate_argnums=(0, 1))
            def pool_copy_block(KP, VP, dst, src):
                """Copy-on-write: duplicate one shared block before a write."""
                return (
                    pin_pool(kv_pool_copy_block(KP, dst, src)),
                    pin_pool(kv_pool_copy_block(VP, dst, src)),
                )

            # -- Pallas paged-decode twins (ops/paged_attention.py) --------
            # Same signatures and return contracts as the *_paged programs
            # minus the ``nb`` static arg: the kernel's grid spans the WHOLE
            # table, so one compile per burst width serves every context
            # length — no gather-view materialization, no scatter-back, no
            # pow2-ladder recompiles. Write-then-attend happens per layer
            # inside forward_decode_paged (the pool is the only KV storage
            # these programs touch).
            fwd_paged = partial(forward_decode_paged, cfg=cfg, mesh=mesh)

            @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(11,))
            def decode_pos_pallas(params, tok, KP, VP, tbl, pos, seeds,
                                  steps, temp, topk, topp, n):
                """Pallas decode burst: n single-token paged forwards in one
                on-device scan, pool carried through."""
                def body(carry, i):
                    tok, KP, VP = carry
                    logits, KP, VP = fwd_paged(
                        params, tokens=tok[:, None], k_pool=KP, v_pool=VP,
                        tbl=tbl, start_pos=pos + i,
                    )
                    nxt = sample_rows(
                        logits[:, -1, :], seeds, steps + i, temp, topk, topp
                    )
                    return (nxt, KP, VP), nxt

                (tok, KP, VP), toks = jax.lax.scan(
                    body, (tok, KP, VP), jnp.arange(n, dtype=jnp.int32)
                )
                return (toks.T, pin_pool(KP), pin_pool(VP), tok, pos + n,
                        steps + n)

            @partial(jax.jit, donate_argnums=(2, 3))
            def decode_pos_pallas_ext(params, tok, KP, VP, tbl, pos, seeds,
                                      steps, temp, topk, topp, mask):
                """Pallas twin of decode_pos_paged_ext: one masked step with
                logprob readback straight off the pool."""
                logits, KP, VP = fwd_paged(
                    params, tokens=tok[:, None], k_pool=KP, v_pool=VP,
                    tbl=tbl, start_pos=pos,
                )
                raw = logits[:, -1, :]
                nxt = sample_rows(raw, seeds, steps, temp, topk, topp,
                                  mask=mask)
                logp = jax.nn.log_softmax(raw.astype(jnp.float32), axis=-1)
                chosen = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
                kk = min(LOGPROBS_K, raw.shape[-1])
                top_lp, top_ids = jax.lax.top_k(logp, kk)
                return (nxt, chosen, top_ids, top_lp, pin_pool(KP),
                        pin_pool(VP), nxt, pos + 1, steps + 1)

            @partial(jax.jit, donate_argnums=(2, 3))
            def spec_verify_pallas(params, tok, KP, VP, tbl, pos, drafts,
                                   dlen, seeds, steps, temp, topk, topp):
                """Pallas spec verify: the width-(k+1) draft bundle rides the
                same kernel (W = k+1 query rows per slot) — rejected drafts'
                pool rows are stale-by-position, overwritten by that slot's
                next writes, exactly the positional-layout contract."""
                toks_in = jnp.concatenate([tok[:, None], drafts], axis=1)
                logits, KP, VP = fwd_paged(
                    params, tokens=toks_in, k_pool=KP, v_pool=VP,
                    tbl=tbl, start_pos=pos,
                )
                out, n_emit = spec_accept_rows(
                    logits, drafts, dlen, seeds, steps, temp, topk, topp
                )
                new_tok = jnp.take_along_axis(
                    out, (n_emit - 1)[:, None], axis=1
                )[:, 0]
                width = toks_in.shape[1]
                return (out, n_emit, pin_pool(KP), pin_pool(VP), new_tok,
                        pos + n_emit, steps + width)

            self._sample_first = self._timed("sample_first", sample_first)
            self._admit_fused_paged = self._timed("admit_fused_paged", admit_fused_paged)
            self._admit_many_fused_paged = self._timed(
                "admit_many_fused_paged", admit_many_fused_paged
            )
            self._finish_admit_paged = self._timed("finish_admit_paged", finish_admit_paged)
            self._finish_admit_group_paged = self._timed(
                "finish_admit_group_paged", finish_admit_group_paged
            )
            self._fill_row_chunk = self._timed("fill_row_chunk", fill_row_chunk)
            self._decode_pos_paged = self._timed("decode_pos_paged", decode_pos_paged)
            self._decode_pos_paged_ext = self._timed(
                "decode_pos_paged_ext", decode_pos_paged_ext
            )
            self._spec_verify_paged = self._timed("spec_verify_paged", spec_verify_paged)
            self._pool_copy_block = self._timed("pool_copy_block", pool_copy_block)
            self._decode_pos_pallas = self._timed("decode_pallas", decode_pos_pallas)
            self._decode_pos_pallas_ext = self._timed(
                "decode_pallas_ext", decode_pos_pallas_ext
            )
            self._spec_verify_pallas = self._timed(
                "spec_verify_pallas", spec_verify_pallas
            )

        self._prefill1 = self._timed("prefill1", prefill1)
        self._prefill_full = self._timed("prefill_full", prefill_full)
        self._write_prefix_block = self._timed("write_prefix_block", write_prefix_block)
        self._admit_fused = self._timed("admit_fused", admit_fused)
        self._admit_many_fused = self._timed("admit_many_fused", admit_many_fused)
        self._finish_admit = self._timed("finish_admit", finish_admit)
        self._prefill_chunk_group = self._timed("prefill_chunk_group", prefill_chunk_group)
        self._select_end = self._timed("select_end", select_end)
        self._finish_admit_group = self._timed("finish_admit_group", finish_admit_group)
        self._decode = self._timed("decode", decode)
        self._decode_pos = self._timed("decode_pos", decode_pos)
        self._decode_pos_ext = self._timed("decode_pos_ext", decode_pos_ext)
        self._spec_verify = self._timed("spec_verify", spec_verify)
        self._compact_ring = self._timed("compact_ring", compact_ring)

        self._inbox: _queue.Queue[_Request | None] = _queue.Queue()
        # cancel notices for the owner thread (consumer-gone requests); the
        # flag on the request is the source of truth, the queue is the wakeup
        self._cancels: _queue.Queue[_Request] = _queue.Queue()
        # owner-maintained mirror of len(waitlist) so _enqueue's depth bound
        # can see waiters that already left the inbox (approximate by a few
        # requests during an admit — fine for an overload guard)
        self._wl_len = 0
        self._slots: list[_Request | None] = [None] * max_slots
        self._thread: threading.Thread | None = None
        self._started = False
        self._stopping = False
        # serializes submit's stopped-check+enqueue against stop's
        # stopping-flag+sentinel so no request can slip into the inbox after
        # the final drain (submit would otherwise hang forever)
        self._submit_lock = threading.Lock()
        # supervision surface (serve/worker.py watchdog): the owner thread
        # stamps `heartbeat` once per main-loop iteration; `crashed` holds
        # the exception that killed the pump loop, if any. The waitlist is
        # an instance attr so a crash handler can fail waiters too.
        self.heartbeat = time.monotonic()
        self.crashed: BaseException | None = None
        self._waitlist: list[_Request] = []

    def _ring_name(self, base: str, t: int) -> str | None:
        """Per-dispatch metrics-name override for a full-prefill of padded
        width ``t``: tagged ``_ring`` when this bucket's program takes the
        sp ring-attention path (parallel.ring_attention.use_ring_prefill —
        t is trace-time static, so the tag matches what the jit compiled).
        None means "use the wrapped name"."""
        if self.mesh is None:
            return None
        from ..parallel.ring_attention import use_ring_prefill

        if not use_ring_prefill(self.mesh, t):
            return None
        if self.cfg.is_moe and getattr(self.cfg, "use_routed_moe", False):
            base += "_moe"
        return base + "_ring"

    def _timed(self, name: str, fn):
        """Wrap one jit-grid program so every dispatch lands in
        stats.program_ms[name] (and, when the caller passes ``_tokens=``,
        tokens-per-dispatch in program_tokens[name]). Times the host-side
        call only — it never blocks on the result, so the depth-2 decode
        pipeline is untouched; decode_step_ms remains the
        readback-inclusive per-step number.

        Forward-bearing programs of a routed-MoE model record under a
        ``_moe``-suffixed name (roofline.program_family) — same timing,
        same prefill/decode classification (classify_program strips the
        suffix), distinct metrics family.

        With the efficiency plane on, the first dispatch per shape-bucket
        also extracts flops/bytes from XLA cost analysis — BEFORE the call,
        because the programs donate their input buffers — and every dispatch
        then folds into the roofline counters plus, via the owner thread's
        charge context, the per-request device-time ledger. A failed
        extraction caches None so a program is probed at most once per
        shape."""
        if (name in _MOE_TAGGED_PROGRAMS and self.cfg.is_moe
                and getattr(self.cfg, "use_routed_moe", False)):
            name = name + "_moe"
        stats = self.stats
        eff = self._efficiency
        cost_cache: dict = {}
        is_prefill = classify_program(name) == "prefill"
        is_spec = program_base(name) in SPEC_PROGRAMS

        def run(*args, _tokens=None, _name=None, **kwargs):
            cost = None
            if eff:
                key = dispatch_shape_key(args, kwargs)
                try:
                    cost = cost_cache[key]
                except KeyError:
                    cost = extract_dispatch_cost(fn, args, kwargs)
                    cost_cache[key] = cost
            t0 = time.monotonic()
            out = fn(*args, **kwargs)
            ms = (time.monotonic() - t0) * 1e3
            # _name: per-dispatch family tag (e.g. "prefill_full_ring" when
            # this bucket's program takes the sp ring path) — same jit, same
            # classification, distinct metrics row
            stats.record_program(_name or name, ms, _tokens)
            if eff:
                stats.record_dispatch_cost(_name or name, cost)
                ctx = self._charge_ctx
                if ctx:
                    share = ms / len(ctx)
                    for r in ctx:
                        if is_prefill:
                            r.dev_prefill_ms += share
                        else:
                            r.dev_decode_ms += share
                            if is_spec:
                                r.dev_spec_ms = share
                else:
                    stats.attribute_device_time("other", ms)
            return out

        run.__name__ = f"timed_{name}"
        return run

    def _ledger_finalize(self, req, category: str) -> None:
        """Resolve a request's accrued device time into an outcome category.

        ``category`` is one of roofline.WASTE_CATEGORIES (or "failed" for
        crash paths). A served request with a ``waste_tag`` (disaggregated
        KV-pull fallback) books its prefill share under the tag — that work
        only happened because the transfer failed. Tolerates duck-typed
        inbox entries (_ControlOp): they never accrue."""
        if not self._efficiency:
            return
        pre = getattr(req, "dev_prefill_ms", 0.0)
        dec = getattr(req, "dev_decode_ms", 0.0)
        if pre <= 0.0 and dec <= 0.0:
            return
        req.dev_prefill_ms = req.dev_decode_ms = req.dev_spec_ms = 0.0
        st = self.stats
        if category == "served":
            if req.waste_tag and pre > 0.0:
                st.attribute_device_time(req.waste_tag, pre)
                st.attribute_device_time("served", dec, req.generated)
            else:
                st.attribute_device_time("served", pre + dec, req.generated)
        else:
            st.attribute_device_time(category, pre + dec)

    def _tenant_served(self, req) -> None:
        """Per-tenant completion accounting for the QoS metrics plane:
        generated tokens (the billable unit) and queue age (admit wait —
        the fairness signal a starved tenant shows first)."""
        try:
            age_ms = max(0.0, (req.t_admit - req.t_enq) * 1e3)
            self.tenant_stats.record_served(req.tenant, req.generated, age_ms)
        except Exception:  # noqa: BLE001 — metrics must never kill the pump
            pass

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(
            target=self._run_guarded, name="batcher", daemon=True
        )
        self._thread.start()

    def _run_guarded(self) -> None:
        """Owner-thread entry: a pump-loop escape (device fault, injected
        chaos exception, bug) must not strand in-flight requests until their
        client timeouts — capture it, fail every in-flight/queued request
        with a *retryable* error, and leave the crash visible for the
        worker's supervisor to restart this engine."""
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 — watchdogs need everything
            self.crashed = e
            log.exception("batcher pump loop crashed")
            n = self._fail_inflight_retryable(e)
            obs_emit(
                "engine_crash", error=f"{type(e).__name__}: {e}",
                inflight_failed=n,
            )
            if self.recorder is not None:
                # the pre-crash timeline is exactly what the recorder is
                # for; the supervisor's restart writes a second (forced)
                # dump whose event tail includes the restart itself
                self.recorder.dump(
                    "engine_crash",
                    extra={
                        "error": f"{type(e).__name__}: {e}",
                        "inflight_failed": n,
                        "device_ms": self.stats.device_time_snapshot()["ms"],
                    },
                )

    def _fail_inflight_retryable(self, cause: BaseException) -> int:
        """Fail every in-flight and queued request with a BatcherStopped
        (its message carries the retryable marker, so clients with a
        RetryPolicy re-issue to a queue-group peer). Returns the count."""
        with self._submit_lock:
            self._stopping = True  # no new submits past this point
        err = BatcherStopped(
            f"engine crashed ({type(cause).__name__}: {cause}); "
            f"retry on another worker"
        )
        n = 0

        def fail(req: _Request) -> None:
            # count BEFORE emit: the emit wakes the consumer, which may read
            # the stats counter (health/metrics scrape) immediately
            nonlocal n
            n += 1
            self._ledger_finalize(req, "failed")
            self.stats.inflight_failed_retryable += 1
            req.emit("err", err)

        for req in self._waitlist:
            fail(req)
        self._waitlist.clear()
        self._wl_len = 0
        for i, req in enumerate(self._slots):
            if isinstance(req, _Request):
                fail(req)
            self._slots[i] = None
        self._slot_view = {}
        while True:
            try:
                req = self._inbox.get_nowait()
            except _queue.Empty:
                break
            if req is not None:
                fail(req)
        return n

    @property
    def brownout_level(self) -> int:
        """Current degradation level (0 normal / 1 brownout / 2 shed-only);
        0 when the controller is off. Plain int read — safe cross-thread."""
        return self.brownout.level if self.brownout is not None else 0

    @property
    def queue_depth(self) -> int:
        """Admitted-but-unscheduled work: waitlist + unread inbox. Two
        GIL-atomic reads — safe from any thread; the advert/router load
        signal (worker.build_advert, serve/dp.py replica pick)."""
        return self._wl_len + self._inbox.qsize()

    def _recorder_frame(self, depth: int, n_active: int) -> dict:
        """One compact flight-recorder frame (owner thread). Everything in
        here must be O(1)-ish: this runs once per OBS_RECORDER_INTERVAL_MS
        inside the pump loop."""
        st = self.stats
        fr = {
            "queue_depth": depth,
            "active_slots": n_active,
            "brownout_level": self.brownout_level,
            "decode_spt_ewma_ms": round(self._decode_spt_ewma * 1e3, 3),
            "spec_accept_ewma": round(self._spec_accept_ewma, 3),
            "requests": st.requests,
            "tokens": st.tokens,
            "shed": st.shed,
            "cancelled": st.cancelled,
            "inflight_failed_retryable": st.inflight_failed_retryable,
        }
        if self.hbm_headroom_fn is not None:
            try:
                hr = self.hbm_headroom_fn()
            except Exception:  # noqa: BLE001 — probe is best-effort
                hr = None
            if hr is not None:
                fr["hbm_headroom_frac"] = round(hr, 4)
        if self._pool is not None:
            ps = self._pool.stats()
            fr["pool_blocks_free"] = ps["blocks_free"]
            fr["pool_blocks_live"] = ps["blocks_live"]
            fr["pool_blocks_shared"] = ps["blocks_shared"]
        if self._suspended or self._suspend_stats["suspended_total"]:
            fr["suspended_slots"] = len(self._suspended)
            fr["suspended_total"] = self._suspend_stats["suspended_total"]
        if self.kv_tiers is not None:
            ts = self.kv_tiers.stats()
            fr["tier_host_bytes"] = ts["host_bytes"]
            fr["tier_host_entries"] = ts["host_entries"]
            fr["tier_demoted_chunks"] = ts["demoted_chunks"]
            fr["tier_promoted_chunks"] = ts["promoted_chunks"]
        if self._efficiency:
            dt = st.device_time_snapshot()["ms"]
            # only nonzero categories: frames are size-sensitive
            fr["device_ms"] = {k: round(v, 1) for k, v in dt.items() if v}
            fr["goodput_tokens_per_device_s"] = round(
                st.goodput_tokens_per_device_s(), 1
            )
        return fr

    def debug_snapshot(self) -> dict:
        """Deep live-state view for ``lmstudio.debug.snapshot``: the slot
        table (per-slot positions and block tables with refcounts), pool
        and prefix-cache summaries, brownout controller state, and the
        recorder ring tail. Safe from any thread — reads the owner's
        wholesale-replaced ``_slot_view`` plus the pool's locked stats."""
        pool = self._pool
        view = self._slot_view  # one GIL-atomic ref read
        slots: dict[int, dict] = {}
        for i, ent in sorted(view.items()):
            e = dict(ent)
            if pool is not None and e.get("blocks"):
                e["block_refcounts"] = [pool.refcount(b) for b in e["blocks"]]
            slots[i] = e
        snap: dict = {
            "max_slots": self.max_slots,
            "max_seq": self.max_seq,
            "paged": self.paged,
            "decode_kernel": self.decode_kernel,
            "kv_block_tokens": self.kv_block_tokens,
            "queue_depth": self._wl_len + self._inbox.qsize(),
            "slots": slots,
            "decode_spt_ewma_ms": round(self._decode_spt_ewma * 1e3, 3),
            "spec_accept_ewma": round(self._spec_accept_ewma, 3),
        }
        bo = self.brownout
        if bo is not None:
            snap["brownout"] = {
                "level": bo.level,
                "level_name": LEVEL_NAMES[bo.level],
                "transitions": bo.transitions,
            }
        if pool is not None:
            snap["pool"] = pool.stats()
        if self.prefix_cache is not None:
            snap["prefix_cache"] = self.prefix_cache.stats()
        if self.recorder is not None:
            snap["recorder_tail"] = self.recorder.tail(20)
            snap["recorder_frames_sampled"] = self.recorder.frames_sampled
        return snap

    def _note_prefill_rate(self, tokens: int, seconds: float) -> None:
        if seconds <= 0 or tokens <= 0:
            return
        rate = tokens / seconds
        prev = self._prefill_rate_ewma
        self._prefill_rate_ewma = rate if prev == 0.0 else 0.8 * prev + 0.2 * rate

    def _note_decode_spt(self, step_seconds: float) -> None:
        if step_seconds <= 0:
            return
        prev = self._decode_spt_ewma
        self._decode_spt_ewma = (
            step_seconds if prev == 0.0 else 0.8 * prev + 0.2 * step_seconds
        )

    def _estimate_serve_s(self, req: _Request) -> float:
        """Seconds to prefill ``req`` and decode its feasibility floor of
        tokens, from the live rate EWMAs (0.0 while cold — no sample means
        no informed shed, only already-expired ones)."""
        est = 0.0
        if self._prefill_rate_ewma > 0.0:
            est += len(req.prompt_ids) / self._prefill_rate_ewma
        min_tok = max(1, min(self.deadline_min_tokens, req.sp.max_tokens))
        est += min_tok * self._decode_spt_ewma
        return est

    def heartbeat_age_s(self) -> float:
        """Seconds since the owner thread last topped its main loop. Only
        meaningful while the batcher is NOT idle: a fully idle owner blocks
        on the inbox and legitimately stops stamping."""
        return time.monotonic() - self.heartbeat

    @property
    def alive(self) -> bool:
        """True while the owner thread is running and has not crashed."""
        return (
            self.crashed is None
            and self._thread is not None
            and self._thread.is_alive()
        )

    def stop(self) -> None:
        if not self._started or self._stopping:
            return
        with self._submit_lock:
            self._stopping = True
            self._inbox.put(None)
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        # anything enqueued between the owner thread's final drain and here
        self._drain_all("shutdown")
        if self.kv_tiers is not None:
            # flush pending spills so the Object Store tier is complete for
            # the restart-with-warm-cache path, then stop the spill thread
            self.kv_tiers.close()

    @property
    def idle(self) -> bool:
        """True when nothing is being served or queued (approximate snapshot,
        safe to read from any thread) — the registry's idle-eviction test.
        Consults the owner's waitlist mirror too: during the admit-coalesce
        window a request sits in neither the inbox nor a slot."""
        return (
            not any(s is not None for s in self._slots)
            and self._inbox.qsize() == 0
            and self._wl_len == 0
        )

    def warm_chunk_programs(self, widths: tuple[int, ...] | None = None) -> int:
        """Compile every (group-width, attention-window) chunked-prefill
        program this engine can reach, deterministically. Chunk windows are
        a pow2 ladder (``_win_bucket``), so one long admit touches several
        distinct programs; warming them by racing concurrent requests is
        timing-fragile — a missed width x window pairs a multi-second XLA
        compile with some unlucky request's TTFT (observed repeatedly on
        the tunneled chip). Call while the engine is idle; safe from any
        thread (pure jitted fns over fresh transient caches — serving K/V
        state is untouched). Returns the number of programs exercised."""
        C = self.prefill_chunk
        wins = sorted({self._win_bucket(s + C) for s in range(0, self.max_seq, C)})
        if widths is None:
            widths = (1,) + tuple(
                2 ** i for i in range(1, max(1, (self.max_group_long - 1).bit_length() + 1))
            )
        n = 0
        for m in widths:
            if m == 1:
                k1, v1 = self._make_row_cache(1, self.max_seq)
                for w in wins:
                    logits, k1, v1 = self._prefill1(
                        self.params, jnp.zeros((1, C), jnp.int32), k1, v1,
                        jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32), w,
                    )
                    n += 1
                # idle-engine full-prefill programs: every bucket an admit
                # length n in (C, max_seq) can map to — the pow2 ladder
                # PLUS the clamped max_seq bucket (a non-pow2 max_seq like
                # 4608 clamps there; sampling every C catches each edge).
                # Flash-gated like the serving shortcut itself: without the
                # kernel these programs are the dense-score blowup the
                # chunked path exists to avoid, and serving never runs them
                if self.cfg.use_flash_attention:
                    full_buckets = sorted(
                        {self._win_bucket(x) for x in range(C + 1, self.max_seq + 1, C)}
                    )
                    for b_ in full_buckets:
                        logits, k1, v1 = self._prefill_full(
                            self.params, jnp.zeros((1, b_), jnp.int32), k1, v1,
                            jnp.int32(1),
                            _name=self._ring_name("prefill_full", b_),
                        )
                        n += 1
            else:
                km, vm = self._make_row_cache(m, self.max_seq)
                for w in wins:
                    logits, km, vm = self._prefill_chunk_group(
                        self.params, jnp.zeros((m, C), jnp.int32), km, vm,
                        jnp.zeros((m,), jnp.int32), jnp.zeros((m,), jnp.int32), w,
                    )
                    n += 1
            jax.block_until_ready(logits)
        return n

    def pool_stats(self) -> dict | None:
        """Paged-KV block pool counters for metrics/bench (None when the
        batcher runs the legacy contiguous layout). Thread-safe snapshot."""
        return self._pool.stats() if self._pool is not None else None

    def drop_prefix_cache(self) -> int:
        """Evict every cached prefix block and zero the budget (the
        registry's HBM-pressure hook). Safe from any thread: blocks pinned
        by an admit in flight are detached now and freed when that admit
        releases them. Returns the number of blocks evicted."""
        pc = self.prefix_cache
        return pc.resize(0) if pc is not None else 0

    def tier_stats(self) -> dict | None:
        """KV tier + slot-suspend counters for metrics/bench (None when
        neither tiering nor suspend is on). Thread-safe snapshot."""
        if self.kv_tiers is None and not self.kv_suspend:
            return None
        out = dict(self._suspend_stats)
        out["suspended"] = len(self._suspended)
        if self.kv_tiers is not None:
            out.update(self.kv_tiers.stats())
        if self.prefix_cache is not None:
            c = self.prefix_cache.counters()
            out["demoted_blocks"] = c.get("demoted_blocks", 0)
            out["demote_failures"] = (
                out.get("demote_failures", 0) + c.get("demote_failures", 0)
            )
        return out

    def suspend_harvest_to_cache(self, timeout: float = 30.0) -> dict:
        """Suspend every active slot and fold its full token history
        (prompt + generated, whole chunks) into the radix prefix cache,
        then fail the request with a retryable envelope. The drain path
        calls this at its deadline so a warm handoff ships *in-progress*
        work too: the survivor serves the client's retry as a prefix hit
        instead of re-prefilling from scratch (zero-lost-work preemption).
        Returns {"slots": n, "tokens": cached_tokens}."""
        return self._control(_ControlOp("suspend_harvest", {}), timeout)

    def _make_row_cache(self, batch: int, seq_len: int):
        """Fresh transient prefill cache, committed with the row sharding
        when a mesh is live (heads on tp — parallel.sharding.row_cache_spec)
        so the prefill jits compile against per-chip heads instead of
        inferring replication from an unsharded host array."""
        k, v = make_cache(self.cfg, batch, seq_len)
        if self.mesh is not None:
            from ..parallel.sharding import row_cache_spec, shard_cache

            k, v = shard_cache(
                k, v, self.mesh, spec=row_cache_spec(self.mesh, self.cfg)
            )
        return k, v

    def _shard_block(self, kb, vb):
        """Commit a gathered prefix-cache block pair to the row sharding
        (heads on tp). ``kv_gather_block`` slices eagerly; on a tp-only
        mesh the slice usually inherits the head sharding, but a dp/sp
        mesh's slice can land gathered on one device — the device_put
        makes per-chip residency deterministic, so a later hit's copy-in
        never pays an all-gather."""
        if self.mesh is None:
            return kb, vb
        from ..parallel.sharding import row_cache_spec, shard_cache

        return shard_cache(
            kb, vb, self.mesh, spec=row_cache_spec(self.mesh, self.cfg)
        )

    # -- client API ----------------------------------------------------------

    def _enqueue(
        self,
        prompt_ids: list[int],
        sp: SamplingParams,
        trace: Trace | None = None,
        deadline: float | None = None,
        constrain=None,
        want_logprobs: bool = False,
        top_logprobs: int = 0,
        waste_tag: str | None = None,
        tenant: str = ANON_TENANT,
        priority: str = DEFAULT_PRIORITY,
        weight: float = 0.0,
    ) -> _Request:
        if not prompt_ids:
            raise ValueError("empty prompt")
        if len(prompt_ids) >= self.max_seq:
            raise ValueError(f"prompt of {len(prompt_ids)} tokens >= max_seq {self.max_seq}")
        if (constrain is not None or want_logprobs) and not (
            self.paged or self.spec_cfg is not None
        ):
            # the rewind trick re-processes prompt[-1] at its own sequence
            # position — only the positional layouts can do that; the legacy
            # ring writes at a shared ring head and would corrupt the cache
            raise ValueError(
                "constrained decoding / logprobs require the positional KV "
                "layout (paged KV or spec decode); KV_PAGED=0 without spec "
                "cannot serve them"
            )
        req = _Request(
            prompt_ids=list(prompt_ids),
            sp=sp,
            loop=asyncio.get_running_loop(),
            out=asyncio.Queue(),
            t_enq=time.monotonic(),
            trace=trace,
            deadline=deadline,
            constrain=constrain,
            cstate=constrain.start if constrain is not None else 0,
            want_logprobs=want_logprobs or top_logprobs > 0,
            top_logprobs=int(top_logprobs),
            waste_tag=waste_tag,
            tenant=str(tenant or ANON_TENANT),
            priority=priority,
            weight=max(0.0, float(weight)),
        )
        self.tenant_stats.record_request(req.tenant)
        if trace is not None:
            trace.mark("enqueue", req.t_enq)
        # expired before it was even queued: shed at submit, zero device work
        # (the caller's budget is gone — serving it helps nobody)
        if deadline is not None and req.t_enq >= deadline:
            self.stats.record_shed("deadline")
            self.tenant_stats.record_shed(req.tenant)
            raise BatcherOverloaded(
                "deadline already expired at submit (shed_cause=deadline); "
                "retry on another worker"
            )
        bo = self.brownout
        with self._submit_lock:
            if self._stopping:
                raise BatcherStopped("batcher is stopped; retry on another worker")
            if bo is not None and bo.level >= SHED_ONLY and self.idle:
                # the owner loop only ticks the controller while it has work;
                # a fully drained pipeline parks it on the inbox, and a bounce
                # below never wakes it — the level would be stuck at shed-only
                # forever. Tick from the submit path with the current (calm)
                # signals so sustained retry traffic can step the level down.
                headroom = None
                if self.hbm_headroom_fn is not None:
                    try:
                        headroom = self.hbm_headroom_fn()
                    except Exception:  # noqa: BLE001 — probe is best-effort
                        headroom = None
                bo.update(
                    depth_frac=self._inbox.qsize()
                    / (self.max_queue or 4 * self.max_slots),
                    age_p95_ms=0.0,
                    hbm_headroom_frac=headroom,
                )
            if bo is not None and bo.level > req.rank:
                # priority-ordered brownout: the load-shed level IS the
                # lowest class still admitted — BROWNOUT (1) sheds batch
                # (rank 0), SHED_ONLY (2) sheds batch AND standard, and
                # premium (rank 2) is never brownout-shed (only the depth
                # bound below can refuse it). Rank-1 behavior at SHED_ONLY
                # is exactly the pre-QoS bounce every default-class caller
                # already saw.
                self.stats.record_shed("brownout")
                self.tenant_stats.record_shed(req.tenant)
                if bo.level >= SHED_ONLY:
                    msg = (
                        "brownout shed-only: worker saturated "
                        "(shed_cause=brownout); retry on another worker"
                    )
                else:
                    msg = (
                        f"brownout: {req.priority} class shed first "
                        f"(shed_cause=brownout); retry on another worker"
                    )
                raise BatcherOverloaded(msg)
            limit = (
                bo.effective_queue_limit(self.max_queue)
                if bo is not None
                else self.max_queue
            )
            if limit:
                # premium rides a 50% depth grace past the bound: a queue
                # full of lower classes must not bounce it — the owner loop
                # displaces the lowest-fair-share waiters instead (the
                # shed_cause=fair_share path)
                eff = limit + (limit >> 1) + 1 if req.rank >= 2 else limit
                if self._inbox.qsize() + self._wl_len >= eff:
                    self.stats.record_shed("depth")
                    self.tenant_stats.record_shed(req.tenant)
                    raise BatcherOverloaded(
                        f"admit queue full ({limit} waiting) "
                        f"(shed_cause=depth); retry on another worker"
                    )
            self._inbox.put(req)
        return req

    def cancel(self, req: _Request) -> None:
        """Mark a request's consumer as gone. The owner thread frees its
        slot (or drops it from the queue) at the next main-loop check —
        within one decode burst for an active stream. Idempotent."""
        req.cancelled = True
        self._cancels.put(req)

    async def submit(
        self,
        prompt_ids: list[int],
        sp: SamplingParams,
        info: dict | None = None,
        trace: Trace | None = None,
        deadline: float | None = None,
        constrain=None,
        want_logprobs: bool = False,
        top_logprobs: int = 0,
        waste_tag: str | None = None,
        tenant: str = ANON_TENANT,
        priority: str = DEFAULT_PRIORITY,
        weight: float = 0.0,
    ) -> AsyncIterator[int]:
        """Yield generated token ids for one request.

        When ``info`` is given, the batcher's end reason ("stop" / "length" /
        "shutdown") is recorded in ``info["finish_reason"]`` so callers report
        cache-capacity terminations truthfully instead of re-deriving from
        token counts. ``deadline`` is an absolute ``time.monotonic()`` value
        (the client's propagated budget): past it the request is shed before
        prefill or cooperatively aborted mid-decode."""
        async for batch in self.submit_batched(
            prompt_ids, sp, info=info, trace=trace, deadline=deadline,
            constrain=constrain, want_logprobs=want_logprobs,
            top_logprobs=top_logprobs, waste_tag=waste_tag,
            tenant=tenant, priority=priority, weight=weight,
        ):
            for tok in batch:
                yield tok

    async def submit_batched(
        self,
        prompt_ids: list[int],
        sp: SamplingParams,
        info: dict | None = None,
        trace: Trace | None = None,
        deadline: float | None = None,
        constrain=None,
        want_logprobs: bool = False,
        top_logprobs: int = 0,
        waste_tag: str | None = None,
        tenant: str = ANON_TENANT,
        priority: str = DEFAULT_PRIORITY,
        weight: float = 0.0,
    ) -> AsyncIterator[list]:
        """Like ``submit`` but yields LISTS of tokens: everything already
        delivered when the consumer wakes comes out as one batch. A decode
        burst lands on the event loop as ``decode_burst`` tokens at once,
        so the streaming layer can publish one NATS chunk per burst instead
        of per token — at 64+ concurrent streams the per-message publish
        overhead is a measurable share of served throughput.

        ``constrain`` is a serve/constrain.py TokenDFA (schema-constrained
        decoding); ``want_logprobs``/``top_logprobs`` switch each batch item
        from a bare token id to a ``(tok, logprob, top_ids, top_logprobs)``
        tuple. Either option routes the request through the single-step
        masked ext decode program."""
        if not self._started:
            self.start()
        if not prompt_ids:
            return
        req = self._enqueue(
            prompt_ids, sp, trace=trace, deadline=deadline,
            constrain=constrain, want_logprobs=want_logprobs,
            top_logprobs=top_logprobs, waste_tag=waste_tag,
            tenant=tenant, priority=priority, weight=weight,
        )
        done = False
        try:
            while True:
                kind, value = await req.out.get()
                batch: list[int] = []
                while True:
                    if kind == "tok":
                        batch.append(value)
                    elif kind == "end":
                        done = True
                        if batch:
                            yield batch
                        if info is not None:
                            info["finish_reason"] = value
                        return
                    else:
                        done = True
                        if batch:
                            yield batch
                        raise value
                    try:
                        kind, value = req.out.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                yield batch
        finally:
            # consumer left before the stream ended (handler deadline fired,
            # client disconnected, generator closed): free the slot instead
            # of decoding to max_tokens for nobody. The Go reference gets
            # this from ctx threading into the HTTP call
            # (/root/reference/nats_llm_studio.go:328, :158-167); here the
            # cancel rides a thread-safe queue into the batcher owner.
            if not done:
                self.cancel(req)

    # -- disaggregated prefill/decode (serve/kv_transfer.py) -----------------

    def export_prefix_blocks(self, prompt_ids: list[int],
                             timeout: float = 30.0) -> dict | None:
        """Gather the prompt's cached full-chunk KV blocks to HOST memory.

        Returns the ``serve.kv_transfer`` export dict (token_ids /
        chunk_tokens / per-chunk k, v, logits leaves as numpy arrays or
        KVQ (codes, scales) pairs), or None when the prefix cache holds
        nothing useful for this prompt (short prompt, cache miss, pool
        reset). Thread-safe: marshals onto the owner thread through the
        inbox; blocking — call via ``asyncio.to_thread`` from a loop."""
        return self._control(_ControlOp(
            "export", {"prompt_ids": [int(t) for t in prompt_ids]}
        ), timeout)

    def import_prefix_blocks(self, export: dict,
                             timeout: float = 30.0) -> dict:
        """Write a transferred prefill export into freshly allocated pool
        blocks and seed the radix prefix cache, so the matching request's
        admit becomes a prefix hit (full hit ⇒ zero local prefill work).
        Returns ``{"tokens": covered, "blocks": allocated}``. Raises
        ``BatcherOverloaded`` (cause ``kv_pool``) when the pool cannot
        hold the import — the decode-pool-exhaustion failure mode; the
        caller falls back to local prefill. Thread-safe and blocking,
        like :meth:`export_prefix_blocks`."""
        return self._control(_ControlOp("import", {"export": export}), timeout)

    def _control(self, op: _ControlOp, timeout: float):
        if not self._started:
            self.start()
        with self._submit_lock:
            if self._stopping:
                raise BatcherStopped(
                    "batcher is stopped; retry on another worker"
                )
            self._inbox.put(op)
        if not op.done.wait(timeout):
            # the owner may still run it later; it checks this flag and
            # skips — nobody is left to read the result
            op.cancelled = True
            raise TimeoutError(
                f"kv {op.kind} control op timed out after {timeout:.1f}s"
            )
        if op.error is not None:
            raise op.error
        return op.result

    # -- device loop (owner thread) ------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_seq

    def _resolve_decode_kernel(self) -> str:
        """DECODE_KERNEL=pallas|xla|auto -> the kernel paged decode uses.

        "pallas" is honored only where the shard_map heads split works
        (Hkv % tp == 0 — the replicated-KV GQA fallback stays on the XLA
        path) and, on a real TPU, where Mosaic can tile the pool layout
        (``paged_decode_eligible``); anything else downshifts with a log
        line. "auto" additionally requires the TPU backend: off-TPU the
        kernel only runs under the Pallas interpreter, which is what the
        equivalence tests want and what serving throughput does not."""
        if not self.paged:
            return "xla"
        mode = os.environ.get("DECODE_KERNEL", "auto").strip().lower() or "auto"
        if mode not in ("pallas", "xla", "auto"):
            raise ValueError(
                f"DECODE_KERNEL must be pallas|xla|auto, got {mode!r}"
            )
        if mode == "xla":
            return "xla"
        from ..ops.paged_attention import paged_decode_eligible

        cfg = self.cfg
        tp = 1
        if self.mesh is not None:
            from ..parallel.mesh import AXIS_TP

            tp = self.mesh.shape.get(AXIS_TP, 1)
        if tp > 1 and cfg.n_kv_heads % tp:
            if mode == "pallas":
                log.warning(
                    "DECODE_KERNEL=pallas needs Hkv %% tp == 0 (have "
                    "Hkv=%d, tp=%d); falling back to xla",
                    cfg.n_kv_heads, tp,
                )
            return "xla"
        on_tpu = jax.default_backend() == "tpu"
        eligible = paged_decode_eligible(
            self.kv_block_tokens, cfg.head_dim,
            4 if cfg.dtype == "float32" else 2,
            cfg.kv_quant == "int8", cfg.n_kv_heads, tp,
        )
        if mode == "auto":
            return "pallas" if (on_tpu and eligible) else "xla"
        if on_tpu and not eligible:
            log.warning(
                "DECODE_KERNEL=pallas but the pool layout (T=%d, D=%d, "
                "kv_quant=%s) is not Mosaic-tileable; falling back to xla",
                self.kv_block_tokens, cfg.head_dim, cfg.kv_quant,
            )
            return "xla"
        return "pallas"

    def _note_compile(self, program: str, *static) -> None:
        """Count first-seen static-arg combos on the decode/verify paths —
        each is a fresh XLA compile (owner thread only). The counter makes
        the pow2 ladder's compile cost visible next to the Pallas kernel's
        flat one (lmstudio_decode_recompiles_total)."""
        key = (program, *static)
        if key not in self._compiled_keys:
            self._compiled_keys.add(key)
            self.stats.decode_recompiles += 1

    def _win_bucket(self, n: int) -> int:
        """Power-of-two attention window >= n, clamped to max_seq — the
        chunked-prefill read bound. Independent of the (often coarse)
        prompt-length buckets: with buckets like [512, 2048, 16k] a
        bucket-based window reads the full 16k slab from chunk 3 on
        (exactly the r4 O(T^2) tail), while the pow2 ladder keeps reads
        proportional to the live prefix at a log-bounded compile count.
        The floor caps the ladder at DECODE_LADDER_RUNGS rungs total."""
        w = 1 << max(0, n - 1).bit_length()
        return min(max(w, self._win_floor), self.max_seq)

    def _run(self) -> None:
        cfg = self.cfg
        B = self.max_slots
        # speculative decoding OR paged KV: the WHOLE cache runs in
        # positional layout (see __init__) — ring head bookkeeping stays
        # frozen at the cold state and every shift/offset below is forced
        # to 0 so admitted prefixes land at sequence positions [0, n)
        spec = self.spec_cfg
        paged = self.paged
        use_pallas = paged and self.decode_kernel == "pallas"
        pool = self._pool
        T = self.kv_block_tokens
        MB = self.blocks_per_row
        positional = spec is not None or paged
        # per-slot n-gram index over prompt + generated tokens (owner-thread
        # state, created at the admit record's readback, dropped with the slot)
        spec_slots: list[SpecSlot | None] = [None] * B
        # ring head: the shared cache slot the next decode step writes; rows'
        # validity is "my last pos+1 ring slots", see models.llama.forward
        self._ring_next = 0
        self._ring_wrapped = False  # once True, windowed reads are unsafe

        def make_pool():
            """The device block pool pair [NB, L, Hkv, T, D] (KVQ under
            int8) — ONE allocation serves live slots, the prefix cache,
            and spec decode; per-slot worst-case rows are gone."""
            shape = (pool.n_blocks, cfg.n_layers, cfg.n_kv_heads, T,
                     cfg.head_dim)
            quant = cfg.kv_quant == "int8"
            dt = jnp.float32 if cfg.dtype == "float32" else jnp.bfloat16
            KP = kv_pool_zeros(shape, dtype=dt, quant=quant)
            VP = kv_pool_zeros(shape, dtype=dt, quant=quant)
            if self.mesh is not None:
                from ..parallel.sharding import pool_spec, shard_cache

                KP, VP = shard_cache(
                    KP, VP, self.mesh, cfg=cfg,
                    spec=pool_spec(self.mesh, cfg),
                )
            return KP, VP

        if paged:
            K, V = make_pool()
        else:
            K, V = make_cache(cfg, B, self.max_seq)
            if self.mesh is not None:
                from ..parallel.sharding import shard_cache

                K, V = shard_cache(K, V, self.mesh, cfg=cfg)

        # paged-KV host bookkeeping (owner thread only): per-slot block
        # tables mirrored to a device [B, MB] int32 on table_dirty. Entries
        # past a slot's allocation are 0 (the null block).
        tables: list[list[int]] = [[] for _ in range(B)]
        tbl_dev = jnp.zeros((B, max(MB, 1)), jnp.int32)
        table_dirty = False

        # hierarchical KV tiers + slot suspend (owner-thread handles)
        tier = self.kv_tiers
        suspend_on = self.kv_suspend and paged

        def alloc_blocks(k: int, suspend_ok: bool = True,
                         internal: bool = False,
                         for_req: _Request | None = None) -> list[int]:
            """Take k fresh pool blocks; on shortage, reclaim unpinned
            prefix-cache blocks (the evictable tier — demoted to the host
            tier when one is attached, discarded otherwise), then suspend
            victim slots (swap-don't-shed), and only shed when every lever
            is exhausted. Raises _PoolExhausted BEFORE any device dispatch
            so the caller sheds one request instead of resetting the cache.

            ``suspend_ok=False`` marks decode-time growth (ensure_blocks/
            ensure_private): those run mid-burst-preparation over a frozen
            active-slot list, where removing a slot would corrupt the
            dispatch. ``internal=True`` marks opportunistic allocations
            (tier promotion, slot resume) — they must neither suspend
            another slot (thrash cycles) nor count a shed (the caller just
            defers the work), so exhaustion raises a quiet _PoolExhausted.

            ``for_req`` is the ADMITTING request (QoS preemption): a
            higher-class admit that finds the pool full first preempts
            strictly-lower-class victims (lowest class, largest table
            first) to the host tier — reason "preempted", resumed
            bit-identically when pressure clears — before falling back to
            the class-blind swap-don't-shed sweep."""
            got = pool.alloc(k)
            if got is None and pc is not None:
                pc.reclaim(k - pool.free_blocks, demote=tier is not None)
                got = pool.alloc(k)
            if got is None and suspend_on and suspend_ok and not internal:
                if (
                    self.qos_preempt
                    and for_req is not None
                    and for_req.rank > 0
                ):
                    # preempt-to-host-tier: only strictly-lower classes are
                    # eligible, so a premium admit never parks a premium peer
                    while got is None and suspend_victim(
                        below_rank=for_req.rank, reason="preempted"
                    ):
                        if pc is not None and pool.free_blocks < k:
                            pc.reclaim(
                                k - pool.free_blocks, demote=tier is not None
                            )
                        got = pool.alloc(k)
                # swap-don't-shed: demote whole victim slots (blocks + full
                # resume state) to the host tier until the allocation fits
                while got is None and suspend_victim():
                    if pc is not None and pool.free_blocks < k:
                        pc.reclaim(
                            k - pool.free_blocks, demote=tier is not None
                        )
                    got = pool.alloc(k)
            if got is None:
                if internal:
                    raise _PoolExhausted(
                        f"pool busy ({k} blocks needed, "
                        f"{pool.free_blocks} free); deferred"
                    )
                if suspend_ok:
                    # decode-time growth (suspend_ok=False) does NOT count
                    # a shed here: grow_for_burst may park the slot instead
                    # of shedding it, and records the shed itself when not
                    self.stats.record_shed("kv_pool")
                    if for_req is not None:
                        self.tenant_stats.record_shed(for_req.tenant)
                if self.recorder is not None:
                    # rate-limited (not forced): a starved pool sheds every
                    # admit attempt, one dump per window tells the story
                    self.recorder.dump(
                        "kv_pool_exhausted",
                        extra={"needed": k, "free": pool.free_blocks,
                               "device_ms": self.stats.device_time_snapshot()["ms"]},
                    )
                raise _PoolExhausted(
                    f"kv block pool exhausted ({k} blocks needed, "
                    f"{pool.free_blocks} free) (shed_cause=kv_pool); "
                    f"retry on another worker"
                )
            return got

        def ensure_blocks(i: int, upto: int) -> None:
            """Grow slot i's table to cover positions [0, min(upto,
            max_seq)) — decode/spec writes must land in owned blocks."""
            nonlocal table_dirty
            need = min(-(-min(upto, self.max_seq) // T), MB)
            tbl = tables[i]
            if len(tbl) < need:
                tbl.extend(alloc_blocks(need - len(tbl), suspend_ok=False))
                table_dirty = True

        def ensure_private(i: int, lo: int, hi: int) -> None:
            """Copy-on-write safety net: any block slot i is about to write
            in [lo, hi) that is still shared (refs > 1) gets a private
            copy first. Chunk-aligned sharing (T | C) means decode writes
            normally start past every shared block, so this almost never
            fires — but it keeps correctness independent of that layout
            argument."""
            nonlocal K, V, table_dirty
            tbl = tables[i]
            if not tbl:
                return
            b0 = lo // T
            b1 = min((min(hi, self.max_seq) - 1) // T, len(tbl) - 1)
            for b in range(b0, b1 + 1):
                bid = tbl[b]
                if bid != 0 and pool.refcount(bid) > 1:
                    nid = alloc_blocks(1, suspend_ok=False)[0]
                    K, V = self._pool_copy_block(
                        K, V, jnp.int32(nid), jnp.int32(bid)
                    )
                    pool.decref([bid])
                    pool.cow_copies += 1
                    tbl[b] = nid
                    table_dirty = True

        def grow_for_burst(act, upto_of, prev_ctx) -> bool:
            """Grow every active row's table (plus CoW privatization) ahead
            of a burst dispatch. ``ensure_blocks`` deliberately never
            suspends (the active-slot list is frozen mid-preparation), so
            exhaustion lands here — BEFORE any dispatch, device buffers
            intact. Resolve it by aborting the round and removing just the
            overflowing slot: PARK it on the host tier when parking can
            ever succeed (zero lost work — it resumes and regrows once
            blocks free up), shed it retryably when it cannot (its full
            extent exceeds the pool, or no other slot will ever release
            blocks, so resume would re-fail the same growth forever). The
            other streams keep their tokens either way; without this the
            escape used to reach the blanket dispatch handler and reset
            the whole cache. Returns False when the caller must skip the
            round (the slot list is stale)."""
            i = -1
            try:
                for i in act:
                    ensure_blocks(i, upto_of(i))
                    ensure_private(i, host_pos[i], upto_of(i))
                return True
            except _PoolExhausted as e:
                self._charge_ctx = prev_ctx
                r = self._slots[i]
                fits = isinstance(r, _Request) and min(
                    -(-(len(r.prompt_ids) + r.sp.max_tokens) // T), MB
                ) <= pool.n_blocks - 1
                others = any(
                    j != i and isinstance(self._slots[j], _Request)
                    for j in range(B)
                )
                need = min(-(-min(upto_of(i), self.max_seq) // T), MB)
                if (fits and others and suspend_on
                        and suspend_slot(i, "growth", min_blocks=need)):
                    return False
                r = self._slots[i]  # the suspend drain may have finished it
                if isinstance(r, _Request):
                    self.stats.record_shed("kv_pool")
                    self._ledger_finalize(r, "shed_after_prefill")
                    r.emit("err", e)
                    finish_slot(i)
                return False

        def refresh_tables() -> None:
            """Mirror the host block tables to the device [B, MB] array the
            paged decode/verify programs gather through."""
            nonlocal tbl_dev, table_dirty
            if not table_dirty:
                return
            arr = np.zeros((B, max(MB, 1)), np.int32)
            for i, t in enumerate(tables):
                arr[i, : len(t)] = t
            tbl_dev = jnp.asarray(arr)
            table_dirty = False

        def paged_window(top: int) -> int:
            """Table-block count covering positions [0, top): the pow2
            window ladder in units of T (so gather-view extents match the
            contiguous path's attention windows program-for-program)."""
            w = min(max(self._win_bucket(top), T), self.max_seq)
            return w // T
        # device-resident next-token carry: burst k+1's input comes straight
        # from burst k's output ON DEVICE, so the host can dispatch k+1
        # before reading k's tokens back (the depth-2 pipeline below) — the
        # tunneled chip's ~50-100 ms round trip overlaps with compute
        # instead of serializing after every burst.
        tok_dev = jnp.zeros((B,), jnp.int32)
        # per-slot sampling tensors AND position/step/seed carries, rebuilt
        # only when membership changes (dirty); pos/steps advance ON DEVICE
        # as decode carries, so steady-state bursts upload nothing but the
        # ring scalar — three [B] transfers per burst were a measurable
        # slice of the served/device gap on the tunneled chip
        temp = jnp.zeros((B,), jnp.float32)
        topk = jnp.zeros((B,), jnp.int32)
        topp = jnp.ones((B,), jnp.float32)
        pos_dev = jnp.zeros((B,), jnp.int32)
        steps_dev = jnp.zeros((B,), jnp.int32)
        seeds_dev = jnp.zeros((B,), jnp.int32)
        dirty = False

        # host-side OPTIMISTIC per-slot counters, advanced at DISPATCH time
        # (the device will have executed that many steps whether or not the
        # host has read the tokens yet): write position, rng step counter
        host_pos = [0] * B
        host_steps = [0] * B
        host_seed = [0] * B

        # in-flight dispatches whose results have not been read back:
        # ("decode", toks_ref, n, [(slot, req), ...]) |
        # ("ext", toks, lps, top_ids, top_lps, [(slot, req), ...], t) |
        # ("admit", firsts_ref, [(row_in_firsts, slot, req), ...])
        inflight: collections.deque = collections.deque()

        def active() -> list[int]:
            # reserved (mid-chunked-admit) slots are excluded: the decode
            # program still computes their rows (fixed width, masked junk),
            # but no tokens are delivered and host bookkeeping stays frozen
            # until the group's finish dispatch writes them
            return [
                i for i, r in enumerate(self._slots) if isinstance(r, _Request)
            ]

        def ext_live() -> bool:
            # any live constrained/logprob slot forces the ext regime: the
            # burst/spec programs advance the device pos carry for EVERY
            # row, so an ext slot cannot sit out a normal dispatch — all
            # decode goes through the masked single-step program until the
            # last ext slot finishes
            return any(
                isinstance(r, _Request) and r.is_ext for r in self._slots
            )

        def finish_slot(i: int) -> None:
            self._slots[i] = None
            host_pos[i] = 0
            host_steps[i] = 0
            spec_slots[i] = None
            # keep the cross-thread slot view honest even when the loop is
            # about to block idle on the inbox (no rebuild tick follows)
            self._slot_view.pop(i, None)
            nonlocal dirty, table_dirty
            dirty = True
            if paged and tables[i]:
                # return only this slot's references — blocks still pinned
                # by the prefix cache (or another slot) stay live
                pool.decref(tables[i])
                tables[i] = []
                table_dirty = True

        def rebuild_slot_view() -> None:
            """Refresh the cross-thread slot snapshot (debug_snapshot's
            data source) from the owner-local tables/positions. Replaced
            wholesale — readers see one consistent dict via the GIL-atomic
            ref swap; block lists are copies, never the live tables."""
            view: dict[int, dict] = {}
            for i, r in enumerate(self._slots):
                if not isinstance(r, _Request):
                    continue
                ent = {
                    "pos": host_pos[i],
                    "prompt_tokens": len(r.prompt_ids),
                    "generated": r.generated,
                    "max_tokens": r.sp.max_tokens,
                    "cancelled": r.cancelled,
                }
                if r.trace is not None:
                    ent["trace_id"] = r.trace.trace_id
                if paged:
                    ent["blocks"] = list(tables[i])
                view[i] = ent
            self._slot_view = view

        def process_record(rec) -> None:
            """Block on one in-flight dispatch's readback, deliver tokens.

            A per-request delivery failure (e.g. the client's event loop was
            torn down mid-stream, so emit raises) only finishes THAT slot —
            it must not escape to the dispatch-failure reset and kill every
            healthy stream (the K/V buffers are fine; only np.asarray
            readback errors mean poisoned device state)."""
            nonlocal tok_dev, dirty
            if rec[0] == "decode":
                _, toks_ref, n, rows, t_disp = rec
                ids = np.asarray(toks_ref)  # ONE [B, n] readback per burst
                # observed per-step latency (dispatch -> tokens readable);
                # includes pipeline wait, i.e. what a stream experiences
                step_s = (time.monotonic() - t_disp) / n
                self.stats.decode_step_ms.record(step_s * 1e3)
                self._note_decode_spt(step_s)
                for slot, req in rows:
                    if self._slots[slot] is not req:
                        continue  # finished at an earlier record; zombie rows
                    if req.cancelled:
                        self._ledger_finalize(
                            req, "deadline_abort" if req.deadline_hit else "cancelled"
                        )
                        finish_slot(slot)
                        self.stats.record_cancel(
                            "deadline" if req.deadline_hit else "decode"
                        )
                        continue
                    st = spec_slots[slot]
                    try:
                        for j in range(n):
                            req.pos += 1
                            t = int(ids[slot, j])
                            if st is not None:
                                st.index.append(t)
                            reason = self._deliver(req, t)
                            if reason is not None:
                                self._ledger_finalize(req, "served")
                                self._tenant_served(req)
                                finish_slot(slot)  # free BEFORE the end event
                                req.emit("end", reason)
                                break
                    except Exception:  # noqa: BLE001 — dead client
                        log.exception("delivery failed; dropping slot %d", slot)
                        self._ledger_finalize(req, "cancelled")
                        finish_slot(slot)
            elif rec[0] == "spec":
                _, out_ref, nacc_ref, rows, t_disp = rec
                ids = np.asarray(out_ref)  # [B, k+1]
                nacc = np.asarray(nacc_ref)  # [B] emitted counts (a + 1)
                self.stats.decode_step_ms.record((time.monotonic() - t_disp) * 1e3)
                for slot, req, dlen in rows:
                    if self._slots[slot] is not req:
                        continue  # spec is depth-0, but stay defensive
                    n_emit = int(nacc[slot])
                    # host pos catches up to the device carry HERE (spec is
                    # the one dispatch whose advance is data-dependent);
                    # host_steps advanced by k+1 at dispatch
                    host_pos[slot] += n_emit
                    if dlen > 0:
                        self.stats.spec_drafted += dlen
                        self.stats.spec_accepted += n_emit - 1
                        rate = (n_emit - 1) / dlen
                        self.stats.spec_accept_rate.record(max(rate, 0.01))
                        prev = self._spec_accept_ewma
                        self._spec_accept_ewma = (
                            rate if prev == 0.0 else 0.8 * prev + 0.2 * rate
                        )
                        if self._efficiency and req.dev_spec_ms > 0.0:
                            # ledger: the rejected-draft fraction of this
                            # verify's cost moves out of the request's
                            # accrual immediately — it can never serve a
                            # token, whatever the request's outcome
                            waste = min(
                                req.dev_spec_ms * (dlen + 1 - n_emit) / (dlen + 1),
                                req.dev_decode_ms,
                            )
                            if waste > 0.0:
                                req.dev_decode_ms -= waste
                                self.stats.attribute_device_time(
                                    "spec_rejected", waste
                                )
                            req.dev_spec_ms = 0.0
                    if req.cancelled:
                        self._ledger_finalize(
                            req, "deadline_abort" if req.deadline_hit else "cancelled"
                        )
                        finish_slot(slot)
                        self.stats.record_cancel(
                            "deadline" if req.deadline_hit else "decode"
                        )
                        continue
                    st = spec_slots[slot]
                    try:
                        for j in range(n_emit):
                            req.pos += 1
                            t = int(ids[slot, j])
                            if st is not None:
                                st.index.append(t)
                            reason = self._deliver(req, t)
                            if reason is not None:
                                self._ledger_finalize(req, "served")
                                self._tenant_served(req)
                                finish_slot(slot)  # free BEFORE the end event
                                req.emit("end", reason)
                                break
                    except Exception:  # noqa: BLE001 — dead client
                        log.exception("delivery failed; dropping slot %d", slot)
                        self._ledger_finalize(req, "cancelled")
                        finish_slot(slot)
            elif rec[0] == "ext":
                _, toks_ref, lp_ref, topids_ref, toplps_ref, rows, t_disp = rec
                ids = np.asarray(toks_ref)  # [B]
                lps = np.asarray(lp_ref)  # [B]
                tis = np.asarray(topids_ref)  # [B, LOGPROBS_K]
                tls = np.asarray(toplps_ref)  # [B, LOGPROBS_K]
                step_s = time.monotonic() - t_disp
                self.stats.decode_step_ms.record(step_s * 1e3)
                self._note_decode_spt(step_s)
                for slot, req in rows:
                    if self._slots[slot] is not req:
                        continue
                    if req.cancelled:
                        self._ledger_finalize(
                            req, "deadline_abort" if req.deadline_hit else "cancelled"
                        )
                        finish_slot(slot)
                        self.stats.record_cancel(
                            "deadline" if req.deadline_hit else "decode"
                        )
                        continue
                    st = spec_slots[slot]
                    try:
                        req.pos += 1
                        t = int(ids[slot])
                        if st is not None:
                            st.index.append(t)  # normal slot riding along
                        dead = False
                        if req.constrain is not None:
                            nstate = req.constrain.advance(req.cstate, t)
                            if nstate is not None:
                                # (None only for an EOS outside an accept
                                # state, which the mask already forbids —
                                # _deliver maps stop ids to "stop" below)
                                req.cstate = nstate
                            dead = not req.constrain.live(req.cstate)
                        if req.want_logprobs:
                            reason = self._deliver(
                                req, t, logprob=float(lps[slot]),
                                top_ids=tis[slot].tolist(),
                                top_lps=tls[slot].tolist(),
                            )
                        else:
                            reason = self._deliver(req, t)
                        if reason is None and dead:
                            # the DFA can extend the document no further:
                            # the constrained output is complete
                            reason = "stop"
                        if reason is not None:
                            self._ledger_finalize(req, "served")
                            self._tenant_served(req)
                            finish_slot(slot)  # free BEFORE the end event
                            req.emit("end", reason)
                    except Exception:  # noqa: BLE001 — dead client
                        log.exception("delivery failed; dropping slot %d", slot)
                        self._ledger_finalize(req, "cancelled")
                        finish_slot(slot)
            else:
                _, firsts_ref, rows = rec
                ids = np.asarray(firsts_ref)
                for row, slot, req in rows:
                    if self._slots[slot] is not req:
                        continue
                    if req.cancelled:
                        self._ledger_finalize(
                            req, "deadline_abort" if req.deadline_hit else "cancelled"
                        )
                        finish_slot(slot)
                        self.stats.record_cancel(
                            "deadline" if req.deadline_hit else "admit"
                        )
                        continue
                    if req.is_ext and not req.rewound:
                        # the rewind trick: the fused admit sampled token 0
                        # without mask or logprob readback — drop it, step
                        # the slot back one position, and put prompt[-1]
                        # back on the device carry. The next ext step
                        # re-processes prompt[-1] at position n-1 (the KV
                        # write repeats identical values; CoW privatizes any
                        # shared block first) and samples the REAL first
                        # token under the mask. host_steps resets to 0 so
                        # the delivered token 0 consumes rng (seed, step 0)
                        # exactly like an unconstrained first token would.
                        req.rewound = True
                        host_pos[slot] -= 1
                        host_steps[slot] = 0
                        tok_dev = tok_dev.at[slot].set(
                            jnp.int32(req.prompt_ids[-1])
                        )
                        dirty = True
                        continue
                    try:
                        first = int(ids[row])
                        reason = self._deliver(req, first)
                        if reason is not None:
                            self._ledger_finalize(req, "served")
                            self._tenant_served(req)
                            finish_slot(slot)  # free BEFORE the end event
                            req.emit("end", reason)
                        elif spec is not None:
                            # history = prompt + the first sampled token
                            # (still riding the device carry, unwritten)
                            spec_slots[slot] = make_slot(
                                req.prompt_ids, first, spec
                            )
                    except Exception:  # noqa: BLE001 — dead client
                        log.exception("delivery failed; dropping slot %d", slot)
                        self._ledger_finalize(req, "cancelled")
                        finish_slot(slot)

        def pump(depth: int = 1) -> None:
            """Process oldest readbacks until at most ``depth`` dispatches
            remain in flight (depth 1 = one burst computing while the host
            delivers the previous one; depth 0 = fully drained)."""
            while len(inflight) > depth or (inflight and not active()):
                process_record(inflight.popleft())

        def drain_cancels(waitlist: list[_Request]) -> None:
            """Free slots / queue entries of consumer-gone requests. Runs
            once per main-loop iteration, so an active stream's slot is
            reclaimed within ~one decode burst of the cancel. Requests still
            in the inbox are dropped at intake via their flag; a request
            cancelled mid-group-admit is caught at first delivery (both
            paths count stats.cancelled exactly once — each checks the slot
            ownership before freeing)."""
            while True:
                try:
                    req = self._cancels.get_nowait()
                except _queue.Empty:
                    return
                if 0 <= req.slot < B and self._slots[req.slot] is req:
                    self._ledger_finalize(
                        req, "deadline_abort" if req.deadline_hit else "cancelled"
                    )
                    finish_slot(req.slot)
                    self.stats.record_cancel("active")
                elif req in waitlist:
                    waitlist.remove(req)
                    self.stats.record_cancel("waitlist")

        def maybe_compact() -> None:
            """Re-roll a wrapped ring when the live window is small enough
            that bounded reads pay for the one-off 2x-cache HBM roll. After
            the roll the head sits at max(live pos) and windowed attention
            resumes; re-triggering needs another full wrap, so the cost is
            amortized over >= (max_seq - head) decode steps."""
            nonlocal K, V
            if not self._ring_wrapped:
                return
            act = active()
            if not act:
                return
            head = max(host_pos[i] for i in act)
            if self._bucket(head + self.decode_burst) > self.max_seq // 2:
                return  # window too wide to be worth the roll yet
            shift = (head - self._ring_next) % self.max_seq
            K, V = self._compact_ring(K, V, jnp.int32(shift))
            self._ring_next = head
            self._ring_wrapped = False
            self.stats.ring_compactions += 1
            obs_emit("ring_compaction", shift=shift, head=head, active=len(act))

        def refresh_rows() -> None:
            """Re-upload the per-slot sampling tensors and pos/step/seed
            carries after a membership change (``dirty``)."""
            nonlocal temp, topk, topp, pos_dev, steps_dev, seeds_dev, dirty
            if not dirty:
                return
            live = [r if isinstance(r, _Request) else None for r in self._slots]
            temp = jnp.asarray(
                [r.sp.temperature if r else 0.0 for r in live], jnp.float32
            )
            topk = jnp.asarray([r.sp.top_k if r else 0 for r in live], jnp.int32)
            topp = jnp.asarray([r.sp.top_p if r else 1.0 for r in live], jnp.float32)
            pos_dev = jnp.asarray(host_pos, jnp.int32)
            steps_dev = jnp.asarray(host_steps, jnp.int32)
            seeds_dev = jnp.asarray(host_seed, jnp.int32)
            dirty = False

        def decode_once() -> None:
            """Dispatch one decode burst (decode_burst steps) for every
            active slot. Does NOT read the tokens back — the record goes on
            the in-flight queue and pump() delivers it while the next burst
            computes."""
            nonlocal K, V, tok_dev, dirty
            nonlocal pos_dev, steps_dev, seeds_dev
            act = active()
            if not act:
                return
            # charge this burst (and its CoW/alloc side dispatches) to the
            # active requests; restore the previous context because decode
            # interleaves inside admit chunk loops
            prev_ctx = self._charge_ctx
            self._charge_ctx = tuple(
                r for r in (self._slots[i] for i in act) if isinstance(r, _Request)
            )
            refresh_rows()
            # cap the burst so no active row can run past the cache capacity.
            # n is a static jit arg: snap to single steps near capacity
            # instead of counting down through n-1 fresh compiles.
            # NOTE: with the depth-2 pipeline, host_pos may TRANSIENTLY sit at
            # or past max_seq for a row whose terminal burst is still awaiting
            # readback (the delivery in process_record ends it with "length").
            # Those zombie steps are safe: the ring mask's mod-S arithmetic
            # degrades to full-window attention once start_pos >= max_seq, so
            # the extra decode computes a token nobody delivers — headroom
            # may be <= 0 here and n=1 covers it.
            headroom = self.max_seq - 1 - max(host_pos[i] for i in act)
            # brownout shrinks the burst (shorter dispatch windows → faster
            # shed/abort reaction under pressure); n stays a static jit arg
            # from a tiny set {burst, burst//2, 1}, so compiles stay bounded
            burst = (
                self.brownout.effective_burst(self.decode_burst)
                if self.brownout is not None
                else self.decode_burst
            )
            n = burst if headroom >= burst else 1
            if paged:
                # grow each row's table to cover its writes, privatize any
                # still-shared block in the write range (CoW), then decode
                # through the gathered block-table view. The view extent
                # nb*T rides the SAME pow2 ladder as the contiguous
                # positional window, so softmax reduction extents match
                # bit-for-bit.
                if not grow_for_burst(act, lambda i: host_pos[i] + n, prev_ctx):
                    return
                refresh_tables()
                if use_pallas:
                    self._note_compile("decode_pallas", n)
                    toks, K, V, tok_dev, pos_dev, steps_dev = (
                        self._decode_pos_pallas(
                            self.params, tok_dev, K, V, tbl_dev, pos_dev,
                            seeds_dev, steps_dev, temp, topk, topp, n,
                            _tokens=len(act) * n,
                        )
                    )
                else:
                    nb = paged_window(max(host_pos[i] for i in act) + n + 1)
                    self._note_compile("decode_pos_paged", n, nb)
                    toks, K, V, tok_dev, pos_dev, steps_dev = (
                        self._decode_pos_paged(
                            self.params, tok_dev, K, V, tbl_dev, pos_dev,
                            seeds_dev, steps_dev, temp, topk, topp, n, nb,
                            _tokens=len(act) * n,
                        )
                    )
            elif positional:
                # writes land at each row's own position: the window only
                # needs to cover the highest live position after the burst
                # (pow2 ladder, same bounded-compile argument as prefill)
                w = self._win_bucket(max(host_pos[i] for i in act) + n + 1)
                window = w if w < self.max_seq else None
                self._note_compile("decode_pos", n, window)
                toks, K, V, tok_dev, pos_dev, steps_dev = self._decode_pos(
                    self.params, tok_dev, K, V, pos_dev,
                    seeds_dev, steps_dev, temp, topk, topp, n, window,
                    _tokens=len(act) * n,
                )
            else:
                # until the ring wraps, every live slot index is < ring_next:
                # attention can read just a bucket covering the head (static
                # windows come from self.buckets, so compiles stay bounded)
                window = None
                if not self._ring_wrapped:
                    w = self._bucket(self._ring_next + n)
                    if w < self.max_seq:
                        window = w
                self._note_compile("decode", n, window)
                toks, K, V, tok_dev, pos_dev, steps_dev = self._decode(
                    self.params, tok_dev, K, V, pos_dev, jnp.int32(self._ring_next),
                    seeds_dev, steps_dev, temp, topk, topp, n, window,
                    _tokens=len(act) * n,
                )
                if self._ring_next + n >= self.max_seq:
                    self._ring_wrapped = True
                self._ring_next = (self._ring_next + n) % self.max_seq
            self.stats.steps += n
            self.stats.tokens_per_step.record(float(len(act)))
            for i in act:
                host_pos[i] += n
                host_steps[i] += n
            inflight.append(
                ("decode", toks, n, [(i, self._slots[i]) for i in act], time.monotonic())
            )
            self._charge_ctx = prev_ctx

        def decode_ext_once() -> None:
            """Dispatch ONE masked single-step decode covering every active
            slot (the ext regime). Constrained rows carry their DFA state's
            vocab mask; every other row gets all-True (a bitwise no-op
            inside _pick). Single-step because the mask for step i+1 is a
            host-side DFA walk over the token chosen at step i — the caller
            runs depth-0 (pump(0) before and after) for the same reason."""
            nonlocal K, V, tok_dev, dirty
            nonlocal pos_dev, steps_dev, seeds_dev
            act = active()
            if not act:
                return
            prev_ctx = self._charge_ctx
            self._charge_ctx = tuple(
                r for r in (self._slots[i] for i in act) if isinstance(r, _Request)
            )
            refresh_rows()
            mask = np.ones((B, cfg.vocab_size), dtype=bool)
            for i in act:
                r = self._slots[i]
                if isinstance(r, _Request) and r.constrain is not None:
                    dm = r.constrain.mask(r.cstate)
                    mask[i, :] = False
                    mask[i, : dm.shape[0]] = dm
            mask_dev = jnp.asarray(mask)
            if paged:
                if not grow_for_burst(act, lambda i: host_pos[i] + 1, prev_ctx):
                    return
                refresh_tables()
                if use_pallas:
                    self._note_compile("decode_pallas_ext")
                    (toks, lps, top_ids, top_lps, K, V, tok_dev, pos_dev,
                     steps_dev) = self._decode_pos_pallas_ext(
                        self.params, tok_dev, K, V, tbl_dev, pos_dev,
                        seeds_dev, steps_dev, temp, topk, topp, mask_dev,
                        _tokens=len(act),
                    )
                else:
                    nb = paged_window(max(host_pos[i] for i in act) + 2)
                    self._note_compile("decode_pos_paged_ext", nb)
                    (toks, lps, top_ids, top_lps, K, V, tok_dev, pos_dev,
                     steps_dev) = self._decode_pos_paged_ext(
                        self.params, tok_dev, K, V, tbl_dev, pos_dev,
                        seeds_dev, steps_dev, temp, topk, topp, mask_dev, nb,
                        _tokens=len(act),
                    )
            else:
                w = self._win_bucket(max(host_pos[i] for i in act) + 2)
                window = w if w < self.max_seq else None
                self._note_compile("decode_pos_ext", window)
                (toks, lps, top_ids, top_lps, K, V, tok_dev, pos_dev,
                 steps_dev) = self._decode_pos_ext(
                    self.params, tok_dev, K, V, pos_dev,
                    seeds_dev, steps_dev, temp, topk, topp, mask_dev, window,
                    _tokens=len(act),
                )
            self.stats.steps += 1
            self.stats.tokens_per_step.record(float(len(act)))
            for i in act:
                host_pos[i] += 1
                host_steps[i] += 1
            inflight.append(
                ("ext", toks, lps, top_ids, top_lps,
                 [(i, self._slots[i]) for i in act], time.monotonic())
            )
            self._charge_ctx = prev_ctx

        def spec_once() -> bool:
            """Dispatch ONE verify forward when at least one live slot has a
            prompt-lookup draft. Returns False (caller runs a plain burst)
            when nothing drafted, a row is too close to the cache end for a
            width-(k+1) write, or there are no active slots. The caller must
            have DRAINED the pipeline first (proposals read each slot's full
            token history, which is only current after every readback) and
            must drain again right after (host pos catches up at readback)."""
            nonlocal K, V, tok_dev, dirty, pos_dev, steps_dev, seeds_dev
            act = active()
            if not act:
                return False
            kspec = spec.k
            if max(host_pos[i] for i in act) + kspec + 1 >= self.max_seq:
                # the per-row cache write would clamp past the end; the
                # plain burst path's n=1 capacity snap handles the tail
                return False
            drafts = np.zeros((B, kspec), np.int32)
            dlens = [0] * B
            total = 0
            for i in act:
                st = spec_slots[i]
                if st is None:
                    continue  # admit readback pending (caller drains first)
                d = st.index.propose(kspec)
                if d:
                    drafts[i, : len(d)] = d
                    dlens[i] = len(d)
                    total += len(d)
            if total == 0:
                return False  # nothing to verify: a plain burst is cheaper
            prev_ctx = self._charge_ctx
            self._charge_ctx = tuple(
                r for r in (self._slots[i] for i in act) if isinstance(r, _Request)
            )
            refresh_rows()
            if paged:
                if not grow_for_burst(
                    act, lambda i: host_pos[i] + kspec + 1, prev_ctx
                ):
                    return False  # slot list is stale; plain burst re-scans
                refresh_tables()
                if use_pallas:
                    self._note_compile("spec_verify_pallas", kspec)
                    out, nacc, K, V, tok_dev, pos_dev, steps_dev = (
                        self._spec_verify_pallas(
                            self.params, tok_dev, K, V, tbl_dev, pos_dev,
                            jnp.asarray(drafts), jnp.asarray(dlens, jnp.int32),
                            seeds_dev, steps_dev, temp, topk, topp,
                            _tokens=len(act) * (kspec + 1),
                        )
                    )
                else:
                    nb = paged_window(max(host_pos[i] for i in act) + kspec + 1)
                    self._note_compile("spec_verify_paged", nb)
                    out, nacc, K, V, tok_dev, pos_dev, steps_dev = (
                        self._spec_verify_paged(
                            self.params, tok_dev, K, V, tbl_dev, pos_dev,
                            jnp.asarray(drafts), jnp.asarray(dlens, jnp.int32),
                            seeds_dev, steps_dev, temp, topk, topp, nb,
                            _tokens=len(act) * (kspec + 1),
                        )
                    )
            else:
                w = self._win_bucket(max(host_pos[i] for i in act) + kspec + 1)
                window = w if w < self.max_seq else None
                self._note_compile("spec_verify", window)
                out, nacc, K, V, tok_dev, pos_dev, steps_dev = self._spec_verify(
                    self.params, tok_dev, K, V, pos_dev,
                    jnp.asarray(drafts), jnp.asarray(dlens, jnp.int32),
                    seeds_dev, steps_dev, temp, topk, topp, window,
                    _tokens=len(act) * (kspec + 1),
                )
            self.stats.steps += 1
            self.stats.spec_verifies += 1
            self.stats.tokens_per_step.record(float(len(act)))
            for i in act:
                # rng streams advance by the verify width for every row
                # (deterministic, matches the device carry); host_pos
                # advances at READBACK — acceptance is data-dependent
                host_steps[i] += kspec + 1
            inflight.append((
                "spec", out, nacc,
                [(i, self._slots[i], dlens[i]) for i in act],
                time.monotonic(),
            ))
            self._charge_ctx = prev_ctx
            return True

        pc = self.prefix_cache

        def harvest_prefix(prompt_ids, kc, vc, row, chunk_logits,
                           skip_chunks: int = 0,
                           slot: int | None = None) -> None:
            """Insert the prompt's full-chunk KV blocks into the prefix
            cache, gathered from the transient row cache ``kc``/``vc`` at
            ``row``. MUST run before the donating finish dispatch consumes
            the transient (program order on the single device stream keeps
            the eager gather slices ahead of it). Insertion happens at
            ADMIT time, not completion — the blocks exist right here in
            un-rolled chunk-aligned layout, and a same-prefix burst already
            hits on its second member; gathering at completion would mean
            un-rolling them back out of the shared ring. ``skip_chunks``
            leading chunks were themselves cache hits: their nodes already
            exist, so None placeholders skip the gather."""
            if pc is None:
                return
            if self.brownout is not None and self.brownout.pause_prefix_harvest:
                return  # browned out: admits stop paying the block copy-out
            C = self.prefill_chunk
            n_full = len(prompt_ids) // C
            if n_full <= skip_chunks:
                return
            if paged and slot is not None:
                # zero-copy harvest: the cache nodes hold pool BLOCK IDS
                # (refcount bumps in acquire_fn), not device copies — the
                # KV bytes already live in the slot's blocks. Epoch-tagged
                # so payloads from before a pool reset free as no-ops.
                nbc = C // T
                tbl = tables[slot]
                payloads: list = [None] * skip_chunks
                for j in range(skip_chunks, n_full):
                    ids = tbl[j * nbc : (j + 1) * nbc]
                    payloads.append(
                        (pool.epoch, list(ids)) if len(ids) == nbc else None
                    )
                pc.insert(
                    list(prompt_ids[: n_full * C]), payloads, chunk_logits
                )
                return
            blocks: list = [None] * skip_chunks
            for j in range(skip_chunks, n_full):
                blocks.append(self._shard_block(
                    kv_gather_block(kc, row, j * C, C),
                    kv_gather_block(vc, row, j * C, C),
                ))
            pc.insert(list(prompt_ids[: n_full * C]), blocks, chunk_logits)

        def _host_kv(x):
            """Device block view -> host leaves (KVQ ships as a pair)."""
            if is_quantized(x):
                return (np.asarray(x.q), np.asarray(x.s))
            return np.asarray(x)

        def _dev_kv(leaf):
            """Host leaves -> the row shape kv_pool_write_row wants."""
            if isinstance(leaf, tuple):
                q, s = leaf
                return KVQ(q=jnp.asarray(np.asarray(q)),
                           s=jnp.asarray(np.asarray(s)))
            return jnp.asarray(np.asarray(leaf))

        if tier is not None and pc is not None and paged:
            def _demote_chunk(token_ids, payload, logits) -> bool:
                """Prefix-cache eviction hook (owner thread, pc lock held):
                read the evicted node's pool blocks back to host in one
                batched gather and hand them to the tier manager — LRU
                eviction becomes demotion. False (plain eviction) for
                payloads that survived a pool reset: their ids reference
                recycled blocks."""
                ep, ids = payload
                if ep != pool.epoch:
                    return False
                bids = jnp.asarray(ids, jnp.int32)
                k_host = _host_kv(kv_pool_read_blocks(K, bids))
                v_host = _host_kv(kv_pool_read_blocks(V, bids))
                return tier.demote(token_ids, k_host, v_host, logits)

            pc.demote_fn = _demote_chunk

        def suspend_slot(i: int, reason: str,
                         min_blocks: int | None = None) -> bool:
            """Demote slot i (KV blocks + full resume state) to the host
            side and free the slot — swap-don't-shed. Returns False with
            the slot untouched when it is not suspendable (mid-admit, no
            tier-consistent state, readback failure); the caller falls back
            to the existing shed path. Chaos hook: a ``raise`` rule at
            SUSPEND is a worker dying mid-suspend (pump crash, supervisor
            restart); any other kind aborts the suspend before any state
            has moved."""
            if not suspend_on:
                return False
            req = self._slots[i]
            if not isinstance(req, _Request) or req.cancelled:
                return False
            if _faults.ACTIVE is not None:
                f = _faults.ACTIVE.check(_faults.SUSPEND)
                if f is not None:
                    self._suspend_stats["suspend_failures"] += 1
                    if f.kind == "raise":
                        raise f.exception()
                    return False
            # drain every in-flight dispatch first: delivered tokens,
            # positions and rng step counters must agree before the state
            # is frozen (a pending burst would deliver tokens the captured
            # state does not cover)
            pump(0)
            req = self._slots[i]
            if not isinstance(req, _Request) or req.cancelled:
                return False  # finished or cancelled during the drain
            hist = len(req.prompt_ids) + len(req.emitted)
            if hist != host_pos[i] + 1 or not tables[i]:
                # a state the resume path cannot rebuild exactly (e.g. a
                # reserved/partial admit): refuse rather than resume wrong
                self._suspend_stats["suspend_failures"] += 1
                return False
            try:
                bids = jnp.asarray(tables[i], jnp.int32)
                k_host = _host_kv(kv_pool_read_blocks(K, bids))
                v_host = _host_kv(kv_pool_read_blocks(V, bids))
            except Exception:  # noqa: BLE001 — readback failed; keep in HBM
                log.exception("suspend readback failed; slot %d stays", i)
                self._suspend_stats["suspend_failures"] += 1
                return False
            srec = _Suspended(
                req=req, k=k_host, v=v_host, n_blocks=len(tables[i]),
                pos=host_pos[i], steps=host_steps[i], seed=host_seed[i],
                spec=spec_slots[i], t_suspend=time.monotonic(),
                reason=reason, min_blocks=min_blocks,
            )
            finish_slot(i)  # decrefs the blocks; the host copy owns the KV
            self._suspended.append(srec)
            self._suspend_stats["suspended_total"] += 1
            if reason == "preempted":
                # the victim is parked, not lost — this counts preemption
                # events per tenant (noisy-neighbor diagnosis), not sheds
                self.tenant_stats.record_preempted(req.tenant)
            obs_emit(
                "slot_suspend", slot=i, reason=reason, pos=srec.pos,
                generated=req.generated, blocks=srec.n_blocks,
            )
            return True

        def suspend_victim(below_rank: int | None = None,
                           reason: str = "kv_pool") -> bool:
            """Suspend the victim slot whose demotion frees the most pool
            blocks (falling through candidates a drain disqualifies),
            lowest priority class first — under uniform class this is
            exactly the pre-QoS largest-table-first sweep. ``below_rank``
            restricts candidates to strictly-lower classes (preemption on
            behalf of a higher-class admit). False when nothing is
            suspendable."""
            cand = sorted(
                (i for i, r in enumerate(self._slots)
                 if isinstance(r, _Request) and not r.cancelled and tables[i]
                 and (below_rank is None or r.rank < below_rank)),
                key=lambda i: (self._slots[i].rank, -len(tables[i])),
            )
            for i in cand:
                if suspend_slot(i, reason):
                    return True
            return False

        def resume_suspended() -> None:
            """Re-admit suspended slots (oldest first) while free slots and
            pool blocks allow. Bit-identical resume: the host KV copies are
            written into freshly allocated blocks, the pos/rng-step/seed
            mirrors are restored, and the device carry token is re-seeded
            from the delivered-token tail — the next decode step computes
            exactly what it would have without the suspension."""
            nonlocal K, V, tok_dev, dirty, table_dirty
            if not self._suspended:
                return
            bo = self.brownout
            if bo is not None and bo.level >= SHED_ONLY:
                return  # still inside the incident window that parked them
            pending = self._suspended
            while pending and None in self._slots:
                rec = pending[0]
                req = rec.req
                if req.cancelled:
                    pending.pop(0)
                    self._ledger_finalize(
                        req,
                        "deadline_abort" if req.deadline_hit else "cancelled",
                    )
                    self.stats.record_cancel("active")
                    continue
                if pool.free_blocks < rec.min_blocks:
                    # growth-parked slots wait for headroom beyond their
                    # own tables (see _Suspended.min_blocks); reclaim the
                    # evictable cache toward it like alloc_blocks would
                    if pc is not None:
                        pc.reclaim(
                            rec.min_blocks - pool.free_blocks,
                            demote=tier is not None,
                        )
                    if pool.free_blocks < rec.min_blocks:
                        return  # pool still tight; retry next tick
                try:
                    # internal: a resume must never suspend another slot to
                    # make room (thrash), and a full pool is a deferral, not
                    # a shed
                    ids = alloc_blocks(rec.n_blocks, internal=True)
                except _PoolExhausted:
                    return  # pool still tight; retry next tick
                slot = self._slots.index(None)
                try:
                    bids = jnp.asarray(ids, jnp.int32)
                    K = kv_pool_write_row(K, _dev_kv(rec.k), bids)
                    V = kv_pool_write_row(V, _dev_kv(rec.v), bids)
                    if self.mesh is not None:
                        # same re-pin as control_import: the eager writes
                        # may lose the pool sharding the donated dispatches
                        # were compiled for
                        from ..parallel.sharding import pool_spec, shard_cache

                        K, V = shard_cache(
                            K, V, self.mesh, cfg=cfg,
                            spec=pool_spec(self.mesh, cfg),
                        )
                except Exception as e:  # noqa: BLE001 — host copy unusable
                    pool.decref(ids)
                    pending.pop(0)
                    self._suspend_stats["suspend_failures"] += 1
                    self._ledger_finalize(req, "failed")
                    try:
                        req.emit("err", BatcherOverloaded(
                            f"resume failed after {req.generated} tokens "
                            f"({e}); retry on another worker"
                        ))
                    except Exception:  # noqa: BLE001 — dead client loop
                        pass
                    continue
                pending.pop(0)
                tables[slot] = list(ids)
                table_dirty = True
                req.slot = slot
                self._slots[slot] = req
                host_pos[slot] = rec.pos
                host_steps[slot] = rec.steps
                host_seed[slot] = rec.seed
                spec_slots[slot] = rec.spec
                carry = req.emitted[-1] if req.emitted else req.prompt_ids[-1]
                tok_dev = tok_dev.at[slot].set(jnp.int32(carry))
                dirty = True
                self._suspend_stats["resumed_total"] += 1
                obs_emit(
                    "slot_resume", slot=slot, reason=rec.reason, pos=rec.pos,
                    generated=req.generated,
                    suspended_ms=round(
                        (time.monotonic() - rec.t_suspend) * 1e3, 1
                    ),
                )

        def promote_from_tier(prompt_ids) -> None:
            """Pull host/spill-tier chunks that EXTEND this prompt's cached
            prefix back into the pool + radix cache (promotion-on-hit), so
            the match that follows resumes from the deepest tier-covered
            chunk. Bounded by ``tier.promote_chunks`` per admit; exhaustion
            or any failure leaves the cache exactly as it was (fresh
            allocations are dropped, survivors are owned by acquire_fn)."""
            nonlocal K, V
            if tier is None or pc is None or tier.promote_chunks <= 0:
                return
            C = self.prefill_chunk
            n_full = len(prompt_ids) // C
            have = pc.peek(prompt_ids) // C
            if n_full <= have:
                return
            nbc = C // T
            token_ids = [int(t) for t in prompt_ids[: n_full * C]]
            payloads: list = [None] * have
            logits_list: list = [None] * have
            alloc: list[int] = []
            found = 0
            try:
                for j in range(have, min(n_full, have + tier.promote_chunks)):
                    ent = tier.lookup(tuple(token_ids[: (j + 1) * C]))
                    if ent is None:
                        break
                    ids = alloc_blocks(nbc, internal=True)
                    alloc.extend(ids)
                    bids = jnp.asarray(ids, jnp.int32)
                    K = kv_pool_write_row(K, _dev_kv(ent.k), bids)
                    V = kv_pool_write_row(V, _dev_kv(ent.v), bids)
                    payloads.append((pool.epoch, list(ids)))
                    logits_list.append(
                        None if ent.logits is None
                        else jnp.asarray(ent.logits, jnp.float32)
                    )
                    found += 1
            except _PoolExhausted:
                pass  # promote what fit; the admit itself decides the rest
            except Exception:  # noqa: BLE001 — promotion is best-effort
                log.exception("tier promotion failed; continuing without")
                if alloc:
                    pool.decref(alloc)
                return
            if found == 0:
                if alloc:
                    pool.decref(alloc)
                return
            if self.mesh is not None:
                from ..parallel.sharding import pool_spec, shard_cache

                K, V = shard_cache(
                    K, V, self.mesh, cfg=cfg,
                    spec=pool_spec(self.mesh, cfg),
                )
            pc.insert(token_ids[: (have + found) * C], payloads, logits_list)
            # acquire_fn holds the surviving refs; these fresh ones drop
            # (mirrors control_import — an insert cut short frees everything)
            pool.decref(alloc)
            tier.note_promoted(found)

        def suspend_harvest() -> dict:
            """Drain-path zero-lost-work: fold every active slot's full
            token history (whole chunks of prompt + generated KV, already
            sitting in pool blocks) into the radix prefix cache, then fail
            the request with the retryable draining envelope. The warm
            handoff that follows (worker.begin_drain) ships these chunks to
            the survivor, so the client's retry admits as a prefix hit that
            covers the generated tokens too — not a from-scratch prefill."""
            pump(0)
            done = 0
            cached_tokens = 0
            C = self.prefill_chunk
            nbc = C // T if (paged and T) else 0
            for i in range(B):
                req = self._slots[i]
                if not isinstance(req, _Request):
                    continue
                if pc is not None and nbc and not req.cancelled:
                    hist = list(req.prompt_ids) + [
                        int(t) for t in req.emitted
                    ]
                    n_full = min(host_pos[i], len(hist)) // C
                    if n_full > 0:
                        tbl = tables[i]
                        payloads: list = []
                        for j in range(n_full):
                            ids = tbl[j * nbc : (j + 1) * nbc]
                            payloads.append(
                                (pool.epoch, list(ids))
                                if len(ids) == nbc else None
                            )
                        try:
                            pc.insert(
                                hist[: n_full * C], payloads, [None] * n_full
                            )
                            cached_tokens += n_full * C
                        except Exception:  # noqa: BLE001 — best-effort
                            log.exception("suspend-harvest insert failed")
                self._ledger_finalize(req, "served")
                finish_slot(i)
                try:
                    req.emit("err", BatcherOverloaded(
                        f"worker draining; {req.generated} generated tokens "
                        f"cached for warm handoff; retry on another worker"
                    ))
                except Exception:  # noqa: BLE001 — dead client loop
                    pass
                done += 1
            return {"slots": done, "tokens": cached_tokens}

        def control_export(args) -> dict | None:
            """Owner-thread half of disaggregated PREFILL: gather the
            prompt's cached full-chunk KV blocks (plus chunk-end logits)
            to host arrays for shipment to a decode peer. None means
            nothing useful is cached — the decode side falls back to
            local prefill, which is always correct."""
            if not paged or pc is None:
                return None
            prompt_ids = args["prompt_ids"]
            C = self.prefill_chunk
            if len(prompt_ids) < C:
                return None
            hit = pc.match(prompt_ids)
            if hit is None:
                return None
            try:
                if any(
                    p2 is None or p2[0] != pool.epoch for p2 in hit.payloads
                ):
                    # survived a pool reset: the ids reference recycled blocks
                    return None
                chunks = []
                for j, (_, ids) in enumerate(hit.payloads):
                    bids = jnp.asarray(ids, jnp.int32)
                    lg = hit.nodes[j].logits
                    chunks.append({
                        "k": _host_kv(kv_pool_read_blocks(K, bids)),
                        "v": _host_kv(kv_pool_read_blocks(V, bids)),
                        "logits": None if lg is None
                        else np.asarray(lg, np.float32).reshape(-1),
                    })
                return {
                    "token_ids": [int(t) for t in prompt_ids[: hit.tokens]],
                    "chunk_tokens": C,
                    "chunks": chunks,
                }
            finally:
                pc.release(hit)

        def control_import(args) -> dict:
            """Owner-thread half of disaggregated DECODE: write the
            transferred chunks into freshly allocated pool blocks and
            seed the prefix cache, so the request that follows admits as
            a prefix hit. The import's own allocation refs are dropped
            once the cache's acquire_fn holds the surviving ones; a
            _PoolExhausted (decode-pool exhaustion) frees everything
            allocated so far and propagates cleanly."""
            nonlocal K, V
            if not paged or pc is None:
                raise ValueError(
                    "kv import requires paged KV and a prefix cache"
                )
            export = args["export"]
            C = self.prefill_chunk
            if int(export["chunk_tokens"]) != C:
                raise ValueError(
                    f"prefill-chunk mismatch: export C="
                    f"{export['chunk_tokens']}, local C={C}"
                )
            token_ids = [int(t) for t in export["token_ids"]]
            n_full = min(len(token_ids) // C, len(export["chunks"]))
            if n_full <= 0:
                return {"tokens": 0, "blocks": 0}
            nbc = C // T
            alloc: list[int] = []
            payloads: list = []
            logits_list: list = []
            try:
                for j in range(n_full):
                    ch = export["chunks"][j]
                    ids = alloc_blocks(nbc)
                    alloc.extend(ids)
                    bids = jnp.asarray(ids, jnp.int32)
                    K = kv_pool_write_row(K, _dev_kv(ch["k"]), bids)
                    V = kv_pool_write_row(V, _dev_kv(ch["v"]), bids)
                    payloads.append((pool.epoch, list(ids)))
                    lg = ch.get("logits")
                    logits_list.append(
                        None if lg is None
                        else jnp.asarray(
                            np.asarray(lg), jnp.float32
                        ).reshape(1, 1, -1)
                    )
            except BaseException:
                if alloc:
                    pool.decref(alloc)
                raise
            if self.mesh is not None:
                # the eager .at[].set updates may lose the pool sharding;
                # re-pin so later donated dispatches see the layout they
                # were compiled for
                from ..parallel.sharding import pool_spec, shard_cache

                K, V = shard_cache(
                    K, V, self.mesh, cfg=cfg,
                    spec=pool_spec(self.mesh, cfg),
                )
            pc.insert(token_ids[: n_full * C], payloads, logits_list)
            # the cache's acquire_fn holds the surviving refs (a chunk
            # whose node already existed stays owned by that node; these
            # fresh blocks free right here)
            pool.decref(alloc)
            return {"tokens": n_full * C, "blocks": len(alloc)}

        def run_control(op: _ControlOp) -> None:
            """Execute one inbox control op inline; failures return to the
            waiting caller and never crash the pump."""
            self.heartbeat = time.monotonic()
            if op.cancelled:  # submitter timed out; nobody reads the result
                op.finish(error=TimeoutError("control op abandoned"))
                return
            try:
                if op.kind == "export":
                    op.finish(result=control_export(op.args))
                elif op.kind == "import":
                    op.finish(result=control_import(op.args))
                elif op.kind == "suspend_harvest":
                    op.finish(result=suspend_harvest())
                else:
                    op.finish(error=ValueError(
                        f"unknown control op {op.kind!r}"
                    ))
            except Exception as e:  # noqa: BLE001 — caller's error, not ours
                op.finish(error=e)

        def admit_paged(req: _Request, slot: int, n: int, seed: int,
                        samp) -> jax.Array:
            """Paged admit: allocate the slot's block table up front (raising
            _PoolExhausted BEFORE any device dispatch), run the same
            short/hit/flash/chunked prefill regimes as the legacy path, and
            land the KV in pool blocks. A FULL prefix hit appends the cached
            blocks to the table with no copy at all — refcount bumps plus
            one sample from the stored prompt-end logits."""
            nonlocal K, V, tok_dev, table_dirty
            C = self.prefill_chunk
            if n <= C:
                bucket = self._bucket(n)
                ids = alloc_blocks(-(-n // T), for_req=req)
                tables[slot] = ids
                table_dirty = True
                bids = ids + [0] * (max(1, bucket // T) - len(ids))
                tokens = jnp.asarray(
                    [req.prompt_ids + [0] * (bucket - n)], jnp.int32
                )
                first, K, V, tok_dev = self._admit_fused_paged(
                    self.params, K, V, tok_dev, tokens, jnp.int32(n),
                    jnp.asarray(bids, jnp.int32), jnp.int32(slot), *samp,
                    _tokens=n, _name=self._ring_name("admit_fused_paged", bucket),
                )
                return first
            # long prompt: same regime choices as the legacy path (see
            # admit_one's comment), but prefix-hit resume references cached
            # POOL blocks instead of copying them into the row
            n_full = n // C
            nbc = C // T
            chunk_logits = [None] * n_full if pc is not None else None
            # promotion-on-hit: chunks the HBM cache evicted to the host /
            # Object Store tiers come back into the pool before the match,
            # so the hit below covers the deepest tier-resident prefix
            promote_from_tier(req.prompt_ids)
            hit = pc.match(req.prompt_ids) if pc is not None else None
            if hit is not None and any(
                p2 is None or p2[0] != pool.epoch for p2 in hit.payloads
            ):
                # survived a pool reset: the ids reference recycled blocks
                pc.release(hit)
                hit = None
            if (
                hit is not None
                and not active()
                and cfg.use_flash_attention
                and 2 * hit.tokens < n
            ):
                pc.release(hit)
                hit = None
            k1 = v1 = None
            try:
                if hit is not None:
                    p = hit.tokens
                    prefix_ids: list[int] = []
                    for _, ids in hit.payloads:
                        prefix_ids.extend(ids)
                    pool.incref(prefix_ids)
                    tables[slot] = list(prefix_ids)
                    table_dirty = True
                    obs_emit(
                        "prefix_hit", tokens=p, prompt=n, full=(p == n),
                    )
                    if p == n:
                        # FULL hit: zero block copies, zero prefill flops
                        first, tok_dev = self._sample_first(
                            tok_dev, hit.end_logits, jnp.int32(slot), *samp,
                        )
                        return first
                    k1, v1 = self._make_row_cache(1, self.max_seq)
                    for j in range(p // C):
                        k1, v1 = self._fill_row_chunk(
                            k1, v1, K, V,
                            jnp.asarray(
                                prefix_ids[j * nbc : (j + 1) * nbc],
                                jnp.int32,
                            ),
                            jnp.int32(j * C),
                        )
                    for start in range(p, n, C):
                        chunk = req.prompt_ids[start : start + C]
                        chunk = chunk + [0] * (C - len(chunk))
                        logits, k1, v1 = self._prefill1(
                            self.params, jnp.asarray([chunk], jnp.int32),
                            k1, v1,
                            jnp.full((1,), start, jnp.int32),
                            jnp.asarray(
                                [min(n - 1 - start, C - 1)], jnp.int32
                            ),
                            self._win_bucket(start + C),
                            _tokens=min(C, n - start),
                        )
                        if start + C <= n:
                            chunk_logits[start // C] = logits
                        if start + C < n and not ext_live():
                            decode_once()
                            pump()
                    skip = p // C
                elif not active() and cfg.use_flash_attention:
                    k1, v1 = self._make_row_cache(1, self.max_seq)
                    wb = self._win_bucket(n)
                    toks = req.prompt_ids + [0] * (wb - n)
                    logits, k1, v1 = self._prefill_full(
                        self.params, jnp.asarray([toks], jnp.int32), k1, v1,
                        jnp.int32(n),
                        _tokens=n,
                        _name=self._ring_name("prefill_full", wb),
                    )
                    if chunk_logits is not None and n_full and n % C == 0:
                        chunk_logits[n_full - 1] = logits
                    skip = 0
                else:
                    k1, v1 = self._make_row_cache(1, self.max_seq)
                    for start in range(0, n, C):
                        chunk = req.prompt_ids[start : start + C]
                        chunk = chunk + [0] * (C - len(chunk))
                        logits, k1, v1 = self._prefill1(
                            self.params, jnp.asarray([chunk], jnp.int32),
                            k1, v1,
                            jnp.full((1,), start, jnp.int32),
                            jnp.asarray(
                                [min(n - 1 - start, C - 1)], jnp.int32
                            ),
                            self._win_bucket(start + C),
                            _tokens=min(C, n - start),
                        )
                        if chunk_logits is not None and start + C <= n:
                            chunk_logits[start // C] = logits
                        if start + C < n and not ext_live():
                            decode_once()
                            pump()
                    skip = 0
            finally:
                if hit is not None:
                    pc.release(hit)
            # extend the table over the freshly prefilled suffix, THEN
            # harvest (host-only id bookkeeping; the device write below is
            # program-ordered before any later admit's gather of these ids)
            total = -(-n // T)
            bstart = len(tables[slot])
            tables[slot].extend(alloc_blocks(total - bstart, for_req=req))
            table_dirty = True
            harvest_prefix(
                req.prompt_ids, None, None, 0, chunk_logits,
                skip_chunks=skip, slot=slot,
            )
            # full [max_seq/T] bid row: NULL for shared prefix blocks (the
            # write must not touch the cache's copies) and the junk tail
            bids = [0] * MB
            for b in range(bstart, total):
                bids[b] = tables[slot][b]
            first, K, V, tok_dev = self._finish_admit_paged(
                self.params, K, V, tok_dev, k1, v1, logits,
                jnp.asarray(bids, jnp.int32), jnp.int32(slot), *samp,
            )
            return first

        def admit_one(req: _Request) -> None:
            nonlocal K, V, tok_dev, dirty, table_dirty
            # queue delay = enqueue -> admission START (the scheduling half
            # of TTFT); a chunked prefill's seconds are NOT queue delay
            t_admit = time.monotonic()
            req.t_admit = t_admit
            if req.trace is not None:
                req.trace.mark("admit", t_admit)
            self.stats.record_admit_delay((t_admit - req.t_enq) * 1e3)
            # every dispatch until the finish (including interleaved decode's
            # own re-scoped context) charges this request's ledger accrual
            prev_ctx = self._charge_ctx
            self._charge_ctx = (req,)
            slot = self._slots.index(None)
            n = len(req.prompt_ids)
            C = self.prefill_chunk
            sp = req.sp
            seed = sp.seed if sp.seed is not None else random.getrandbits(31)
            samp = (
                jnp.int32(seed), jnp.float32(sp.temperature),
                jnp.int32(sp.top_k), jnp.float32(sp.top_p),
            )
            note_admit(n)
            # reserve AFTER note_admit (whose cold-ring check must see the
            # true all-empty table), BEFORE the prefill: a multi-second
            # chunked/full prefill with every slot still None would read as
            # idle() to the registry's eviction check and the engine could
            # be unloaded mid-admit (admit_group_chunked already does this).
            # The failure path releases via reset_after_failed_dispatch,
            # which clears placeholders too.
            self._slots[slot] = _RESERVED
            if paged:
                try:
                    first = admit_paged(req, slot, n, seed, samp)
                except BaseException:
                    # _PoolExhausted (raised pre-dispatch) must NOT trigger
                    # the cache reset — release just this reservation. Other
                    # exceptions reset via the caller, but returning the
                    # blocks first keeps the pool books exact either way.
                    if tables[slot]:
                        pool.decref(tables[slot])
                        tables[slot] = []
                        table_dirty = True
                    self._slots[slot] = None
                    self._charge_ctx = prev_ctx
                    raise
            elif n <= C:
                # short prompt: the whole admit is one fused dispatch
                bucket = self._bucket(n)
                tokens = jnp.asarray([req.prompt_ids + [0] * (bucket - n)], jnp.int32)
                shift = jnp.int32(
                    0 if positional else (self._ring_next - n) % self.max_seq
                )
                first, K, V, tok_dev = self._admit_fused(
                    self.params, K, V, tok_dev, tokens, jnp.int32(n),
                    jnp.int32(slot), shift, *samp,
                    _tokens=n, _name=self._ring_name("admit_fused", bucket),
                )
            else:
                # long prompt. PREFIX-CACHE hit: copy the cached chunk
                # blocks into the fresh row cache (where a chunked prefill
                # would have written them) and prefill only the uncached
                # suffix — a full-prefix hit skips prefill entirely and
                # samples from the stored prompt-end logits. Miss, IDLE
                # engine: the whole prompt in ONE fresh flash dispatch at a
                # pow2 token bucket — chunking only exists to bound live
                # streams' inter-token gap, and with nothing else decoding
                # it costs ~2x the wall time (scripts/ablate_chunk_one.py);
                # a hit covering less than half the prompt is released in
                # favor of it. Otherwise: chunked prefill, fixed [1, C]
                # chunks with a shared decode step between chunks, so
                # concurrent streams stall at most ~one chunk's latency,
                # not the whole prompt's. The final chunk's logits row
                # (prompt end) is selected by logit_positions, so only
                # [1, 1, vocab] materializes; with the cache on, every
                # full chunk's END row is kept too — that row is what makes
                # a future full-prefix hit sampleable.
                k1, v1 = self._make_row_cache(1, self.max_seq)
                n_full = n // C
                chunk_logits = [None] * n_full if pc is not None else None
                hit = pc.match(req.prompt_ids) if pc is not None else None
                if (
                    hit is not None
                    and not active()
                    and cfg.use_flash_attention
                    and 2 * hit.tokens < n
                ):
                    # the single flash dispatch beats resuming a SHORT
                    # cached prefix through per-chunk dispatches
                    pc.release(hit)
                    hit = None
                try:
                    if hit is not None:
                        p = hit.tokens
                        for j, (kb, vb) in enumerate(hit.blocks):
                            k1, v1 = self._write_prefix_block(
                                k1, v1, kb, vb, jnp.int32(j * C)
                            )
                        obs_emit(
                            "prefix_hit", tokens=p, prompt=n,
                            full=(p == n),
                        )
                        if p == n:
                            logits = hit.end_logits
                        else:
                            for start in range(p, n, C):
                                chunk = req.prompt_ids[start : start + C]
                                chunk = chunk + [0] * (C - len(chunk))
                                logits, k1, v1 = self._prefill1(
                                    self.params, jnp.asarray([chunk], jnp.int32),
                                    k1, v1,
                                    jnp.full((1,), start, jnp.int32),
                                    jnp.asarray(
                                        [min(n - 1 - start, C - 1)], jnp.int32
                                    ),
                                    self._win_bucket(start + C),
                                    _tokens=min(C, n - start),
                                )
                                if start + C <= n:
                                    chunk_logits[start // C] = logits
                                if start + C < n and not ext_live():
                                    decode_once()
                                    pump()
                        harvest_prefix(
                            req.prompt_ids, k1, v1, 0, chunk_logits,
                            skip_chunks=p // C,
                        )
                    elif not active() and cfg.use_flash_attention:
                        # the shortcut needs the fresh FLASH path: through the
                        # dense fallback a full-bucket prefill would materialize
                        # the [Hq, bucket, S] f32 scores the chunked path exists
                        # to bound (2+ GB at 4k on a flash-off CPU worker)
                        wb = self._win_bucket(n)
                        toks = req.prompt_ids + [0] * (wb - n)
                        logits, k1, v1 = self._prefill_full(
                            self.params, jnp.asarray([toks], jnp.int32), k1, v1,
                            jnp.int32(n),
                            _tokens=n,
                            _name=self._ring_name("prefill_full", wb),
                        )
                        # only the prompt-end row exists here; chunk-end
                        # rows for interior chunks are backfilled if a
                        # later chunked admit recomputes them
                        if chunk_logits is not None and n_full and n % C == 0:
                            chunk_logits[n_full - 1] = logits
                        harvest_prefix(req.prompt_ids, k1, v1, 0, chunk_logits)
                    else:
                        for start in range(0, n, C):
                            chunk = req.prompt_ids[start : start + C]
                            chunk = chunk + [0] * (C - len(chunk))
                            logits, k1, v1 = self._prefill1(
                                self.params, jnp.asarray([chunk], jnp.int32), k1, v1,
                                jnp.full((1,), start, jnp.int32),
                                jnp.asarray([min(n - 1 - start, C - 1)], jnp.int32),
                                self._win_bucket(start + C),
                                _tokens=min(C, n - start),
                            )
                            if chunk_logits is not None and start + C <= n:
                                chunk_logits[start // C] = logits
                            if start + C < n and not ext_live():
                                decode_once()
                                pump()
                        harvest_prefix(req.prompt_ids, k1, v1, 0, chunk_logits)
                finally:
                    if hit is not None:
                        pc.release(hit)
                # shift MUST be computed here, after the chunk loop: the
                # interleaved decode_once() calls advanced the ring head,
                # and the prefix has to end at the CURRENT head for the
                # ring-validity mask to see it
                shift = jnp.int32(
                    0 if positional else (self._ring_next - n) % self.max_seq
                )
                first, K, V, tok_dev = self._finish_admit(
                    self.params, K, V, tok_dev, k1, v1, logits,
                    jnp.int32(slot), shift, *samp,
                )
            req.slot = slot
            req.pos = n
            self._slots[slot] = req
            self.stats.requests += 1
            dirty = True
            host_pos[slot] = n
            host_steps[slot] = 1  # the admit program sampled at rng step 0
            host_seed[slot] = seed
            if req.trace is not None:
                req.trace.mark("prefill")  # prefill dispatched; first token next
            inflight.append(("admit", first, [(0, slot, req)]))
            self._charge_ctx = prev_ctx

        def note_admit(n: int) -> None:
            """Shared cold-ring / wrap bookkeeping for an admit of length n
            (the ring-validity invariant lives in exactly one place)."""
            if positional:
                return  # no shared head: prefixes always land at [0, n)
            if not any(r is not None for r in self._slots):
                self._ring_next = n  # cold ring: the prefix fits below
                self._ring_wrapped = False
            elif self._ring_next < n:
                # the prefix placement wraps to the high slots: windowed
                # reads would miss it from here on
                self._ring_wrapped = True

        def admit_group(reqs: list[_Request], bucket: int) -> bool:
            """Admit m same-bucket short prompts in one fused dispatch.
            Returns False (caller admits individually) when any block would
            wrap around the ring. The first tokens are NOT read back here —
            the record rides the in-flight queue like a decode burst."""
            nonlocal K, V, tok_dev, dirty, table_dirty
            ns = [len(r.prompt_ids) for r in reqs]
            max_n = max(ns)
            note_admit(max_n)
            # every [bucket]-length block [ring_next - n_i, ring_next - n_i
            # + bucket) must lie inside [0, max_seq). Positional mode has no
            # head: blocks land at [0, bucket) and can never wrap.
            if not positional and (
                self._ring_next < max_n
                or self._ring_next - min(ns) + bucket > self.max_seq
            ):
                return False
            if paged:
                # pre-dispatch capacity check: a group alloc is all-or-
                # nothing, so verify (and reclaim toward) the total need
                # BEFORE reserving; a shortfall falls back to per-request
                # admits where _PoolExhausted sheds just the overflow.
                need = sum(-(-n // T) for n in ns)
                if need > pool.free_blocks and pc is not None:
                    pc.reclaim(need - pool.free_blocks)
                if need > pool.free_blocks:
                    return False
            prev_ctx = self._charge_ctx
            self._charge_ctx = tuple(reqs)
            slots: list[int] = []
            try:
                for r in reqs:
                    s = self._slots.index(None)
                    self._slots[s] = r  # reserve so index(None) advances
                    slots.append(s)
                m = len(reqs)
                mpad = 1 << (m - 1).bit_length()  # bound compiles: m in {2,4,8,..}
                idx = list(range(m)) + [0] * (mpad - m)  # pad rows repeat row 0
                seeds = [
                    r.sp.seed if r.sp.seed is not None else random.getrandbits(31)
                    for r in reqs
                ]
                tokens = [
                    reqs[i].prompt_ids + [0] * (bucket - ns[i]) for i in idx
                ]
                if paged:
                    nblk_row = max(1, bucket // T)
                    for j, s in enumerate(slots):
                        tables[s] = alloc_blocks(
                            -(-ns[j] // T), for_req=reqs[j]
                        )
                    table_dirty = True
                    bid_rows = [
                        tables[slots[i]]
                        + [0] * (nblk_row - len(tables[slots[i]]))
                        for i in idx
                    ]
                    firsts, K, V, tok_dev = self._admit_many_fused_paged(
                        self.params, K, V, tok_dev,
                        jnp.asarray(tokens, jnp.int32),
                        jnp.asarray([ns[i] for i in idx], jnp.int32),
                        jnp.asarray(bid_rows, jnp.int32),
                        jnp.asarray([slots[i] for i in idx], jnp.int32),
                        jnp.asarray([seeds[i] for i in idx], jnp.int32),
                        jnp.asarray(
                            [reqs[i].sp.temperature for i in idx], jnp.float32
                        ),
                        jnp.asarray([reqs[i].sp.top_k for i in idx], jnp.int32),
                        jnp.asarray([reqs[i].sp.top_p for i in idx], jnp.float32),
                        _tokens=sum(ns[i] for i in idx),
                        _name=self._ring_name("admit_many_fused_paged", bucket),
                    )
                else:
                    firsts, K, V, tok_dev = self._admit_many_fused(
                        self.params, K, V, tok_dev,
                        jnp.asarray(tokens, jnp.int32),
                        jnp.asarray([ns[i] for i in idx], jnp.int32),
                        jnp.asarray([slots[i] for i in idx], jnp.int32),
                        jnp.asarray(
                            [0 if positional else self._ring_next - ns[i] for i in idx],
                            jnp.int32,
                        ),
                        jnp.asarray([seeds[i] for i in idx], jnp.int32),
                        jnp.asarray([reqs[i].sp.temperature for i in idx], jnp.float32),
                        jnp.asarray([reqs[i].sp.top_k for i in idx], jnp.int32),
                        jnp.asarray([reqs[i].sp.top_p for i in idx], jnp.float32),
                        _tokens=sum(ns[i] for i in idx),
                        _name=self._ring_name("admit_many_fused", bucket),
                    )
            except BaseException:
                for s in slots:  # release reservations; caller emits the error
                    self._slots[s] = None
                    if paged and tables[s]:
                        pool.decref(tables[s])
                        tables[s] = []
                        table_dirty = True
                self._charge_ctx = prev_ctx
                raise
            dirty = True
            self.stats.grouped_admits += len(reqs)
            rows = []
            t_admit = time.monotonic()
            for j, r in enumerate(reqs):
                s = slots[j]
                r.slot = s
                r.pos = ns[j]
                r.t_admit = t_admit
                self.stats.requests += 1
                self.stats.record_admit_delay((t_admit - r.t_enq) * 1e3)
                if r.trace is not None:
                    r.trace.mark("admit", t_admit)
                    r.trace.mark("prefill")  # the group dispatch just went out
                host_pos[s] = ns[j]
                host_steps[s] = 1  # the admit program sampled at rng step 0
                host_seed[s] = seeds[j]
                rows.append((j, s, r))
            inflight.append(("admit", firsts, rows))
            self._charge_ctx = prev_ctx
            return True

        def admit_group_chunked(reqs: list[_Request]) -> None:
            """Admit m LONG prompts (each > prefill_chunk) through SHARED
            [m, C] chunk dispatches + one batched finish. Serial chunked
            admits at B=1 leave most of the MXU idle and, worse, make
            waiting long prompts queue a whole prefill each; batching
            divides the chunk-pass count by m. A shared decode step still
            interleaves between chunk dispatches, so live streams' inter-
            token gap stays bounded by ~one [m, C] chunk.

            Reserved slots hold the _RESERVED placeholder during the loop:
            the fixed-width decode program computes their rows as masked
            junk (same as empty slots) and nothing is delivered; the
            finish dispatch overwrites the full rows and installs the
            requests atomically."""
            nonlocal K, V, tok_dev, dirty, table_dirty
            if paged:
                # all-or-nothing capacity check up front; a shortfall routes
                # each request through admit_one, where _PoolExhausted sheds
                # just the requests that truly do not fit
                need = sum(-(-len(r.prompt_ids) // T) for r in reqs)
                if need > pool.free_blocks and pc is not None:
                    pc.reclaim(need - pool.free_blocks)
                if need > pool.free_blocks:
                    for r in reqs:
                        try:
                            admit_one(r)
                        except _PoolExhausted as e:
                            # chunk prefills may have run before the alloc
                            # failed: that device time is shed-after-prefill
                            self._ledger_finalize(r, "shed_after_prefill")
                            r.emit("err", e)
                    return
            prev_ctx = self._charge_ctx
            self._charge_ctx = tuple(reqs)
            # queue delay = enqueue -> admission START (scheduling only;
            # the chunk loop's seconds are prefill, not queueing)
            t_start = time.monotonic()
            for r in reqs:
                r.t_admit = t_start
                self.stats.record_admit_delay((t_start - r.t_enq) * 1e3)
                if r.trace is not None:
                    r.trace.mark("admit", t_start)
            C = self.prefill_chunk
            ns = [len(r.prompt_ids) for r in reqs]
            note_admit(max(ns))
            slots: list[int] = []
            try:
                for r in reqs:
                    s = self._slots.index(None)
                    self._slots[s] = _RESERVED
                    slots.append(s)
                m = len(reqs)
                mpad = 1 << (m - 1).bit_length()
                idx = list(range(m)) + [0] * (mpad - m)  # pad rows repeat row 0
                seeds = [
                    r.sp.seed if r.sp.seed is not None else random.getrandbits(31)
                    for r in reqs
                ]
                km, vm = self._make_row_cache(mpad, self.max_seq)
                final = jnp.zeros((mpad, 1, cfg.vocab_size), jnp.float32)
                n_chunks = -(-max(ns) // C)
                end_chunk = [(ns[i] - 1) // C for i in idx]
                # per-chunk [mpad, 1, vocab] logits, kept only while the
                # prefix cache is on: full-chunk END rows become the cached
                # nodes' first-token logits (transient cost ~n_chunks x
                # mpad x vocab f32, freed right after harvest below)
                glogits: list = [] if pc is not None else None
                for j in range(n_chunks):
                    start = j * C
                    rows = []
                    for i in idx:
                        chunk = reqs[i].prompt_ids[start : start + C]
                        rows.append(chunk + [0] * (C - len(chunk)))
                    last_pos = [
                        min(max(ns[i] - 1 - start, 0), C - 1) for i in idx
                    ]
                    logits, km, vm = self._prefill_chunk_group(
                        self.params, jnp.asarray(rows, jnp.int32), km, vm,
                        jnp.full((mpad,), start, jnp.int32),
                        jnp.asarray(last_pos, jnp.int32),
                        self._win_bucket(start + C),
                        _tokens=mpad * C,
                    )
                    final = self._select_end(
                        final, logits,
                        jnp.asarray([e == j for e in end_chunk], jnp.bool_),
                    )
                    if glogits is not None:
                        glogits.append(logits)
                    if start + C < max(ns) and not ext_live():
                        decode_once()
                        pump()
                if paged:
                    # tables BEFORE harvest (the paged harvest records the
                    # rows' pool block ids, not device copies)
                    for j, s in enumerate(slots):
                        tables[s] = alloc_blocks(
                            -(-ns[j] // T), for_req=reqs[j]
                        )
                    table_dirty = True
                if glogits is not None:
                    # harvest each real row's full-chunk blocks BEFORE the
                    # finish dispatch; jnp.copy detaches each [1, 1, vocab]
                    # end row so the [mpad, ...] chunk buffers can free
                    for j in range(m):
                        cl = [
                            jnp.copy(glogits[t][j : j + 1])
                            if (t + 1) * C <= ns[j]
                            else None
                            for t in range(ns[j] // C)
                        ]
                        harvest_prefix(
                            reqs[j].prompt_ids, km, vm, j, cl, slot=slots[j]
                        )
                    glogits = None
                if paged:
                    bid_rows = np.zeros((mpad, max(MB, 1)), np.int32)
                    for j in range(m):
                        t = tables[slots[j]]
                        bid_rows[j, : len(t)] = t
                    firsts, K, V, tok_dev = self._finish_admit_group_paged(
                        self.params, K, V, tok_dev, km, vm, final,
                        jnp.asarray(bid_rows),
                        jnp.asarray([slots[i] for i in idx], jnp.int32),
                        jnp.asarray([seeds[i] for i in idx], jnp.int32),
                        jnp.asarray(
                            [reqs[i].sp.temperature for i in idx], jnp.float32
                        ),
                        jnp.asarray([reqs[i].sp.top_k for i in idx], jnp.int32),
                        jnp.asarray([reqs[i].sp.top_p for i in idx], jnp.float32),
                    )
                else:
                    # shifts AFTER the loop: interleaved decodes moved the head
                    shifts = [
                        0 if positional else (self._ring_next - ns[i]) % self.max_seq
                        for i in idx
                    ]
                    firsts, K, V, tok_dev = self._finish_admit_group(
                        self.params, K, V, tok_dev, km, vm, final,
                        jnp.asarray([slots[i] for i in idx], jnp.int32),
                        jnp.asarray(shifts, jnp.int32),
                        jnp.asarray([seeds[i] for i in idx], jnp.int32),
                        jnp.asarray([reqs[i].sp.temperature for i in idx], jnp.float32),
                        jnp.asarray([reqs[i].sp.top_k for i in idx], jnp.int32),
                        jnp.asarray([reqs[i].sp.top_p for i in idx], jnp.float32),
                    )
            except BaseException:
                for s in slots:  # release reservations; caller emits the error
                    self._slots[s] = None
                    if paged and tables[s]:
                        pool.decref(tables[s])
                        tables[s] = []
                        table_dirty = True
                self._charge_ctx = prev_ctx
                raise
            dirty = True
            self.stats.chunked_group_admits += len(reqs)
            out_rows = []
            for j, r in enumerate(reqs):
                s = slots[j]
                r.slot = s
                r.pos = ns[j]
                self._slots[s] = r
                self.stats.requests += 1
                if r.trace is not None:
                    r.trace.mark("prefill")  # chunk loop + finish dispatched
                host_pos[s] = ns[j]
                host_steps[s] = 1  # the finish program sampled at rng step 0
                host_seed[s] = seeds[j]
                out_rows.append((j, s, r))
            inflight.append(("admit", firsts, out_rows))
            self._charge_ctx = prev_ctx

        def reset_after_failed_dispatch() -> None:
            """A failed admit/decode dispatch may have consumed the donated
            K/V buffers (e.g. device OOM raised after donation); continuing
            would wedge every subsequent dispatch against invalidated
            buffers (round-2 advisor). Fail the active streams honestly and
            rebuild a fresh cache. In-flight records reference the poisoned
            buffers and are discarded."""
            nonlocal K, V, tok_dev, dirty, table_dirty
            inflight.clear()
            self._charge_ctx = None  # drop any context the failed call left
            err = RuntimeError("batcher cache reset after a failed device dispatch")
            for i, r in enumerate(self._slots):
                if isinstance(r, _Request):
                    self._ledger_finalize(r, "failed")
                    r.emit("err", err)
                if r is not None:  # includes _RESERVED placeholders
                    self._slots[i] = None
                    host_pos[i] = 0
                    host_steps[i] = 0
                spec_slots[i] = None
            self._ring_next = 0
            self._ring_wrapped = False
            dirty = True
            if paged:
                # epoch bump: prefix-cache payloads minted before the reset
                # free as no-ops, and stale hits are rejected at match time
                pool.reset()
                for i in range(B):
                    tables[i] = []
                table_dirty = True
                if pc is not None:
                    pc.clear()
                K, V = make_pool()
            else:
                K, V = make_cache(cfg, B, self.max_seq)
                if self.mesh is not None:
                    from ..parallel.sharding import shard_cache

                    K, V = shard_cache(K, V, self.mesh, cfg=cfg)
            tok_dev = jnp.zeros((B,), jnp.int32)

        coalesce_s = self.admit_coalesce_ms / 1e3
        # instance attr (not a local): a pump-loop crash must be able to
        # fail waiters that have left the inbox but not yet won a slot
        waitlist = self._waitlist
        while True:
            self.heartbeat = time.monotonic()  # supervisor liveness stamp
            if _faults.ACTIVE is not None:  # chaos harness; off ⇒ one attr read
                f = _faults.ACTIVE.check(_faults.PUMP)
                if f is not None and f.kind == "raise":
                    raise f.exception()
            act = active()
            self.stats.peak_active = max(self.stats.peak_active, len(act))
            # intake: block when fully idle, otherwise just drain what's
            # queued. Suspended slots keep their deadline clocks running,
            # so with any parked the idle park becomes a bounded poll (the
            # suspended sweep/resume below must keep ticking); when a
            # resume is already possible, don't wait at all.
            bo0 = self.brownout
            can_resume = bool(
                self._suspended
                and None in self._slots
                and (bo0 is None or bo0.level < SHED_ONLY)
            )
            block = (
                not act and not waitlist and not inflight and not can_resume
            )
            poll_s = 0.05 if (block and self._suspended) else None
            first_intake = block
            while True:
                try:
                    item = self._inbox.get(block=block, timeout=poll_s)
                except _queue.Empty:
                    break
                block = False
                if item is None:
                    self._drain_all("shutdown", waitlist)
                    return
                if isinstance(item, _ControlOp):
                    run_control(item)
                    continue
                if item.cancelled:
                    self.stats.record_cancel("inbox")
                    continue
                waitlist.append(item)
                self._wl_len = len(waitlist)  # keep idle() honest mid-intake
                if first_intake and coalesce_s > 0:
                    # the worker was idle and one request just arrived —
                    # concurrent arrivals are usually a few scheduler ticks
                    # apart; waiting a few ms turns 1 + (m-1) admit
                    # dispatches (each a full device round trip) into ONE
                    # batched admit, the dominant TTFT term under bursty
                    # load on a tunneled chip
                    first_intake = False
                    deadline = time.monotonic() + coalesce_s
                    while True:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        try:
                            nxt = self._inbox.get(timeout=left)
                        except _queue.Empty:
                            break
                        if nxt is None:
                            self._drain_all("shutdown", waitlist)
                            return
                        if isinstance(nxt, _ControlOp):
                            run_control(nxt)
                            continue
                        if nxt.cancelled:
                            self.stats.record_cancel("inbox")
                            continue
                        waitlist.append(nxt)
                        self._wl_len = len(waitlist)
            drain_cancels(waitlist)
            now = time.monotonic()
            depth = len(waitlist) + self._inbox.qsize()
            rebuild_slot_view()
            rec = self.recorder
            if rec is not None and rec.due(now):
                rec.sample(
                    self._recorder_frame(depth=depth, n_active=len(active())),
                    now=now,
                )
            bo = self.brownout
            lvl_before = bo.level if bo is not None else SHED_ONLY
            if bo is not None:
                # controller tick: queue depth as a fraction of the
                # (configured, or nominal 4x-slots) limit, queue-age p95
                # over the current waiters, HBM headroom via the
                # registry-injected probe
                limit = self.max_queue or 4 * self.max_slots
                ages = sorted((now - r.t_enq) * 1e3 for r in waitlist)
                age_p95 = ages[max(0, int(len(ages) * 0.95) - 1)] if ages else 0.0
                headroom_frac = None
                if self.hbm_headroom_fn is not None:
                    try:
                        headroom_frac = self.hbm_headroom_fn()
                    except Exception:  # noqa: BLE001 — probe is best-effort
                        headroom_frac = None
                bo.update(depth_frac=depth / limit, age_p95_ms=age_p95,
                          hbm_headroom_frac=headroom_frac, now=now)
                if (
                    bo.level == SHED_ONLY
                    and lvl_before < SHED_ONLY
                    and rec is not None
                ):
                    # entering full shed is an incident, not a metric blip:
                    # capture the ramp that led here (rate-limited)
                    rec.dump(
                        "shed_only_entry",
                        extra={"depth": depth, "age_p95_ms": round(age_p95, 1),
                               "hbm_headroom_frac": headroom_frac,
                               "device_ms": self.stats.device_time_snapshot()["ms"]},
                    )
                if bo.level == SHED_ONLY and lvl_before < SHED_ONLY:
                    # swap-don't-shed on the incident edge: park the
                    # youngest streams on the host tier so the survivors
                    # keep full decode width; they resume once the level
                    # drops back below SHED_ONLY (resume_suspended gates
                    # on it)
                    target = bo.suspend_target(self.max_slots)
                    while suspend_on:
                        live = [
                            i for i, r in enumerate(self._slots)
                            if isinstance(r, _Request)
                        ]
                        if len(live) <= target:
                            break
                        # lowest class first, youngest within a class — a
                        # premium stream is the last to be parked
                        victim = min(
                            live,
                            key=lambda i: (
                                self._slots[i].rank, -self._slots[i].t_admit
                            ),
                        )
                        if not suspend_slot(victim, "brownout"):
                            break
            if tier is not None and paged:
                # proactive demotion: keep ~demote_free_frac of the pool
                # free by demoting cold cache chunks to the host tier
                # BETWEEN bursts, so admissions stop paying the reclaim at
                # the worst moment (and the tier fills before pressure
                # peaks). No-op once the cache holds nothing unpinned.
                floor_blocks = int(pool.n_blocks * tier.demote_free_frac)
                if pool.free_blocks < floor_blocks:
                    pc.reclaim(floor_blocks - pool.free_blocks, demote=True)
            # deadline sweep, queued side: waiters whose budget already ran
            # out — or whose remaining budget the live rate EWMAs say cannot
            # cover prefill plus the token floor — are shed BEFORE any
            # prefill work, with a retryable envelope
            if waitlist and any(r.deadline is not None for r in waitlist):
                kept = []
                for r in waitlist:
                    left = None if r.deadline is None else r.deadline - now
                    if left is None or (
                        left > 0 and self._estimate_serve_s(r) <= left
                    ):
                        kept.append(r)
                        continue
                    waited_ms = (now - r.t_enq) * 1e3
                    self.stats.record_shed("deadline", waited_ms=waited_ms)
                    self.tenant_stats.record_shed(r.tenant)
                    msg = (
                        f"deadline infeasible (~{self._estimate_serve_s(r) * 1e3:.0f} ms "
                        f"needed, {left * 1e3:.0f} ms left) "
                        f"(shed_cause=deadline); skipped prefill; "
                        if left > 0
                        else f"deadline expired after {waited_ms:.0f} ms "
                        f"queued (shed_cause=deadline); "
                    )
                    try:
                        r.emit("err", BatcherOverloaded(
                            msg + "retry on another worker"
                        ))
                    except Exception:  # noqa: BLE001 — dead client loop
                        pass
                waitlist[:] = kept
                self._wl_len = len(waitlist)
            # deadline sweep, active side: a slot past its deadline is
            # cooperatively aborted through the consumer-gone cancel path
            # (freed at the next burst readback, cause-tagged "deadline")
            for r in self._slots:
                if (
                    isinstance(r, _Request)
                    and r.deadline is not None
                    and not r.cancelled
                    and now > r.deadline
                ):
                    r.deadline_hit = True
                    r.cancelled = True
                    try:
                        r.emit("err", BatcherOverloaded(
                            f"deadline exceeded mid-decode after {r.generated} "
                            f"tokens (shed_cause=deadline); retry on another "
                            f"worker"
                        ))
                    except Exception:  # noqa: BLE001 — dead client loop
                        pass
            # deadline sweep, suspended side: a parked slot's clock keeps
            # running — an expired one is failed right here with the same
            # retryable deadline cause (it holds no pool blocks, so there
            # is nothing to free), and a cancelled one is dropped
            if self._suspended:
                kept_s = []
                for srec in self._suspended:
                    r = srec.req
                    if r.cancelled:
                        self._ledger_finalize(
                            r,
                            "deadline_abort" if r.deadline_hit else "cancelled",
                        )
                        self.stats.record_cancel("active")
                        continue
                    if r.deadline is not None and now > r.deadline:
                        r.deadline_hit = True
                        waited_ms = (now - r.t_enq) * 1e3
                        self.stats.record_shed(
                            "deadline", waited_ms=waited_ms
                        )
                        self._suspend_stats["suspended_deadline_expired"] += 1
                        self._ledger_finalize(r, "deadline_abort")
                        self.tenant_stats.record_shed(r.tenant)
                        try:
                            r.emit("err", BatcherOverloaded(
                                f"deadline exceeded while suspended after "
                                f"{r.generated} tokens (shed_cause=deadline); "
                                f"retry on another worker"
                            ))
                        except Exception:  # noqa: BLE001 — dead client loop
                            pass
                        continue
                    kept_s.append(srec)
                self._suspended = kept_s
            # resume parked slots BEFORE admitting new waiters: they are
            # strictly older work and already hold their first tokens
            resume_suspended()
            # weighted fair-share admission: reorder the waitlist by
            # deficit round-robin over tenants (FIFO within a tenant,
            # prompt tokens as cost, class/key weight as share). A single
            # tenant degenerates to exact FIFO, so every pre-QoS workload
            # admits in the same order it always did.
            if len(waitlist) > 1:
                waitlist[:] = self._drr.order(
                    waitlist,
                    tenant_of=lambda r: r.tenant,
                    cost_of=lambda r: len(r.prompt_ids),
                    weight_of=lambda r: r.drr_weight,
                )
                # the premium depth grace in _enqueue can leave the queue
                # over its bound; settle it here by displacing the excess
                # from the BACK of the DRR order, lowest class first — the
                # requests weighted fair share says would wait the longest
                # anyway go retry on a less loaded worker
                limit = (
                    bo.effective_queue_limit(self.max_queue)
                    if bo is not None else self.max_queue
                )
                if limit and len(waitlist) > limit:
                    order = {id(r): i for i, r in enumerate(waitlist)}
                    excess = len(waitlist) - limit
                    victims = sorted(
                        waitlist, key=lambda r: (r.rank, -order[id(r)])
                    )[:excess]
                    vset = {id(r) for r in victims}
                    waitlist[:] = [r for r in waitlist if id(r) not in vset]
                    for r in victims:
                        waited_ms = (now - r.t_enq) * 1e3
                        self.stats.record_shed(
                            "fair_share", waited_ms=waited_ms
                        )
                        self.tenant_stats.record_shed(r.tenant)
                        try:
                            r.emit("err", BatcherOverloaded(
                                "displaced by weighted fair share "
                                "(shed_cause=fair_share); retry on another "
                                "worker"
                            ))
                        except Exception:  # noqa: BLE001 — dead client
                            pass
            self._wl_len = len(waitlist)
            # admit waiters: bursts of short same-bucket prompts go through
            # one batched dispatch; runs of LONG prompts go through one
            # batched CHUNKED dispatch; odd ones admit individually
            while waitlist and None in self._slots:
                self._wl_len = len(waitlist)
                free = self._slots.count(None)
                head_long = len(waitlist[0].prompt_ids) > self.prefill_chunk
                head_bucket = (
                    None if head_long
                    else self._bucket(len(waitlist[0].prompt_ids))
                )
                group: list[_Request] = []

                def _peek_hit(r: _Request) -> bool:
                    # a long prompt with a usable cached prefix is admitted
                    # ALONE: the group-chunked program prefills every row
                    # from position 0, which would throw the hit away (a
                    # peek, not a match — nothing is pinned until admit_one)
                    return (
                        pc is not None
                        and len(r.prompt_ids) > self.prefill_chunk
                        and pc.peek(r.prompt_ids) >= self.prefill_chunk
                    )

                if head_long:
                    cap = min(free, self.max_group_long)
                    head_hit = _peek_hit(waitlist[0])
                    group.append(waitlist.pop(0))
                    while (
                        not head_hit
                        and waitlist
                        and len(group) < cap
                        and len(waitlist[0].prompt_ids) > self.prefill_chunk
                        and not _peek_hit(waitlist[0])
                    ):
                        group.append(waitlist.pop(0))
                    # top-up: a chunked admit costs SECONDS of prefill, so
                    # waiting ~50 ms for co-arriving long prompts (e.g. a
                    # synchronized client wave trickling through the
                    # broker) is always worth one more group row — the
                    # arrival race otherwise serializes them into separate
                    # full prefill passes (and, once, a separate COMPILE
                    # per distinct group width). With live streams the
                    # wait is spent as a decode burst instead of idling
                    # (same wall clock, but the chip works and nobody's
                    # inter-token gap grows).
                    def drain_topup() -> bool:
                        """Pull queued longs; False = stop topping up."""
                        while len(group) < cap:
                            try:
                                nxt = self._inbox.get_nowait()
                            except _queue.Empty:
                                return True
                            if nxt is None:
                                # shutdown sentinel: push back for the
                                # outer intake to see after this admit
                                self._inbox.put(None)
                                return False
                            if isinstance(nxt, _ControlOp):
                                run_control(nxt)
                                continue
                            if nxt.cancelled:
                                self.stats.record_cancel("inbox")
                                continue
                            if (
                                len(nxt.prompt_ids) > self.prefill_chunk
                                and not _peek_hit(nxt)
                            ):
                                group.append(nxt)
                            else:
                                waitlist.append(nxt)
                                return False
                        return False

                    if (
                        not head_hit
                        and len(group) < cap
                        and not waitlist
                        and coalesce_s > 0
                        and not ext_live()
                    ):
                        if active():
                            # guarded like every other dispatch site: a
                            # device failure here must fail the popped group
                            # honestly and reset, not kill the owner thread
                            # with the group's streams hung (r4 advisor)
                            try:
                                decode_once()
                                pump()
                            except Exception as e:  # noqa: BLE001
                                for req in group:
                                    req.emit("err", e)
                                reset_after_failed_dispatch()
                                continue
                            drain_topup()
                        else:
                            deadline = time.monotonic() + max(coalesce_s, 0.05)
                            while len(group) < cap:
                                left = deadline - time.monotonic()
                                if left <= 0:
                                    break
                                try:
                                    nxt = self._inbox.get(timeout=left)
                                except _queue.Empty:
                                    break
                                if nxt is None:
                                    self._inbox.put(None)
                                    break
                                if isinstance(nxt, _ControlOp):
                                    run_control(nxt)
                                    continue
                                if nxt.cancelled:
                                    self.stats.record_cancel("inbox")
                                    continue
                                if (
                                    len(nxt.prompt_ids) > self.prefill_chunk
                                    and not _peek_hit(nxt)
                                ):
                                    group.append(nxt)
                                else:
                                    waitlist.append(nxt)
                                    break
                    # requests popped into the group are being ADMITTED, not
                    # queued: refresh the mirror before the seconds-long
                    # chunked admit so the depth bound doesn't count them
                    # and spuriously shed new submits (measured against the
                    # "queued-not-yet-admitted" semantics _enqueue documents)
                    self._wl_len = len(waitlist)
                    if len(group) > 1:
                        try:
                            admit_group_chunked(group)
                        except _PoolExhausted as e:
                            # raised pre-dispatch: the device pool is intact,
                            # shed the group without the cache reset
                            for req in group:
                                self._ledger_finalize(req, "shed_after_prefill")
                                req.emit("err", e)
                        except Exception as e:  # noqa: BLE001 — surface to callers
                            for req in group:
                                self._ledger_finalize(req, "failed")
                                req.emit("err", e)
                            reset_after_failed_dispatch()
                        continue
                elif head_bucket is not None:
                    while (
                        waitlist
                        and len(group) < min(free, self.max_group_admit)
                        and len(waitlist[0].prompt_ids) <= self.prefill_chunk
                        and self._bucket(len(waitlist[0].prompt_ids)) == head_bucket
                    ):
                        group.append(waitlist.pop(0))
                self._wl_len = len(waitlist)  # popped-into-group != queued
                if len(group) > 1:  # here only via the short same-bucket path
                    try:
                        handled = admit_group(group, head_bucket)
                    except Exception as e:  # noqa: BLE001 — surface to callers
                        for req in group:
                            self._ledger_finalize(req, "failed")
                            req.emit("err", e)
                        reset_after_failed_dispatch()
                        continue
                    if handled:
                        continue
                    # group placement would wrap the ring (or the block pool
                    # cannot fit the whole group): admit one by one
                for req in group:
                    try:
                        admit_one(req)
                    except _PoolExhausted as e:
                        # pre-dispatch shed: pool state is intact, the other
                        # streams keep decoding; no cache reset — but a long
                        # prompt's chunk prefills may have run before the
                        # suffix alloc failed: that device time was wasted
                        self._ledger_finalize(req, "shed_after_prefill")
                        req.emit("err", e)
                    except Exception as e:  # noqa: BLE001 — surface to the caller
                        self._ledger_finalize(req, "failed")
                        req.emit("err", e)
                        reset_after_failed_dispatch()
            # age bound: requests STILL waiting after admission had its
            # chance (i.e. genuinely slot-starved, not just coalescing) and
            # older than the limit are shed with an honest error instead of
            # queueing invisibly (the r4 bench's silent 38.6 s admit-delay
            # tail) — the reply lets the client retry on a queue-group peer
            if self.max_queue_age_ms and waitlist:
                now = time.monotonic()
                kept = []
                for r in waitlist:
                    waited_ms = (now - r.t_enq) * 1e3
                    if waited_ms > self.max_queue_age_ms:
                        self.stats.record_shed("age", waited_ms=waited_ms)
                        self.tenant_stats.record_shed(r.tenant)
                        try:
                            r.emit("err", BatcherOverloaded(
                                f"shed after {waited_ms:.0f} ms queued "
                                f"(> {self.max_queue_age_ms:.0f} ms bound) "
                                f"(shed_cause=age); retry on another worker"
                            ))
                        except Exception:  # noqa: BLE001 — dead client loop
                            pass
                    else:
                        kept.append(r)
                waitlist[:] = kept
            self._wl_len = len(waitlist)
            # depth-2 pipeline: dispatch the next burst, THEN block on the
            # oldest in-flight readback — the device computes burst k+1
            # while the host delivers burst k's tokens. EXCEPT when an admit
            # is in flight AT LIGHT LOAD: its first-token readback must not
            # queue behind the next burst (the remote transport orders D2H
            # transfers behind queued programs, which would add a whole
            # burst to TTFT) — drain first, then resume the pipeline. At
            # high occupancy (>= 3/4 of slots live) the trade flips:
            # closed-loop traffic admits every few bursts, and draining the
            # pipeline on each one idles the device for a readback round
            # trip per admit (~30% of the silicon at 96 slots on a ~115 ms
            # tunnel — the r4 served/device gap); there TTFT is queue-
            # dominated anyway, so keep the pipeline full and let the
            # admit's first token ride one burst later.
            try:
                if any(rec[0] == "admit" for rec in inflight) and (
                    4 * len(active()) < 3 * self.max_slots
                ):
                    pump(0)
                maybe_compact()
                if ext_live():
                    # ext regime: a constrained/logprob slot advances one
                    # masked step at a time, and the burst/spec programs
                    # would advance the device pos carry of EVERY row —
                    # so while any ext slot is live, all slots decode
                    # through the masked single-step program. pump(0)
                    # first so an ext admit's rewind lands before its
                    # first masked step; pump(0) after so the DFA state
                    # advances before the next mask is built.
                    pump(0)
                    decode_ext_once()
                    pump(0)
                elif (
                    spec is not None
                    and 0 < len(active()) <= spec.max_active
                    and not (bo is not None and bo.pause_spec)
                ):
                    # speculative regime (low occupancy = memory-bound):
                    # drain so proposals see full history and admit records
                    # have installed their n-gram indices, verify, drain
                    # again (host pos only catches up at readback). The
                    # depth-2 pipeline is deliberately given up here — one
                    # verify emits up to k+1 tokens per slot, so the
                    # readback round trip amortizes across the whole burst.
                    pump(0)
                    if spec_once():
                        pump(0)
                    else:
                        decode_once()
                        pump()
                else:
                    decode_once()
                    pump()
            except Exception:  # noqa: BLE001 — K/V were donated; must reset
                reset_after_failed_dispatch()

    def _deliver(
        self,
        req: _Request,
        tok_id: int,
        logprob: float | None = None,
        top_ids: list | None = None,
        top_lps: list | None = None,
    ) -> str | None:
        """Push one token; returns the end reason when the request just
        finished, else None. The END event is NOT emitted here — the caller
        frees the slot first, then emits, so a consumer observing "end" can
        rely on the slot (and the batcher's ``idle`` view) being current
        (the registry's idle-eviction check reads it immediately after a
        chat returns). Requests with ``want_logprobs`` receive
        ``(tok, logprob, top_ids, top_logprobs)`` tuples instead of bare
        ids (the ext readback supplies the extra fields)."""
        if tok_id in req.sp.stop_ids:
            if req.trace is not None:
                req.trace.mark("decode_done")
            return "stop"
        req.generated += 1
        self.stats.tokens += 1
        if req.generated == 1:
            # the first delivered token closes both latency halves: TTFT
            # (enqueue -> token) and prefill (admit dispatch -> token)
            now = time.monotonic()
            self.stats.ttft_ms.record((now - req.t_enq) * 1e3)
            if req.t_admit:
                self.stats.prefill_ms.record((now - req.t_admit) * 1e3)
                self._note_prefill_rate(len(req.prompt_ids), now - req.t_admit)
            if req.trace is not None:
                req.trace.mark("first_token", now)
        if req.want_logprobs:
            req.emit("tok", (tok_id, logprob, top_ids, top_lps))
        else:
            req.emit("tok", tok_id)
        req.emitted.append(int(tok_id))
        if req.generated >= req.sp.max_tokens or req.pos + 1 >= self.max_seq:
            if req.trace is not None:
                req.trace.mark("decode_done")
            return "length"
        return None

    def _drain_all(self, reason: str, waitlist: list[_Request] = ()) -> None:
        # the owner thread is gone (or going): nothing is waiting any more,
        # so zero the waitlist mirror unconditionally — a stopped batcher
        # must read as idle (the registry's eviction check relies on it)
        self._wl_len = 0
        self._slot_view = {}
        for req in waitlist:
            req.emit("end", reason)
        if isinstance(waitlist, list):
            waitlist.clear()  # self._waitlist: a later crash must not re-fail these
        for i, req in enumerate(self._slots):
            if isinstance(req, _Request):
                # whatever streamed before shutdown was served; the ledger
                # keeps its tokens so goodput stays honest across drains
                self._ledger_finalize(req, "served")
                req.emit("end", reason)
            if req is not None:  # includes _RESERVED placeholders
                self._slots[i] = None
        for rec in self._suspended:
            # suspended slots are live requests parked on the host tier;
            # a drain fails them exactly like active slots (their streamed
            # tokens were served, the rest retries elsewhere)
            self._ledger_finalize(rec.req, "served")
            rec.req.emit("end", reason)
        self._suspended = []
        while True:
            try:
                req = self._inbox.get_nowait()
            except _queue.Empty:
                return
            if req is not None:
                req.emit("end", reason)

"""Engine and registry interfaces the NATS handler layer is written against.

The reference's handler layer talks to an ``LMStudioClient`` interface
(PullModel/DeleteModel/ListModels/Chat — /root/reference/nats_llm_studio.go:22-179)
that proxies to an external process. Here the same four capabilities are an
in-process ``Registry`` managing ``ChatEngine`` instances (the TPU decode
loops). Tests substitute fakes at this seam (SURVEY.md §4.2).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, AsyncIterator


class EngineError(Exception):
    """Inference/registry failure carried into the error envelope."""


class ModelNotFound(EngineError):
    pass


class ChatEngine(ABC):
    """A loaded model able to serve OpenAI-style chat completions."""

    model_id: str

    @abstractmethod
    async def chat(self, payload: dict) -> dict:
        """Full (non-streaming) completion for an OpenAI-style chat payload
        (the reference passes this payload verbatim to LM Studio,
        nats_llm_studio.go:161; response shape README.md:208-231)."""

    async def chat_stream(self, payload: dict) -> AsyncIterator[dict]:
        """Yield OpenAI-style chunk dicts; default shim yields the full
        completion as one chunk."""
        yield await self.chat(payload)

    @abstractmethod
    def info(self) -> dict:
        """LM-Studio-shaped model entry (id, object, publisher, state, ...;
        README.md:66-80)."""

    async def unload(self) -> None:
        """Release device memory."""


class Registry(ABC):
    """Model lifecycle: the in-process replacement for LM Studio + `lms` CLI."""

    @abstractmethod
    async def list_models(self) -> dict:
        """LM-Studio-shaped listing: ``{"object": "list", "data": [...]}``."""

    @abstractmethod
    async def pull(self, identifier: str) -> str:
        """Fetch a model into the local cache (object store / path import).
        Returns a human-readable transcript — the analog of `lms get`'s
        combined output (nats_llm_studio.go:53-55)."""

    @abstractmethod
    async def delete(self, model_id: str) -> str:
        """Unload + remove from local cache. Returns the deleted directory
        (the reference returns ``deleted_dir``, nats_llm_studio.go:316-323).
        Raises EngineError with the attempted dir in ``.dir`` when missing."""

    @abstractmethod
    async def get_engine(self, model_id: str) -> ChatEngine:
        """Return a loaded engine for ``model_id``, loading it if cached on
        disk; raise ModelNotFound otherwise."""

    async def sync_from_bucket(self, name: str, model_id: str | None = None) -> str:
        """Object-store → local cache download; returns local path
        (the conceptual ``lmstudio.sync_model_from_bucket`` subject,
        /root/reference/README.md:286-318)."""
        raise EngineError("object store not configured")

    def stats(self) -> dict[str, Any]:
        return {}

    def loaded_engines(self) -> dict[str, "ChatEngine"]:
        """Currently-loaded engines by model id, for metrics/observability.
        Default: none (registries without persistent engines)."""
        return {}

"""NATS worker runtime: the handler layer the reference leaves unwritten.

The reference is a library with no ``main()``/``Subscribe`` (SURVEY.md §1);
its README specifies the runtime: connect to ``NATS_URL``, queue-subscribe the
subjects under ``NATS_QUEUE_GROUP`` (/root/reference/README.md:475-494). This
module implements that contract plus the handler semantics of
/root/reference/nats_llm_studio.go:228-364:

* uniform ``{ok, error?, data?}`` envelope (``:186-190``)
* validation branches and error strings (``:254-262, :293-300, :331-345``) —
  with the Portuguese "payload vazio em ChatModel" (``:332``) consciously
  normalized to English (deviation documented in SURVEY.md §2.1)
* per-op deadline ladder: list 30 s / pull 10 min / delete 2 min / chat 2 min
  (``:229, :251, :289, :328``)
* subjects: the four from README.md:17-21, the conceptual
  ``sync_model_from_bucket`` (README.md:286-318) made real, and a ``health``
  subject (SURVEY.md §5 failure-detection gap).

Streaming: when the chat payload sets ``"stream": true``, tokens are published
to the reply inbox as OpenAI-style chunks and the terminal message carries the
full aggregate completion with a ``Nats-Stream-Done`` header — so naive
single-reply clients (``nats req``) still receive a complete response.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import logging
import os
import time

from ..config import WorkerConfig
from ..obs import (
    EVENTS,
    PromRenderer,
    Trace,
    Span,
    compile_cache_counts,
    efficiency_enabled,
    install_compile_cache_listener,
    new_span_id,
    new_trace_id,
    parse_span_context,
    span_context_value,
)
from ..transport.client import Msg, NatsClient, connect
from ..transport.envelope import deadline_remaining_s, envelope_error, envelope_ok
from ..transport.jetstream import ObjectStoreError
from ..transport.protocol import (
    ATTEMPT_HEADER,
    DEADLINE_HEADER,
    EXCLUDED_WORKERS_HEADER,
    KV_PREFILL_HEADER,
    PRIORITY_HEADER,
    STREAM_CANCEL_SUFFIX,
    TENANT_HEADER,
    TRACE_HEADER,
    TRACEPARENT_HEADER,
    WORKER_HEADER,
    parse_worker_list,
)
from .api import EngineError, ModelNotFound, Registry
from .kv_transfer import KVTransferFormatError, decode_kv_blob, encode_kv_blob
from .router import ADVERT_SUBJECT, RecentHeads, prompt_head_hash

log = logging.getLogger(__name__)

# model id accompanying a raw KVX1 blob pushed at a peer's kv_import
# subject (warm prefix-cache handoff, ISSUE 15); the Object Store
# reference form carries the model inside its JSON body instead
KV_MODEL_HEADER = "X-KV-Model"


def _zip_dir(path: str) -> bytes:
    """Zip a directory tree (relative paths) into an in-memory archive —
    runs in a thread from on_profile; trace dirs are tens of MB at most."""
    import io
    import zipfile

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, path))
    return buf.getvalue()


if hasattr(asyncio, "timeout"):
    _timeout = asyncio.timeout  # Python >= 3.11
else:

    @contextlib.asynccontextmanager
    async def _timeout(delay: float):
        """asyncio.timeout backport for 3.10: arm a timer that cancels the
        current task; the cancellation surfaces as TimeoutError at the
        ``async with`` boundary, exactly like the 3.11 primitive."""
        task = asyncio.current_task()
        assert task is not None
        fired = False

        def _fire() -> None:
            nonlocal fired
            fired = True
            task.cancel()

        handle = asyncio.get_running_loop().call_later(delay, _fire)
        try:
            yield
        except asyncio.CancelledError:
            if fired:
                raise asyncio.TimeoutError from None
            raise
        finally:
            handle.cancel()


class _ObjectStoreSpill:
    """Sync ``SpillStore`` adapter over the worker's JetStream Object Store
    (bucket ``kv-tier``) for serve/kv_tiers.py: the tier manager's spill
    thread calls put/get/delete, each marshalled onto the worker's asyncio
    loop with ``run_coroutine_threadsafe``. Unlike ``kv-transfer`` blobs the
    bucket is NOT single-use — it is the cold KV tier that survives process
    death, which is the whole restart-with-warm-cache story."""

    _PROBE_TIMEOUT_S = 2.0

    def __init__(self, nc, loop, timeout: float = 10.0):
        from ..transport.jetstream import ObjectStore

        self._store = ObjectStore(nc, timeout=timeout)
        self._loop = loop
        self._timeout = timeout
        self._bucket = "kv-tier"
        # availability probe, kicked off NOW but never awaited on the hot
        # path: the broker has no no-responders signalling, so a deployment
        # without the object-store module (bare EmbeddedBroker in tests,
        # core-NATS-only brokers) would otherwise stall the full transfer
        # timeout on every call — 10s added to engine load via
        # warm_exports, 10s per spill attempt. One short STREAM.CREATE,
        # latched both ways: ready, or dead for the process (host tier
        # stays, cold tier off).
        probe_t = min(self._PROBE_TIMEOUT_S, timeout)
        probe = ObjectStore(nc, timeout=probe_t)

        async def _probe_once() -> bool:
            try:
                await probe.ensure_bucket(self._bucket)
                return True
            except Exception as e:  # noqa: BLE001 — any failure = no tier
                log.warning(
                    "kv-tier object store unreachable (%s); cold KV spill "
                    "disabled for this process", type(e).__name__,
                )
                return False

        self._probe_fut = asyncio.run_coroutine_threadsafe(_probe_once(), loop)

    def _run(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            self._timeout + 5.0
        )

    def _alive(self, wait: bool) -> bool:
        """Probe verdict. ``wait=False`` (read path: engine-load warm
        restore, promotion fetches) treats an unresolved probe as dead-for-
        now so lookups degrade to instant misses; ``wait=True`` (the tier
        manager's background spill thread) blocks for the verdict."""
        try:
            if wait:
                return bool(self._probe_fut.result(self._PROBE_TIMEOUT_S + 5.0))
            return self._probe_fut.done() and bool(self._probe_fut.result(0))
        except Exception:  # noqa: BLE001 — cancelled/timed out probe = dead
            return False

    def put(self, name: str, data: bytes) -> None:
        if not self._alive(wait=True):
            raise ObjectStoreError("kv-tier object store unavailable")
        self._run(self._store.put(self._bucket, name, data))

    def get(self, name: str) -> bytes | None:
        from ..transport.jetstream import ObjectNotFound

        if not self._alive(wait=False):
            return None  # no (confirmed) cold tier: same as a miss
        try:
            return self._run(self._store.get(self._bucket, name))
        except ObjectNotFound:
            return None  # never spilled, or pruned: a clean miss

    def delete(self, name: str) -> None:
        if not self._alive(wait=False):
            return
        with contextlib.suppress(Exception):
            self._run(self._store.delete(self._bucket, name))


class Worker:
    """One serving process: NATS subscriptions + an in-process model registry."""

    def __init__(self, config: WorkerConfig, registry: Registry):
        self.config = config
        self.registry = registry
        self.worker_id = config.worker_id
        self.nc: NatsClient | None = None
        self._started = asyncio.Event()
        self._stop = asyncio.Event()
        self._requests_total = 0
        self._tokens_total = 0
        self._streams_cancelled = 0  # consumer-gone aborts (<inbox>.cancel)
        self._profiling = False
        self._supervisor_task: asyncio.Task | None = None
        self._t0 = time.monotonic()
        # -- cluster state (serve/router.py) ---------------------------------
        self.draining = False
        self._queue_subs: list = []  # dropped on drain; control subs stay
        self._advert_task: asyncio.Task | None = None
        self._advert_seq = 0
        self._recent_heads = RecentHeads()
        self._excluded_bounce_total = 0  # X-Excluded-Workers self-matches
        self._drain_bounce_total = 0  # requests bounced while draining
        # -- disaggregated prefill/decode (ISSUE 13) -------------------------
        # bytes/ms by direction: "export" is KV shipped to decode peers (we
        # are the prefill side), "import" is KV pulled from a prefill peer
        self._kv_transfer_bytes = {"export": 0, "import": 0}
        self._kv_transfer_ms = {"export": 0.0, "import": 0.0}
        self._kv_transfer_failures = 0  # pulls that fell back to local prefill
        # -- warm prefix-cache handoff (ISSUE 15) ----------------------------
        # hot prefixes pushed to a replacement worker at drain/scale-up, and
        # prefixes received+imported from a draining donor
        self._warm_handoff_sent = 0
        self._warm_handoff_received = 0
        # chat requests slower than this end-to-end land in the event ring
        # for post-hoc diagnosis (0 disables)
        self._slow_request_ms = float(
            os.environ.get("OBS_SLOW_REQUEST_MS", "5000").strip() or 0
        )
        # -- cross-process spans (obs/trace.py + obs/aggregator.py) ----------
        # spans emitted in one event-loop tick coalesce into a single batch
        # publish on {prefix}.obs.spans; OBS_SPANS=0 disables emission
        self._span_buf: list[dict] = []
        self._span_flush_task: asyncio.Task | None = None
        self._spans_emitted_total = 0

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        cfg = self.config
        # count XLA compile-cache hits/misses from the very first engine
        # load (idempotent; surfaces as lmstudio_compile_cache_*_total)
        install_compile_cache_listener()
        self.nc = await connect(
            cfg.nats_url,
            # worker_id in the CONNECT name: the chaos harness's
            # worker-scoped sever rule (faults.sever_worker) keys on it
            name=f"tpu-worker-{self.worker_id}",
            max_reconnects=cfg.max_reconnects,
            reconnect_wait_s=cfg.reconnect_wait_s,
            reconnect_max_wait_s=cfg.reconnect_max_wait_s,
            ping_interval_s=cfg.ping_interval_s,
        )
        # cold KV tier (serve/kv_tiers.py): hand the registry a spill-store
        # factory over this connection so engine loads can give their tier
        # managers an Object Store behind the host-RAM tier. Late-bound —
        # the registry is constructed before the connection exists; a
        # registry without tiering (or tests' fakes) never passes the gate.
        if (
            getattr(cfg, "kv_spill_objstore", True)
            and getattr(self.registry, "kv_host_pool_bytes", 0) > 0
            and getattr(self.registry, "kv_spill_factory", None) is None
        ):
            loop = asyncio.get_running_loop()
            nc, spill_t = self.nc, cfg.kv_transfer_timeout_s
            self.registry.kv_spill_factory = (
                lambda: _ObjectStoreSpill(nc, loop, timeout=spill_t)
            )
        q = cfg.queue_group
        subs = {
            cfg.subject("list_models"): self.on_list_models,
            cfg.subject("pull_model"): self.on_pull_model,
            cfg.subject("delete_model"): self.on_delete_model,
            cfg.subject("chat_model"): self.on_chat_model,
            cfg.subject("sync_model_from_bucket"): self.on_sync_model_from_bucket,
            cfg.subject("health"): self.on_health,
            cfg.subject("metrics"): self.on_metrics,
            cfg.subject("metrics.prom"): self.on_metrics_prom,
            cfg.subject("events"): self.on_events,
            cfg.subject("profile"): self.on_profile,
        }
        if getattr(cfg, "debug_subjects", False):
            # deep-debug surface (DEBUG_SUBJECTS=1 only): slot tables with
            # block refcounts expose request shapes and debug.dump forces
            # disk writes, so the subjects simply don't exist by default
            subs[cfg.subject("debug.snapshot")] = self.on_debug_snapshot
            subs[cfg.subject("debug.dump")] = self.on_debug_dump
        # flight-recorder frames carry worker-level counters too: register
        # them with the registry so every engine's recorder sees them
        # (FakeRegistry in tests has no recorder_counters — guard)
        counters = getattr(self.registry, "recorder_counters", None)
        if counters is not None:
            counters["reconnects"] = lambda: getattr(self.nc, "reconnects", 0)
            counters["requests_total"] = lambda: self._requests_total
            counters["excluded_bounces"] = lambda: self._excluded_bounce_total
            counters["drain_bounces"] = lambda: self._drain_bounce_total
        for subject, handler in subs.items():
            sub = await self.nc.subscribe(subject, queue=q, cb=self._guarded(handler))
            self._queue_subs.append(sub)
        # directed per-worker subjects (plain subs, NOT the queue group):
        # the router steers at .chat_model; .health/.metrics.prom make one
        # specific worker scrapeable (the queue-group subjects route to a
        # random member). These survive a drain — control plane stays up.
        wid_prefix = f"{cfg.subject_prefix}.worker.{self.worker_id}"
        for op, handler in (
            ("chat_model", self.on_chat_model),
            ("health", self.on_health),
            ("metrics.prom", self.on_metrics_prom),
            # every worker serves kv_export (not just prefill-role ones):
            # an engine that cannot export replies no_export gracefully, so
            # a stale role map degrades to local prefill instead of timeout
            ("kv_export", self.on_kv_export),
            # warm prefix-cache handoff (ISSUE 15): kv_import receives a
            # pushed KVX1 blob (or an Object Store reference) from a
            # draining donor; kv_handoff tells THIS worker to push its
            # hottest prefixes to a named recipient (autoscaler control)
            ("kv_import", self.on_kv_import),
            ("kv_handoff", self.on_kv_handoff),
        ):
            await self.nc.subscribe(f"{wid_prefix}.{op}", cb=self._guarded(handler))
        # drain control: broadcast subject, each worker matches on payload
        await self.nc.subscribe(
            cfg.subject("admin.drain"), cb=self._guarded(self.on_admin_drain)
        )
        await self.nc.flush()
        if cfg.supervise_interval_s > 0:
            self._supervisor_task = asyncio.ensure_future(self._supervise())
        if getattr(cfg, "cluster_advert_interval_s", 0) > 0:
            self._advert_task = asyncio.ensure_future(self._advert_loop())
        self._started.set()
        log.info(
            "worker %s serving %s.* (queue=%s)",
            self.worker_id, cfg.subject_prefix, q,
        )

    async def run(self) -> None:
        await self.start()
        await self._stop.wait()
        await self.drain()

    def request_stop(self) -> None:
        self._stop.set()

    async def drain(self) -> None:
        if self._supervisor_task is not None:
            self._supervisor_task.cancel()
            self._supervisor_task = None
        if self._advert_task is not None:
            self._advert_task.cancel()
            self._advert_task = None
        if self.nc is not None:
            await self.nc.drain()

    # -- cluster adverts + graceful drain (ISSUE 10 tentpole) ----------------

    def build_advert(self) -> dict:
        """The compact membership advert ``{prefix}.cluster.adverts`` carries:
        identity, load (queue depth summed over engines, worst brownout
        level, HBM headroom), capacity (``slots`` summed over engines — a
        dp>1 worker really advertises dp x per-replica slots), the named
        mesh shape (routers prefer sp-capable workers for long prompts),
        loaded models, draining flag, and the head hashes of recently
        served prompts (router prefix-locality)."""
        depth = 0
        brownout = 0
        slots = 0
        tier_depth = 0
        for eng in self.registry.loaded_engines().values():
            b = getattr(eng, "batcher", None)
            if b is None:
                continue
            depth += int(getattr(b, "queue_depth", 0) or 0)
            slots += int(getattr(b, "max_slots", 0) or 0)
            brownout = max(brownout, int(getattr(b, "brownout_level", 0) or 0))
            # warm-KV depth (router tiebreak): host-tier entries held by
            # this worker's engines — a deeper tier serves repeat prefixes
            # without recompute, so equal-load routing prefers it
            tier_fn = getattr(b, "tier_stats", None)
            if tier_fn is not None:
                try:
                    ts = tier_fn()
                except Exception:  # noqa: BLE001 — adverts never crash
                    ts = None
                if ts:
                    tier_depth += int(ts.get("host_entries", 0) or 0)
        headroom_fn = getattr(self.registry, "_hbm_headroom_frac", None)
        try:
            headroom = float(headroom_fn()) if headroom_fn is not None else 1.0
        except Exception:  # noqa: BLE001 — an advert must never crash the loop
            headroom = 1.0
        mesh = getattr(self.registry, "mesh", None)
        return {
            "worker_id": self.worker_id,
            "role": getattr(self.config, "worker_role", ""),
            "queue_depth": depth,
            "slots": slots,
            "brownout": brownout,
            "hbm_headroom": round(headroom, 4),
            "mesh": dict(mesh.shape) if mesh is not None else {},
            "models": sorted(self.registry.loaded_engines()),
            "kv_tier_depth": tier_depth,
            "draining": self.draining,
            "heads": self._recent_heads.snapshot(),
            "seq": self._advert_seq,
        }

    async def _publish_advert(self) -> None:
        if self.nc is None:
            return
        self._advert_seq += 1
        try:
            await self.nc.publish(
                self.config.subject(ADVERT_SUBJECT),
                json.dumps(self.build_advert(), separators=(",", ":")).encode(),
            )
        except (ConnectionError, ValueError):
            pass  # reconnect in flight; the next tick re-advertises

    async def _advert_loop(self) -> None:
        try:
            while True:
                await self._publish_advert()
                await asyncio.sleep(self.config.cluster_advert_interval_s)
        except asyncio.CancelledError:
            return

    async def on_admin_drain(self, msg: Msg) -> None:
        """admin.drain {worker_id, deadline_s?} — puts THE NAMED worker (or
        every worker, with ``"*"``) into draining mode. Broadcast subject:
        all workers hear it, only addressees act and reply."""
        try:
            req = json.loads(msg.payload or b"{}")
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in Drain: {e}")
            return
        target = (req.get("worker_id") or "").strip()
        if not target:
            await self._respond_error(
                msg, "'worker_id' is required ('*' drains every worker)"
            )
            return
        if target not in ("*", self.worker_id):
            return  # addressed to a peer; its reply is the reply
        try:
            deadline_s = float(req.get("deadline_s", self.config.drain_deadline_s))
        except (TypeError, ValueError):
            await self._respond_error(msg, "'deadline_s' must be a number")
            return
        handoff_to = (req.get("handoff_to") or "").strip() or None
        result = await self.begin_drain(deadline_s, handoff_to=handoff_to)
        await self._respond_ok(msg, result)

    async def begin_drain(
        self, deadline_s: float | None = None, handoff_to: str | None = None
    ) -> dict:
        """Graceful handoff: stop accepting new queue-group work (drop the
        queue subs — the broker routes around us immediately), advertise the
        draining flag, let in-flight decode finish up to the drain deadline,
        then stop the batchers — which fail the remainder with the existing
        retryable "worker draining, retry on another worker" envelope so the
        client RetryPolicy lands them on a peer. Directed/control subjects
        stay up: a draining worker still answers health and bounces chat.

        With ``handoff_to`` (ISSUE 15), the hottest prefix-cache block sets
        are pushed to the named replacement worker after in-flight work
        settles and before the batchers stop — so the replacement starts
        with a hit rate instead of a cold cache."""
        if deadline_s is None:
            deadline_s = self.config.drain_deadline_s
        if self.draining:
            return {"worker_id": self.worker_id, "draining": True,
                    "already_draining": True}
        self.draining = True
        # suppress the registry's engine-restart path for the whole
        # teardown: a supervisor restart already sleeping out its backoff
        # must not resurrect an engine we are about to stop
        set_drain = getattr(self.registry, "set_draining", None)
        if set_drain is not None:
            set_drain(True)
        EVENTS.emit("worker_drain", worker_id=self.worker_id,
                    deadline_s=deadline_s, handoff_to=handoff_to or "")
        log.info("worker %s draining (deadline %.1fs)", self.worker_id, deadline_s)
        for sub in self._queue_subs:
            await sub.unsubscribe()
        self._queue_subs.clear()
        await self._publish_advert()  # peers + routers see draining NOW
        deadline = time.monotonic() + max(0.0, deadline_s)
        finished_in_time = True
        while True:
            busy = [
                mid for mid, eng in self.registry.loaded_engines().items()
                if getattr(getattr(eng, "batcher", None), "alive", False)
                and not getattr(eng.batcher, "idle", True)
            ]
            if not busy:
                break
            if time.monotonic() >= deadline:
                finished_in_time = False
                log.warning(
                    "worker %s drain deadline: %s still busy; failing the "
                    "remainder retryably", self.worker_id, busy,
                )
                break
            await asyncio.sleep(0.05)
        # zero-lost-work preemption: fold every still-running slot's full
        # token history (prompt + generated so far) into its prefix cache
        # BEFORE the handoff export below, so in-progress work ships to the
        # survivor too and the client's retry resumes as a prefix hit
        # instead of re-prefilling (and re-decoding) from scratch. No-op on
        # idle engines; best-effort — a failure falls back to the plain
        # retryable-drain envelope the stop() below produces anyway.
        harvested = {"slots": 0, "tokens": 0}
        for mid, eng in list(self.registry.loaded_engines().items()):
            b = getattr(eng, "batcher", None)
            harvest = getattr(b, "suspend_harvest_to_cache", None)
            if harvest is None or not getattr(b, "alive", False):
                continue
            try:
                got = await asyncio.to_thread(harvest)
                harvested["slots"] += int(got.get("slots", 0))
                harvested["tokens"] += int(got.get("tokens", 0))
            except Exception:  # noqa: BLE001
                log.warning("suspend-harvest failed for %s", mid, exc_info=True)
        handoff: dict | None = None
        if handoff_to and handoff_to != self.worker_id:
            # after the busy-wait, before the batcher stops: the cache
            # blocks must still be alive to export. Best-effort — a failed
            # handoff degrades the replacement to a cold cache, never
            # blocks the drain.
            handoff = await self.push_warm_handoff(handoff_to)
        stopped = []
        for mid, eng in list(self.registry.loaded_engines().items()):
            b = getattr(eng, "batcher", None)
            if b is not None and getattr(b, "alive", False) and hasattr(b, "stop"):
                # stop() drains in-flight slots with the retryable draining
                # envelope (registry's shutdown finish path); it blocks on
                # the owner thread, so keep the event loop breathing
                await asyncio.to_thread(b.stop)
                stopped.append(mid)
        await self._publish_advert()
        result = {
            "worker_id": self.worker_id,
            "draining": True,
            "finished_in_time": finished_in_time,
            "stopped_engines": stopped,
            "deadline_s": deadline_s,
        }
        if harvested["slots"]:
            result["harvested"] = harvested
        if handoff is not None:
            result["handoff"] = handoff
        return result

    async def _supervise(self) -> None:
        """Engine watchdog: every ``supervise_interval_s`` check each loaded
        batcher's owner thread — crashed (uncaught pump exception; its
        in-flight slots were already failed retryable) or hung (heartbeat
        stale while NOT idle; an idle owner blocks on its inbox and
        legitimately stops stamping) — and hand unhealthy engines to the
        registry's restart path (capped backoff; repeated crashes within the
        window poison the model). The watchdog itself must never die: every
        per-engine action is individually guarded."""
        cfg = self.config
        hb_timeout = cfg.engine_heartbeat_timeout_s
        restart = getattr(self.registry, "restart_engine", None)
        try:
            while True:
                await asyncio.sleep(cfg.supervise_interval_s)
                if self.draining:
                    continue  # drain stops batchers on purpose; no restarts
                for mid, eng in list(self.registry.loaded_engines().items()):
                    b = getattr(eng, "batcher", None)
                    if b is None or not hasattr(b, "alive"):
                        continue  # fake/test engines have no pump loop
                    try:
                        dead = not b.alive
                        hung = (
                            not dead
                            and hb_timeout > 0
                            and not b.idle
                            and b.heartbeat_age_s() > hb_timeout
                        )
                        if not dead and not hung:
                            continue
                        why = "crashed" if dead else (
                            f"hung (heartbeat {b.heartbeat_age_s():.1f}s stale)"
                        )
                        log.warning("supervisor: engine %s %s", mid, why)
                        EVENTS.emit("engine_supervisor", model=mid, state=why)
                        if restart is not None:
                            outcome = await restart(mid, reason=why)
                            log.info("supervisor: engine %s -> %s", mid, outcome)
                    except Exception:  # noqa: BLE001 — watchdog must survive
                        log.exception("supervisor action for %s failed", mid)
        except asyncio.CancelledError:
            return

    def _guarded(self, handler):
        """Last-resort catch-all: the Go reference replies with an error
        envelope on every failure path; an exception escaping a handler must
        not leave the requester waiting out its timeout."""

        async def run(msg: Msg) -> None:
            try:
                await handler(msg)
            except Exception as e:  # noqa: BLE001 — deliberate catch-all seam
                log.exception("handler for %s failed", msg.subject)
                await self._respond_error(msg, f"internal error: {e}")

        return run

    # -- envelope helpers ----------------------------------------------------

    async def _respond_json(self, msg: Msg, payload: bytes, headers=None) -> None:
        # every reply names its worker (X-Worker-Id): the client retry loop
        # reads it to exclude a shedding worker from the next hop, and the
        # router uses it to attribute replies in a multi-worker scrape
        headers = dict(headers) if headers else {}
        headers.setdefault(WORKER_HEADER, self.worker_id)
        try:
            await msg.respond(payload, headers=headers)
        except (ConnectionError, ValueError):
            log.warning("failed to respond on %s", msg.subject)

    async def _respond_ok(self, msg: Msg, data=None) -> None:
        await self._respond_json(msg, envelope_ok(data))

    async def _respond_error(
        self, msg: Msg, error: str, data=None, headers=None, trace_id=None
    ) -> None:
        await self._respond_json(msg, envelope_error(error, data, trace_id=trace_id), headers=headers)

    # -- handlers ------------------------------------------------------------

    async def on_list_models(self, msg: Msg) -> None:
        """list_models → wraps the registry listing as ``data.models`` +
        ``data.http_status`` (nats_llm_studio.go:240-247 shape, status fixed
        at 200 since no HTTP hop exists any more)."""
        self._requests_total += 1
        try:
            async with _timeout(self.config.list_timeout_s):
                models = await self.registry.list_models()
        except asyncio.TimeoutError:
            await self._respond_error(msg, "timeout listing models")
            return
        except EngineError as e:
            await self._respond_error(msg, f"error listing models: {e}")
            return
        await self._respond_ok(msg, {"models": models, "http_status": 200})

    async def on_pull_model(self, msg: Msg) -> None:
        """pull_model {identifier} — nats_llm_studio.go:250-286. On failure the
        data still carries {model, output} (:266-275)."""
        self._requests_total += 1
        try:
            req = json.loads(msg.payload or b"{}")
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in PullModel: {e}")
            return
        identifier = (req.get("identifier") or "").strip()
        if not identifier:
            await self._respond_error(msg, "'identifier' is required")
            return
        try:
            async with _timeout(self.config.pull_timeout_s):
                output = await self.registry.pull(identifier)
        except asyncio.TimeoutError:
            await self._respond_error(
                msg, "error pulling model: deadline exceeded", {"model": identifier}
            )
            return
        except EngineError as e:
            await self._respond_error(
                msg, f"error pulling model: {e}", {"model": identifier, "output": str(e)}
            )
            return
        await self._respond_ok(msg, {"model": identifier, "output": output})

    async def on_delete_model(self, msg: Msg) -> None:
        """delete_model {model_id} — nats_llm_studio.go:288-324. Error
        responses include the attempted dir (:304-313); success returns
        ``deleted_dir`` (:316-323)."""
        self._requests_total += 1
        try:
            req = json.loads(msg.payload or b"{}")
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in DeleteModel: {e}")
            return
        model_id = (req.get("model_id") or "").strip()
        if not model_id:
            await self._respond_error(msg, "'model_id' is required")
            return
        try:
            async with _timeout(self.config.delete_timeout_s):
                deleted_dir = await self.registry.delete(model_id)
        except asyncio.TimeoutError:
            await self._respond_error(msg, "error deleting model: deadline exceeded", {"model": model_id})
            return
        except EngineError as e:
            data = {"model": model_id}
            attempted = getattr(e, "dir", None)
            if attempted:
                data["dir"] = str(attempted)
            await self._respond_error(msg, f"error deleting model: {e}", data)
            return
        await self._respond_ok(msg, {"model": model_id, "deleted_dir": deleted_dir})

    async def on_chat_model(self, msg: Msg) -> None:
        """chat_model — nats_llm_studio.go:327-364. Payload is the OpenAI-style
        body passed through to the engine verbatim (:348); success wraps
        {http_status, response} (:356-362).

        Trace: the client's ``X-Trace-Id`` header (minted one if absent)
        becomes a per-request span record. The batcher stamps its stage
        transitions through ``payload["_trace"]``; the final envelope carries
        ``trace_id`` and the response ``stats.trace`` holds the waterfall —
        no extra round-trip."""
        self._requests_total += 1
        hdrs = msg.headers or {}
        try:
            attempt = int(hdrs[ATTEMPT_HEADER]) if ATTEMPT_HEADER in hdrs else None
        except (TypeError, ValueError):
            attempt = None
        # upstream span context (gateway/router Traceparent header): the
        # serve span this handler emits becomes that hop's child, so the
        # assembled cluster tree stays causally linked across retries
        parent = parse_span_context(hdrs.get(TRACEPARENT_HEADER))
        trace = Trace(hdrs.get(TRACE_HEADER) or new_trace_id(), attempt=attempt,
                      parent_span_id=parent[1] if parent else "")
        trace.mark("recv")
        if self.worker_id in parse_worker_list(hdrs.get(EXCLUDED_WORKERS_HEADER)):
            # a queue-group redelivery landed the retry back on the worker
            # that just shed/failed it: bounce retryably so the next hop
            # (with us in the header) reaches a peer
            self._excluded_bounce_total += 1
            await self._respond_error(
                msg,
                "worker excluded by this request's retry history, "
                "retry on another worker",
                # excluded_bounce marks this as a one-shot deflection: the
                # client drops us from the exclusion list after it, so a
                # single-worker group (or one whose every member already
                # shed once) can still serve the next attempt
                {"worker_id": self.worker_id, "excluded_bounce": True},
                trace_id=trace.trace_id,
            )
            # the bounce is a real hop of the retry story: without its span
            # the assembled tree shows a hole where the redelivery landed
            self._emit_span(trace.to_span("worker.serve", self.worker_id,
                                          attrs={"outcome": "excluded_bounce"}))
            return
        if self.draining:
            self._drain_bounce_total += 1
            await self._respond_error(
                msg,
                "worker draining, retry on another worker",
                {"worker_id": self.worker_id},
                trace_id=trace.trace_id,
            )
            self._emit_span(trace.to_span("worker.serve", self.worker_id,
                                          attrs={"outcome": "drain_bounce"}))
            return
        if not msg.payload:
            await self._respond_error(msg, "empty payload in ChatModel", trace_id=trace.trace_id)
            return
        try:
            payload = json.loads(msg.payload)
            if not isinstance(payload, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(
                msg, f"invalid JSON in ChatModel: {e}", trace_id=trace.trace_id
            )
            return
        model_id = (payload.get("model") or "").strip()
        if not model_id:
            await self._respond_error(
                msg, "'model' is required in ChatModel", trace_id=trace.trace_id
            )
            return
        if payload.get("stream") and not msg.reply:
            return  # fire-and-forget stream request: nowhere to send tokens
        streaming = bool(payload.get("stream"))
        if self.config.router_prefix_head_chars > 0:
            # remember this prompt's head: the advert's ``heads`` set is the
            # router's prefix-cache locality signal (same hash both sides)
            self._recent_heads.add(prompt_head_hash(
                model_id, payload.get("messages"),
                self.config.router_prefix_head_chars,
            ))
        payload["_trace"] = trace  # engines pop it; fakes ignore it
        # tenant identity + priority class from the gateway-stamped bus
        # headers (transport/protocol.py): engines pop them and thread them
        # into the batcher's fair-share admission. Raw-NATS callers that
        # never heard of tenancy set neither — the registry defaults them
        # to the anonymous tenant at standard priority, so pre-QoS clients
        # and tests see unchanged behavior.
        if hdrs.get(TENANT_HEADER):
            payload["_tenant"] = str(hdrs[TENANT_HEADER])
        if hdrs.get(PRIORITY_HEADER):
            payload["_priority"] = str(hdrs[PRIORITY_HEADER])
        if self.config.deadline_propagation:
            # client budget (X-Deadline-Ms, wall ms) → monotonic deadline
            # capped by the per-op ladder; the batcher sheds expired work at
            # submit/admit and aborts mid-decode slots past it. An
            # already-expired budget still flows through: the shed there is
            # a retryable envelope, not a silent drop.
            remaining = deadline_remaining_s((msg.headers or {}).get(DEADLINE_HEADER))
            if remaining is not None:
                payload["_deadline"] = time.monotonic() + min(
                    remaining, self.config.chat_timeout_s
                )
        try:
            async with _timeout(self.config.chat_timeout_s):
                engine = await self.registry.get_engine(model_id)
                prefill_peer = (hdrs.get(KV_PREFILL_HEADER) or "").strip()
                if prefill_peer and prefill_peer != self.worker_id:
                    # disaggregated two-hop: the router already ran (or is
                    # running) this prompt's prefill on the named peer; pull
                    # its KV blocks into our pool before serving so decode
                    # starts from a full prefix-cache hit. Never fatal — any
                    # failure inside counts itself and we prefill locally.
                    await self._kv_prefetch(engine, model_id, payload,
                                            prefill_peer, trace)
                if streaming:
                    await self._chat_streaming(msg, engine, payload, trace)
                else:
                    response = await engine.chat(payload)
                    usage = response.get("usage") or {}
                    self._tokens_total += usage.get("completion_tokens", 0)
                    trace.mark("publish")
                    self._finish_trace(trace, model_id, response)
                    await self._respond_json(
                        msg,
                        envelope_ok(
                            {"http_status": 200, "response": response},
                            trace_id=trace.trace_id,
                        ),
                    )
        except asyncio.TimeoutError:
            await self._error_terminal(
                msg, "error in chat: deadline exceeded", {"model": model_id}, streaming, trace
            )
        except ModelNotFound as e:
            await self._error_terminal(
                msg, f"model not found: {e}", {"model": model_id}, streaming, trace
            )
        except EngineError as e:
            await self._error_terminal(
                msg, f"error in chat: {e}", {"model": model_id}, streaming, trace
            )
        except Exception as e:  # noqa: BLE001 — mid-stream crash must still terminate the stream
            log.exception("chat handler failed for %s", model_id)
            await self._error_terminal(
                msg, f"internal error: {e}", {"model": model_id}, streaming, trace
            )

    def _finish_trace(self, trace: Trace, model_id: str, response) -> None:
        """Inject the span waterfall into the response stats block and emit
        a slow-request event when the end-to-end time crosses the threshold."""
        report = trace.report()
        if isinstance(response, dict):
            response.setdefault("stats", {})["trace"] = report
        total_ms = report["spans_ms"].get("total_ms", 0.0)
        self._emit_span(trace.to_span(
            "worker.serve", self.worker_id,
            attrs={"model": model_id, "outcome": "ok",
                   "role": getattr(self.config, "worker_role", "") or "monolithic"},
        ))
        if self._slow_request_ms and total_ms > self._slow_request_ms:
            EVENTS.emit(
                "slow_request",
                model=model_id,
                trace_id=trace.trace_id,
                total_ms=total_ms,
                spans_ms=report["spans_ms"],
            )
            # attach the offending request's waterfall to a flight dump so
            # the pre-slowness frames (queue depth, brownout, pool state)
            # land next to the trace that suffered them
            eng = self.registry.loaded_engines().get(model_id)
            recorder = getattr(getattr(eng, "batcher", None), "recorder", None)
            if recorder is not None:
                recorder.dump(
                    "slow_request",
                    trace=report,
                    extra={"model": model_id, "total_ms": round(total_ms, 1)},
                )

    async def _error_terminal(
        self, msg: Msg, error: str, data, streaming: bool, trace: Trace | None = None
    ) -> None:
        """Error reply that, mid-stream, still carries the terminal
        ``Nats-Stream-Done`` header so ``request_stream`` consumers end
        cleanly instead of waiting out their idle timeout."""
        headers = {"Nats-Stream-Done": "1"} if streaming else None
        await self._respond_error(
            msg, error, data, headers=headers,
            trace_id=trace.trace_id if trace is not None else None,
        )
        if trace is not None:
            self._emit_span(trace.to_span(
                "worker.serve", self.worker_id,
                attrs={"outcome": "error", "error": error[:160]},
            ))

    # -- cross-process span emission (obs/aggregator.py consumes) ------------

    def _emit_span(self, span: dict) -> None:
        """Buffer one span for fire-and-forget batch publish on
        ``{prefix}.obs.spans``. Spans emitted in the same event-loop tick
        (serve + kv_pull of one request) coalesce into one message; span
        loss on a dropped connection is acceptable by design — spans are
        diagnostics, never load-bearing."""
        if self.nc is None or not getattr(self.config, "obs_spans", True):
            return
        self._span_buf.append(span)
        self._spans_emitted_total += 1
        if self._span_flush_task is None or self._span_flush_task.done():
            self._span_flush_task = asyncio.ensure_future(self._flush_spans())

    async def _flush_spans(self) -> None:
        await asyncio.sleep(0)  # let same-tick spans join this batch
        batch, self._span_buf = self._span_buf, []
        if not batch or self.nc is None:
            return
        try:
            await self.nc.publish(
                self.config.subject("obs.spans"),
                json.dumps({"spans": batch}, separators=(",", ":")).encode(),
            )
        except (ConnectionError, ValueError):
            pass  # reconnect in flight; these spans are lost, the next batch isn't

    async def _chat_streaming(self, msg: Msg, engine, payload: dict, trace: Trace) -> None:
        assert self.nc is not None
        if not msg.reply:
            return
        final: dict | None = None
        seq = 0
        model_id = payload.get("model", "")
        # consumer-gone watcher: request_stream publishes an empty message
        # to <inbox>.cancel when its consumer abandons the stream before the
        # terminal Nats-Stream-Done. Racing each chunk pull against that
        # signal lets this worker close the engine stream (freeing the
        # batcher slot) within one chunk instead of decoding to max_tokens
        # for nobody.
        cancel_sub = None
        cancel_task: asyncio.Task | None = None
        try:
            cancel_sub = await self.nc.subscribe(msg.reply + STREAM_CANCEL_SUFFIX)
            cancel_task = asyncio.ensure_future(cancel_sub.next_msg(timeout=None))
        except Exception:  # noqa: BLE001 — watcher is best-effort
            cancel_sub = None
            cancel_task = None
        gen = engine.chat_stream(payload)
        cancelled = False
        try:
            while True:
                step = asyncio.ensure_future(gen.__anext__())
                if cancel_task is not None:
                    await asyncio.wait(
                        {step, cancel_task},
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                    if cancel_task.done() and not step.done():
                        step.cancel()
                        with contextlib.suppress(
                            BaseException
                        ):
                            await step
                        cancelled = True
                        break
                try:
                    chunk = await step
                except StopAsyncIteration:
                    break
                if chunk.get("object") == "chat.completion":
                    final = chunk  # engines yield the aggregate last
                    continue
                await self.nc.publish(
                    msg.reply,
                    json.dumps({"ok": True, "data": {"chunk": chunk}}, separators=(",", ":")).encode(),
                    headers={"X-Seq": str(seq)},
                )
                seq += 1
        finally:
            if cancel_task is not None:
                cancel_task.cancel()
                with contextlib.suppress(BaseException):
                    await cancel_task
            if cancel_sub is not None:
                with contextlib.suppress(Exception):
                    await cancel_sub.unsubscribe()
            if cancelled:
                # aclose() raises GeneratorExit inside chat_stream at its
                # yield point; submit_batched's finally cancels the batcher
                # request, freeing the slot
                with contextlib.suppress(BaseException):
                    await gen.aclose()
        if cancelled:
            self._streams_cancelled += 1
            trace.mark("publish")
            self._emit_span(trace.to_span(
                "worker.serve", self.worker_id,
                attrs={"model": model_id, "outcome": "cancelled"},
            ))
            return
        if final is None:
            # An engine whose stream ends without the terminal chat.completion
            # aggregate is broken: regenerating via engine.chat() here would
            # silently double the cost AND could return a different completion
            # than the chunks already streamed. Fail loudly instead; the
            # caller's handler turns this into a terminal error envelope.
            raise EngineError(
                "engine stream ended without a chat.completion aggregate"
            )
        usage = final.get("usage") or {}
        self._tokens_total += usage.get("completion_tokens", 0)
        trace.mark("publish")
        self._finish_trace(trace, model_id, final)
        await self.nc.publish(
            msg.reply,
            envelope_ok({"http_status": 200, "response": final}, trace_id=trace.trace_id),
            headers={"Nats-Stream-Done": "1", "X-Seq": str(seq),
                     WORKER_HEADER: self.worker_id},
        )

    # -- disaggregated prefill/decode (ISSUE 13 tentpole) --------------------

    async def on_kv_export(self, msg: Msg) -> None:
        """kv_export — directed-only subject ``{prefix}.worker.<id>.kv_export``:
        a decode-role peer sends the chat body ``{model, messages}``; this
        (prefill-role) worker runs/looks-up the prompt's chunked prefill,
        gathers the finished KV blocks to host memory, and streams the
        serialized blob back as raw binary chunk messages followed by a
        terminal ``Nats-Stream-Done`` JSON envelope ``{sha256, bytes,
        chunks}``. Over ``kv_transfer_objstore_bytes`` the blob ships via
        the JetStream Object Store instead and the terminal envelope carries
        ``{bucket, object, sha256, bytes}``.

        An engine that cannot export (fake/test engine, prompt shorter than
        one prefill chunk, dense-only batcher) answers ``{no_export: true}``
        — a graceful skip the peer treats as "prefill locally", never an
        error."""
        self._requests_total += 1
        if not msg.reply:
            return  # nowhere to ship the blob
        t0 = time.monotonic()
        # span context from the pulling decode worker: the kv_export span
        # emitted here is the child of its kv_pull span, which is what makes
        # the two-hop visible in the assembled cluster tree instead of
        # vanishing from the requesting worker's waterfall
        hdrs = msg.headers or {}
        span_parent = parse_span_context(hdrs.get(TRACEPARENT_HEADER))
        span_trace_id = hdrs.get(TRACE_HEADER) or (
            span_parent[0] if span_parent else ""
        )
        span_t0 = time.time()
        span_attrs: dict = {"outcome": "error"}
        try:
            try:
                payload = json.loads(msg.payload or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("payload must be a JSON object")
            except ValueError as e:
                span_attrs["outcome"] = "bad_request"
                await self._error_terminal(
                    msg, f"invalid JSON in KvExport: {e}", None, True
                )
                return
            model_id = (payload.get("model") or "").strip()
            if not model_id:
                span_attrs["outcome"] = "bad_request"
                await self._error_terminal(
                    msg, "'model' is required in KvExport", None, True
                )
                return
            span_attrs["model"] = model_id
            try:
                async with _timeout(self.config.kv_transfer_timeout_s):
                    engine = await self.registry.get_engine(model_id)
                    export_fn = getattr(engine, "export_prefix", None)
                    export = (
                        await export_fn(dict(payload)) if export_fn is not None else None
                    )
            except asyncio.TimeoutError:
                span_attrs["outcome"] = "timeout"
                await self._error_terminal(
                    msg, "error in kv export: deadline exceeded",
                    {"model": model_id}, True,
                )
                return
            except (ModelNotFound, EngineError, ValueError, RuntimeError) as e:
                # ValueError/RuntimeError: the export's internal prefill can hit
                # the same admission guards as a chat (prompt >= max_seq, pool
                # exhaustion). A terminal error lets the puller fall back to
                # local prefill immediately instead of idling out its pull.
                span_attrs["error"] = str(e)[:160]
                await self._error_terminal(
                    msg, f"error in kv export: {e}", {"model": model_id}, True
                )
                return
            if export is None or not export.get("chunks"):
                span_attrs["outcome"] = "no_export"
                await self._respond_json(
                    msg, envelope_ok({"no_export": True}),
                    headers={"Nats-Stream-Done": "1"},
                )
                return
            try:
                blob = encode_kv_blob(export)
            except KVTransferFormatError as e:
                span_attrs["error"] = str(e)[:160]
                await self._error_terminal(
                    msg, f"error in kv export: {e}", {"model": model_id}, True
                )
                return
            digest = hashlib.sha256(blob).hexdigest()
            meta = {"sha256": digest, "bytes": len(blob),
                    "tokens": len(export["token_ids"])}
            sent = await self._ship_blob(msg, blob, meta)
            if sent:
                span_attrs.update(outcome="ok", bytes=len(blob),
                                  tokens=meta["tokens"])
                self._kv_transfer_bytes["export"] += len(blob)
                self._kv_transfer_ms["export"] += (time.monotonic() - t0) * 1000.0
                EVENTS.emit("kv_export", model=model_id, bytes=len(blob),
                            tokens=meta["tokens"], trace_id=span_trace_id or None)
        finally:
            if span_trace_id:
                self._emit_span(Span(
                    trace_id=span_trace_id,
                    span_id=new_span_id(),
                    stage="worker.kv_export",
                    worker_id=self.worker_id,
                    parent_span_id=span_parent[1] if span_parent else "",
                    t0=span_t0,
                    t1=time.time(),
                    attrs=span_attrs,
                ).to_dict())

    async def _ship_blob(self, msg: Msg, blob: bytes, meta: dict) -> bool:
        """Ship an encoded KV blob to ``msg.reply``: Object Store when the
        blob crosses the configured threshold (and JetStream answers),
        otherwise chunked inline publishes. Returns False only when even the
        inline path failed (connection gone)."""
        assert self.nc is not None
        cfg = self.config
        objstore_min = int(getattr(cfg, "kv_transfer_objstore_bytes", 0) or 0)
        if objstore_min > 0 and len(blob) >= objstore_min:
            from ..transport.jetstream import ObjectStore

            bucket = "kv-transfer"
            obj = f"{self.worker_id}-{meta['sha256'][:16]}"
            try:
                store = ObjectStore(self.nc, timeout=cfg.kv_transfer_timeout_s)
                await store.ensure_bucket(bucket)
                await store.put(bucket, obj, blob)
                await self._respond_json(
                    msg,
                    envelope_ok({**meta, "bucket": bucket, "object": obj}),
                    headers={"Nats-Stream-Done": "1"},
                )
                return True
            except Exception as e:  # noqa: BLE001 — objstore is an optimization
                # no JetStream on this broker (or a mid-put hiccup): the
                # inline chunk path below is the degradation, not a failure
                log.warning("kv export object-store path failed (%s); "
                            "falling back to inline chunks", e)
        chunk_bytes = max(1, int(getattr(cfg, "kv_transfer_chunk_bytes", 256 << 10)))
        limit = (getattr(self.nc, "server_info", None) or {}).get("max_payload")
        if limit:
            # leave headroom for the header block within the broker frame
            chunk_bytes = min(chunk_bytes, max(1, int(limit) - 1024))
        try:
            seq = 0
            for off in range(0, len(blob), chunk_bytes):
                await self.nc.publish(
                    msg.reply, blob[off : off + chunk_bytes],
                    headers={"X-KV-Seq": str(seq)},
                )
                seq += 1
            await self._respond_json(
                msg, envelope_ok({**meta, "chunks": seq}),
                headers={"Nats-Stream-Done": "1"},
            )
            return True
        except (ConnectionError, ValueError):
            log.warning("kv export to %s failed mid-ship", msg.reply)
            return False

    async def _kv_prefetch(
        self, engine, model_id: str, payload: dict, peer: str, trace: Trace
    ) -> None:
        """Decode-side pull: fetch the prompt's exported KV blocks from the
        prefill peer's directed ``kv_export`` subject, verify the SHA-256,
        and import them into the local engine's block pool + prefix cache so
        the chat below decodes from a full prefix hit (zero local prefill).

        EVERY failure mode — peer gone, transfer timeout, digest mismatch,
        malformed blob, decode-pool exhaustion on import — lands in
        ``lmstudio_kv_transfer_failures_total`` and returns normally: the
        caller serves with local prefill, bit-identical, just slower."""
        import_fn = getattr(engine, "import_prefix", None)
        if import_fn is None:
            return  # engine can't import (fake/test engine): local prefill
        assert self.nc is not None
        cfg = self.config
        t0 = time.monotonic()
        trace.mark("kv_pull")
        # the pull is its own span (child of this worker's serve span); its
        # id travels to the prefill peer in the Traceparent header so the
        # peer's kv_export span links under it in the assembled tree
        pull_span_id = new_span_id()
        pull_t0 = time.time()
        req = {"model": model_id, "messages": payload.get("messages")}
        subject = f"{cfg.subject_prefix}.worker.{peer}.kv_export"
        try:
            parts: list[bytes] = []
            meta: dict | None = None
            stream = self.nc.request_stream(
                subject,
                json.dumps(req, separators=(",", ":")).encode(),
                timeout=cfg.kv_transfer_timeout_s,
                idle_timeout=cfg.kv_transfer_timeout_s,
                headers={
                    TRACE_HEADER: trace.trace_id,
                    TRACEPARENT_HEADER: span_context_value(
                        trace.trace_id, pull_span_id
                    ),
                },
            )
            async for m in stream:
                if m.headers and "Nats-Stream-Done" in m.headers:
                    env = json.loads(m.payload)
                    if not env.get("ok"):
                        raise ConnectionError(
                            f"kv export failed on {peer}: {env.get('error')}"
                        )
                    meta = env.get("data") or {}
                else:
                    parts.append(m.payload)
            if meta is None:
                raise ConnectionError(f"kv export stream from {peer} ended early")
            if meta.get("no_export"):
                # graceful skip (peer can't export this prompt) — NOT a
                # transfer failure; just prefill locally
                trace.mark("kv_import")
                self._emit_span(Span(
                    trace_id=trace.trace_id, span_id=pull_span_id,
                    stage="worker.kv_pull", worker_id=self.worker_id,
                    parent_span_id=trace.span_id, t0=pull_t0, t1=time.time(),
                    attrs={"model": model_id, "peer": peer,
                           "outcome": "no_export"},
                ).to_dict())
                return
            if meta.get("object"):
                from ..transport.jetstream import ObjectStore

                store = ObjectStore(self.nc, timeout=cfg.kv_transfer_timeout_s)
                blob = await store.get(meta["bucket"], meta["object"])
                # best-effort cleanup: the blob is single-use
                with contextlib.suppress(Exception):
                    await store.delete(meta["bucket"], meta["object"])
            else:
                blob = b"".join(parts)
            if len(blob) != int(meta.get("bytes", -1)) or (
                hashlib.sha256(blob).hexdigest() != meta.get("sha256")
            ):
                raise KVTransferFormatError(
                    f"kv blob from {peer} failed integrity check "
                    f"({len(blob)} bytes)"
                )
            export = decode_kv_blob(blob)
            trace.mark("kv_import")
            imported = await import_fn(export)
            self._kv_transfer_bytes["import"] += len(blob)
            self._kv_transfer_ms["import"] += (time.monotonic() - t0) * 1000.0
            EVENTS.emit(
                "kv_import", model=model_id, peer=peer, bytes=len(blob),
                tokens=(imported or {}).get("tokens", 0),
                trace_id=trace.trace_id,
            )
            self._emit_span(Span(
                trace_id=trace.trace_id, span_id=pull_span_id,
                stage="worker.kv_pull", worker_id=self.worker_id,
                parent_span_id=trace.span_id, t0=pull_t0, t1=time.time(),
                attrs={"model": model_id, "peer": peer, "outcome": "ok",
                       "bytes": len(blob),
                       "tokens": (imported or {}).get("tokens", 0)},
            ).to_dict())
        except Exception as e:  # noqa: BLE001 — transfer failure must never fail the chat
            self._kv_transfer_failures += 1
            self._kv_transfer_ms["import"] += (time.monotonic() - t0) * 1000.0
            # the local re-prefill below is duplicated device work (the peer
            # already prefilled this prompt): tag the request so the batcher's
            # device-time ledger charges its prefill ms to the disagg-fallback
            # waste category instead of counting it as goodput
            payload["_waste_tag"] = "disagg_fallback_reprefill"
            log.warning(
                "kv prefetch from %s failed (%s: %s); serving with local prefill",
                peer, type(e).__name__, e,
            )
            # span context rides the failure event AND the anomaly dump, so
            # a kv_transfer_failed dump joins the assembled cluster trace by
            # trace_id (and this pull's exact hop by span_id)
            EVENTS.emit(
                "kv_transfer_failed", model=model_id, peer=peer,
                cause=type(e).__name__, error=str(e)[:200],
                trace_id=trace.trace_id, span_id=pull_span_id,
                parent_span_id=trace.span_id,
            )
            self._emit_span(Span(
                trace_id=trace.trace_id, span_id=pull_span_id,
                stage="worker.kv_pull", worker_id=self.worker_id,
                parent_span_id=trace.span_id, t0=pull_t0, t1=time.time(),
                attrs={"model": model_id, "peer": peer, "outcome": "failed",
                       "cause": type(e).__name__},
            ).to_dict())
            recorder = getattr(getattr(engine, "batcher", None), "recorder", None)
            if recorder is not None:
                recorder.dump(
                    "kv_transfer_failed",
                    trace=trace.report(),
                    extra={"model": model_id, "peer": peer,
                           "cause": type(e).__name__, "error": str(e)[:200],
                           "span_id": pull_span_id,
                           "parent_span_id": trace.span_id},
                )

    # -- warm prefix-cache handoff (ISSUE 15 tentpole) -----------------------

    async def push_warm_handoff(
        self, recipient: str, limit: int | None = None
    ) -> dict:
        """Push this worker's hottest prefix-cache block sets to
        ``recipient``'s directed ``kv_import`` subject so it starts serving
        with a hit rate instead of a cold cache. Used by a draining worker
        handing off to its replacement, and by the autoscaler to warm a
        fresh spawn from the best live peer. Best-effort throughout: every
        failed prefix is counted and skipped, never raised — a botched
        handoff degrades the recipient to a cold cache, nothing worse."""
        assert self.nc is not None
        cfg = self.config
        if limit is None:
            limit = int(getattr(cfg, "autoscale_handoff_prefixes", 4) or 0)
        if limit <= 0 or recipient == self.worker_id:
            return {"to": recipient, "sent": 0, "failed": 0, "tokens": 0}
        subject = f"{cfg.subject_prefix}.worker.{recipient}.kv_import"
        sent = failed = tokens = 0
        for mid, eng in list(self.registry.loaded_engines().items()):
            b = getattr(eng, "batcher", None)
            pc = getattr(b, "prefix_cache", None)
            export_fn = getattr(b, "export_prefix_blocks", None)
            hot_fn = getattr(pc, "hot_prefixes", None)
            if b is None or hot_fn is None or export_fn is None:
                continue  # fake/test engine or dense-only batcher: nothing to hand
            for path in hot_fn(limit):
                t0 = time.monotonic()
                try:
                    export = await asyncio.to_thread(export_fn, path)
                    if not export or not export.get("chunks"):
                        continue  # evicted between enumeration and gather
                    blob = encode_kv_blob(export)
                    ok = await self._push_kv_blob(subject, mid, blob)
                except Exception as e:  # noqa: BLE001 — handoff must not block the drain
                    log.warning("warm handoff of a %s prefix to %s failed: %s",
                                mid, recipient, e)
                    failed += 1
                    continue
                if ok:
                    sent += 1
                    tokens += len(export["token_ids"])
                    self._warm_handoff_sent += 1
                    self._kv_transfer_bytes["export"] += len(blob)
                    self._kv_transfer_ms["export"] += (
                        time.monotonic() - t0
                    ) * 1000.0
                else:
                    failed += 1
        EVENTS.emit("warm_handoff", worker_id=self.worker_id, to=recipient,
                    sent=sent, failed=failed, tokens=tokens)
        log.info("worker %s warm handoff to %s: %d prefixes (%d tokens), "
                 "%d failed", self.worker_id, recipient, sent, tokens, failed)
        return {"to": recipient, "sent": sent, "failed": failed,
                "tokens": tokens}

    async def _push_kv_blob(
        self, subject: str, model_id: str, blob: bytes
    ) -> bool:
        """One encoded blob to a peer's kv_import: a raw request when it
        fits under the broker frame limit (and the Object Store threshold),
        a JetStream Object Store reference otherwise. True when the peer
        confirms the import."""
        assert self.nc is not None
        cfg = self.config
        digest = hashlib.sha256(blob).hexdigest()
        objstore_min = int(getattr(cfg, "kv_transfer_objstore_bytes", 0) or 0)
        frame = (getattr(self.nc, "server_info", None) or {}).get("max_payload")
        inline_max = max(1, int(frame) - 1024) if frame else None
        via_objstore = (objstore_min > 0 and len(blob) >= objstore_min) or (
            inline_max is not None and len(blob) > inline_max
        )
        if via_objstore:
            from ..transport.jetstream import ObjectStore

            bucket = "kv-transfer"
            obj = f"{self.worker_id}-handoff-{digest[:16]}"
            store = ObjectStore(self.nc, timeout=cfg.kv_transfer_timeout_s)
            await store.ensure_bucket(bucket)
            await store.put(bucket, obj, blob)
            ref = {"model": model_id, "bucket": bucket, "object": obj,
                   "sha256": digest, "bytes": len(blob)}
            reply = await self.nc.request(
                subject, json.dumps(ref, separators=(",", ":")).encode(),
                timeout=cfg.kv_transfer_timeout_s,
            )
        else:
            reply = await self.nc.request(
                subject, blob, timeout=cfg.kv_transfer_timeout_s,
                headers={KV_MODEL_HEADER: model_id},
            )
        env = json.loads(reply.payload or b"{}")
        return bool(env.get("ok")) and bool(
            (env.get("data") or {}).get("imported")
        )

    async def on_kv_import(self, msg: Msg) -> None:
        """kv_import — directed subject ``{prefix}.worker.<id>.kv_import``:
        a draining donor (or the autoscaler's chosen peer) PUSHES a hot
        prefix here. The payload is either the raw KVX1 blob with the model
        id in the ``X-KV-Model`` header, or a JSON Object Store reference
        ``{model, bucket, object, sha256, bytes}`` for blobs over the
        threshold. The blocks land in the local pool + radix cache so the
        next matching prompt admits as a prefix hit. An engine that cannot
        import (fake/test engine) replies ``{imported: false}`` — a graceful
        no-op, never an error."""
        self._requests_total += 1
        payload = msg.payload or b""
        t0 = time.monotonic()
        try:
            if payload[:4] == b"KVX1":
                model_id = (
                    (msg.headers or {}).get(KV_MODEL_HEADER) or ""
                ).strip()
                if not model_id:
                    await self._respond_error(
                        msg,
                        f"'{KV_MODEL_HEADER}' header is required with a raw "
                        f"KV blob",
                    )
                    return
                blob = payload
            else:
                try:
                    ref = json.loads(payload or b"{}")
                    if not isinstance(ref, dict):
                        raise ValueError("payload must be a JSON object")
                except ValueError as e:
                    await self._respond_error(
                        msg, f"invalid JSON in KvImport: {e}"
                    )
                    return
                model_id = (ref.get("model") or "").strip()
                if not model_id or not ref.get("object"):
                    await self._respond_error(
                        msg, "'model' and 'object' are required in KvImport"
                    )
                    return
                from ..transport.jetstream import ObjectStore

                assert self.nc is not None
                store = ObjectStore(
                    self.nc, timeout=self.config.kv_transfer_timeout_s
                )
                blob = await store.get(ref["bucket"], ref["object"])
                # best-effort cleanup: the blob is single-use
                with contextlib.suppress(Exception):
                    await store.delete(ref["bucket"], ref["object"])
                if len(blob) != int(ref.get("bytes", -1)) or (
                    hashlib.sha256(blob).hexdigest() != ref.get("sha256")
                ):
                    raise KVTransferFormatError(
                        "handoff blob failed integrity check"
                    )
            export = decode_kv_blob(blob)
            engine = await self.registry.get_engine(model_id)
            import_fn = getattr(engine, "import_prefix", None)
            if import_fn is None:
                await self._respond_ok(
                    msg, {"imported": False, "reason": "no_import"}
                )
                return
            imported = await import_fn(export)
            self._warm_handoff_received += 1
            self._kv_transfer_bytes["import"] += len(blob)
            self._kv_transfer_ms["import"] += (time.monotonic() - t0) * 1000.0
            EVENTS.emit("warm_handoff_import", model=model_id, bytes=len(blob),
                        tokens=(imported or {}).get("tokens", 0))
            await self._respond_ok(msg, {
                "imported": True, "model": model_id,
                "tokens": (imported or {}).get("tokens", 0),
            })
        except (ModelNotFound, EngineError, KVTransferFormatError,
                ValueError, RuntimeError) as e:
            self._kv_transfer_failures += 1
            await self._respond_error(msg, f"error in kv import: {e}")

    async def on_kv_handoff(self, msg: Msg) -> None:
        """kv_handoff — control subject ``{prefix}.worker.<id>.kv_handoff``:
        ``{"to": worker_id, "limit"?}`` makes THIS worker push its hottest
        cached prefixes to the named peer. The autoscaler uses it to warm a
        freshly spawned worker from the best live donor without waiting for
        anyone to drain."""
        self._requests_total += 1
        try:
            req = json.loads(msg.payload or b"{}")
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in KvHandoff: {e}")
            return
        to = (req.get("to") or "").strip()
        if not to:
            await self._respond_error(msg, "'to' is required in KvHandoff")
            return
        if to == self.worker_id:
            await self._respond_error(msg, "cannot hand off to self")
            return
        limit = req.get("limit")
        try:
            limit = int(limit) if limit is not None else None
        except (TypeError, ValueError):
            await self._respond_error(msg, "'limit' must be an integer")
            return
        result = await self.push_warm_handoff(to, limit=limit)
        await self._respond_ok(msg, result)

    async def on_sync_model_from_bucket(self, msg: Msg) -> None:
        """sync_model_from_bucket {object_name, model_id?} — implements the
        README-only conceptual subject (/root/reference/README.md:286-318):
        object store → local model cache, responds {local_path}."""
        self._requests_total += 1
        try:
            req = json.loads(msg.payload or b"{}")
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in SyncModelFromBucket: {e}")
            return
        name = (req.get("object_name") or req.get("name") or "").strip()
        if not name:
            await self._respond_error(msg, "'object_name' is required")
            return
        try:
            async with _timeout(self.config.pull_timeout_s):
                local_path = await self.registry.sync_from_bucket(name, req.get("model_id"))
        except asyncio.TimeoutError:
            await self._respond_error(msg, "error syncing model: deadline exceeded", {"object": name})
            return
        except EngineError as e:
            await self._respond_error(msg, f"error syncing model: {e}", {"object": name})
            return
        await self._respond_ok(msg, {"object": name, "local_path": str(local_path)})

    async def on_health(self, msg: Msg) -> None:
        """health — heartbeat + counters (SURVEY.md §5: the reference has no
        health subject; client timeout is its only failure detector)."""
        data = {
            "status": "draining" if self.draining else "ok",
            "worker_id": self.worker_id,
            "role": getattr(self.config, "worker_role", ""),
            "draining": self.draining,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "requests_total": self._requests_total,
            "tokens_total": self._tokens_total,
            "streams_cancelled": self._streams_cancelled,
            "queue_group": self.config.queue_group,
            "reconnects": getattr(self.nc, "reconnects", 0),
        }
        data.update(self.registry.stats())
        # per-engine liveness/readiness (additive keys): lets clients and the
        # bench route around a worker whose engine is restarting
        health_fn = getattr(self.registry, "engine_health", None)
        if health_fn is not None:
            engines = health_fn()
            if engines:
                data["engines"] = engines
        poisoned_fn = getattr(self.registry, "poisoned_models", None)
        if poisoned_fn is not None:
            poisoned = poisoned_fn()
            if poisoned:
                data["poisoned"] = sorted(poisoned)
        await self._respond_ok(msg, data)

    async def on_metrics(self, msg: Msg) -> None:
        """metrics — full observability snapshot (SURVEY.md §5: counters on a
        NATS metrics subject): worker totals plus per-engine batcher stats
        (decode steps, tokens/step, peak active slots) and device info."""
        import jax

        engines = {}
        for mid, eng in self.registry.loaded_engines().items():
            batcher = getattr(eng, "batcher", None)
            if batcher is None or not hasattr(batcher, "stats"):
                continue
            reps = getattr(batcher, "replicas", None) or [batcher]
            for ri, rb in enumerate(reps):
                key = mid if len(reps) == 1 else f"{mid}#dp{ri}"
                engines[key] = rb.stats.snapshot()
        devices = [
            {"id": d.id, "platform": d.platform, "kind": d.device_kind}
            for d in jax.devices()
        ]
        data = {
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "requests_total": self._requests_total,
            "tokens_total": self._tokens_total,
            "queue_group": self.config.queue_group,
            "registry": self.registry.stats(),
            "engines": engines,
            "devices": devices,
        }
        await self._respond_ok(msg, data)

    def render_prometheus(self) -> str:
        """Worker totals + registry gauges + per-engine batcher counters and
        histograms in Prometheus text exposition (obs/prom.py)."""
        # worker_id on every family: a multi-worker scrape (or one pushed
        # through a shared gateway) stays attributable per worker
        r = PromRenderer(default_labels={"worker_id": self.worker_id})
        r.gauge("lmstudio_uptime_seconds", round(time.monotonic() - self._t0, 3))
        r.gauge("lmstudio_draining", 1 if self.draining else 0,
                help="1 while this worker is in graceful drain")
        r.counter("lmstudio_excluded_bounce_total", self._excluded_bounce_total,
                  help="chat requests bounced retryably because this worker "
                       "appeared in their X-Excluded-Workers header")
        r.counter("lmstudio_spans_emitted_total", self._spans_emitted_total,
                  help="trace spans published on the obs.spans subject")
        r.counter("lmstudio_drain_bounce_total", self._drain_bounce_total,
                  help="chat requests bounced retryably while draining")
        r.counter("lmstudio_requests_total", self._requests_total,
                  help="NATS requests handled by this worker")
        r.counter("lmstudio_tokens_total", self._tokens_total,
                  help="completion tokens generated")
        r.counter("lmstudio_streams_cancelled_total", self._streams_cancelled,
                  help="streaming chats aborted because the consumer vanished")
        # disaggregated prefill/decode families — ALWAYS present (zero-valued
        # on monolithic workers) so a role dashboard can group the fleet and
        # the disagg bench can scrape transfer volume without existence checks
        r.gauge("lmstudio_worker_role", 1,
                labels={"role": getattr(self.config, "worker_role", "") or "monolithic"},
                help="info gauge: this worker's serving role "
                     "(prefill | decode | monolithic)")
        for direction in ("export", "import"):
            dl = {"direction": direction}
            r.counter("lmstudio_kv_transfer_bytes_total",
                      self._kv_transfer_bytes[direction], labels=dl,
                      help="KV blob bytes moved between prefill and decode "
                           "workers, by direction")
            r.counter("lmstudio_kv_transfer_ms_total",
                      round(self._kv_transfer_ms[direction], 3), labels=dl,
                      help="wall milliseconds spent in KV transfers, by "
                           "direction (export: gather+ship; import: "
                           "pull+verify+pool write)")
        r.counter("lmstudio_kv_transfer_failures_total",
                  self._kv_transfer_failures,
                  help="KV pulls that failed (timeout, corrupt blob, pool "
                       "exhaustion) and fell back to local prefill")
        r.counter("lmstudio_warm_handoff_sent_total",
                  self._warm_handoff_sent,
                  help="hot prefix-cache block sets pushed to a replacement "
                       "worker (drain handoff or autoscaler warm-up)")
        r.counter("lmstudio_warm_handoff_received_total",
                  self._warm_handoff_received,
                  help="hot prefix-cache block sets imported from a donor "
                       "worker at kv_import")
        reg = self.registry.stats()
        for key in ("models_cached", "models_loaded", "engine_requests",
                    "hbm_committed_bytes"):
            v = reg.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                r.gauge(f"lmstudio_registry_{key}", v)
        mesh = reg.get("mesh") or {}
        r.gauge("lmstudio_mesh_tp", int(mesh.get("tp", 1)),
                help="tensor-parallel width of the serving mesh "
                     "(1 = unsharded serving)")
        r.gauge("lmstudio_mesh_dp", int(mesh.get("dp", 1)),
                help="data-parallel batcher replicas per worker "
                     "(1 = single batcher)")
        r.gauge("lmstudio_mesh_ep", int(mesh.get("ep", 1)),
                help="expert-parallel width of the serving mesh "
                     "(1 = experts unsharded)")
        r.gauge("lmstudio_mesh_sp", int(mesh.get("sp", 1)),
                help="sequence-parallel width: ring-attention prefill "
                     "degree for long prompts (1 = off)")
        # HBM ledger (obs/roofline.py, ticked by the flight recorder):
        # priced-component sum vs the allocator's bytes_in_use. Guarded —
        # test fakes implement stats() without the ledger key.
        hbm = reg.get("hbm_ledger")
        if efficiency_enabled() and isinstance(hbm, dict) and hbm:
            r.gauge("lmstudio_hbm_bytes_in_use", hbm.get("bytes_in_use", 0),
                    help="allocator bytes_in_use at the last ledger tick "
                         "(0 on backends without memory_stats)")
            r.gauge("lmstudio_hbm_priced_bytes", hbm.get("priced_bytes", 0),
                    help="sum of priced HBM components (weights+pool, "
                         "prefix cache, workspace slack)")
            r.gauge("lmstudio_hbm_unexplained_bytes",
                    hbm.get("unexplained_bytes", 0),
                    help="bytes_in_use minus priced components")
            r.gauge("lmstudio_hbm_drift_bytes", hbm.get("drift_bytes", 0),
                    help="unexplained-bytes growth above the ledger baseline")
        ledger = getattr(self.registry, "hbm_ledger", None)
        if efficiency_enabled() and ledger is not None:
            r.counter("lmstudio_hbm_drift_events_total",
                      getattr(ledger, "drift_events", 0),
                      help="hbm_drift events fired (unexplained bytes grew "
                           "monotonically past the threshold)")
        r.gauge("lmstudio_events_emitted_total", EVENTS.emitted)
        # XLA persistent-compile-cache effectiveness (obs/compile_cache.py;
        # the listener is installed at worker start). Distinguishes "restart
        # re-jitted from the cache in seconds" from "cache cold, every
        # program paid a full compile" — the r05 e2e_long failure mode.
        cc = compile_cache_counts()
        r.counter("lmstudio_compile_cache_hits_total", cc["hits"],
                  help="XLA persistent compile-cache hits in this process")
        r.counter("lmstudio_compile_cache_misses_total", cc["misses"],
                  help="XLA persistent compile-cache misses in this process")
        # fault-tolerance families — ALWAYS present (zero-valued when
        # nothing has failed) so dashboards and the chaos tests can assert
        # their existence, not just their increments
        r.counter("lmstudio_reconnects_total", getattr(self.nc, "reconnects", 0),
                  help="NATS connection re-establishments by this worker")
        r.counter("lmstudio_engine_restarts_total",
                  getattr(self.registry, "engine_restarts_total", 0),
                  help="supervisor-driven engine restarts")
        inflight_failed = getattr(self.registry, "inflight_failed_retryable", 0)
        for eng in self.registry.loaded_engines().values():
            b = getattr(eng, "batcher", None)
            for rb in (getattr(b, "replicas", None) or [b]) if b is not None else []:
                stats = getattr(rb, "stats", None)
                # live batchers' counts (every dp replica); crashed ones were
                # harvested into the registry accumulator at restart, so no
                # double count
                inflight_failed += getattr(stats, "inflight_failed_retryable", 0)
        r.counter("lmstudio_inflight_failed_retryable_total", inflight_failed,
                  help="in-flight requests failed with a retryable envelope "
                       "by an engine crash")
        poisoned_fn = getattr(self.registry, "poisoned_models", None)
        if poisoned_fn is not None:
            r.gauge("lmstudio_engines_poisoned", len(poisoned_fn()))
        restart_hist = getattr(self.registry, "restart_latency_ms", None)
        if restart_hist is not None:
            r.histogram("lmstudio_engine_restart_ms", restart_hist.snapshot())
        per_replica = []
        for mid, eng in self.registry.loaded_engines().items():
            b = getattr(eng, "batcher", None)
            if b is None:
                continue
            reps = getattr(b, "replicas", None) or [b]
            for ri, rb in enumerate(reps):
                per_replica.append((mid, ri if len(reps) > 1 else None, rb))
        for mid, ri, rb in per_replica:
            stats = getattr(rb, "stats", None)
            if stats is None or not hasattr(stats, "histograms"):
                continue
            # a dp>1 engine exposes every per-batcher family once per
            # replica under a "replica" label — the proof that an overload
            # wave actually distributed lives in per-replica
            # lmstudio_batcher_requests_total
            labels = {"model": mid}
            if ri is not None:
                labels["replica"] = str(ri)
            for name, v in stats.counters().items():
                r.counter(f"lmstudio_batcher_{name}_total", v, labels=labels)
            r.gauge("lmstudio_batcher_peak_active_slots", stats.peak_active, labels=labels)
            for cause, v in stats.shed_cause_counts().items():
                r.counter("lmstudio_batcher_shed_by_cause_total", v,
                          labels={**labels, "cause": cause})
            # multi-tenant QoS families (serve/qos.py): per-tenant serving
            # counters under a capped ``tenant`` label — the top-K tenants
            # by volume keep their own rows, the rest roll up into
            # tenant="other" so a key-guessing client cannot mint unbounded
            # label values
            tstats = getattr(rb, "tenant_stats", None)
            if tstats is not None:
                topk = getattr(self.config, "qos_tenant_topk", 8)
                for tenant, row in sorted(tstats.snapshot(topk).items()):
                    tl = {**labels, "tenant": tenant}
                    for key, fam in (
                        ("requests", "lmstudio_tenant_requests_total"),
                        ("served", "lmstudio_tenant_served_total"),
                        ("shed", "lmstudio_tenant_shed_total"),
                        ("preempted", "lmstudio_tenant_preempted_total"),
                        ("tokens", "lmstudio_tenant_tokens_total"),
                    ):
                        r.counter(fam, row.get(key, 0), labels=tl)
                    r.counter("lmstudio_tenant_queue_age_ms_total",
                              round(row.get("queue_age_ms_sum", 0.0), 3),
                              labels=tl,
                              help="summed enqueue->admit wait ms of served "
                                   "requests, by tenant (the fairness "
                                   "signal: divide by served for the mean)")
            # deadline/brownout families — always present (zero-valued when
            # quiet) so overload dashboards can alert on the first increment
            causes = stats.shed_cause_counts()
            r.counter("lmstudio_deadline_shed_total",
                      causes.get("deadline", 0), labels=labels,
                      help="requests shed because the client deadline "
                           "expired or became infeasible before prefill")
            r.counter("lmstudio_deadline_aborted_total",
                      getattr(stats, "cancel_causes", {}).get("deadline", 0),
                      labels=labels,
                      help="mid-decode slots aborted past the client deadline")
            r.gauge("lmstudio_brownout_level",
                    getattr(rb, "brownout_level", 0), labels=labels,
                    help="0=normal 1=brownout 2=shed-only")
            # decode-kernel family: which kernel serves paged decode and how
            # many fresh decode-program compiles the window ladder has cost
            # (flat under DECODE_KERNEL=pallas — its grid is context-length
            # independent)
            r.counter("lmstudio_decode_recompiles_total",
                      getattr(stats, "decode_recompiles", 0), labels=labels,
                      help="first-seen (program, static-args) combos on the "
                           "decode/verify paths — each is a fresh XLA compile")
            r.gauge("lmstudio_decode_kernel_pallas",
                    1 if getattr(rb, "decode_kernel", "xla") == "pallas"
                    else 0, labels=labels,
                    help="1 when the Pallas paged-decode kernel is serving")
            if hasattr(stats, "spec_counters"):
                # speculative decoding: lmstudio_spec_{verifies,drafted,
                # accepted}_total; the lmstudio_spec_accept_rate histogram
                # rides the generic histograms() loop below
                for name, v in stats.spec_counters().items():
                    r.counter(f"lmstudio_spec_{name}_total", v, labels=labels)
            tier_fn = getattr(rb, "tier_stats", None)
            tier = tier_fn() if tier_fn is not None else None
            if tier:
                # hierarchical KV tier + slot suspend/resume families
                # (serve/kv_tiers.py): gauges describe the host tier's
                # current occupancy, counters the chunk traffic between
                # tiers and the swap-don't-shed slot movements
                for name in ("host_entries", "host_bytes",
                             "host_budget_bytes", "spill_pending"):
                    if name in tier:
                        r.gauge(f"lmstudio_kv_tier_{name}", tier[name],
                                labels=labels)
                r.gauge("lmstudio_kv_tier_suspended_slots",
                        tier.get("suspended", 0), labels=labels,
                        help="slots currently swapped out to the host tier "
                             "awaiting resume")
                for name in ("demoted_chunks", "promoted_chunks",
                             "demote_failures", "host_hits", "host_misses",
                             "spilled_blobs", "fetched_blobs",
                             "spill_failures", "fetch_failures",
                             "demoted_blocks", "suspended_total",
                             "resumed_total", "suspend_failures",
                             "suspended_deadline_expired"):
                    if name in tier:
                        # stat keys like suspended_total already carry the
                        # suffix; strip it so the family never doubles up
                        base = name[:-6] if name.endswith("_total") else name
                        r.counter(f"lmstudio_kv_tier_{base}_total",
                                  tier[name], labels=labels)
            for name, h in stats.histograms().items():
                r.histogram(f"lmstudio_{name}", h.snapshot(), labels=labels)
            if hasattr(stats, "program_histograms"):
                # per-program device dispatch timing: every jit-grid program
                # the batcher launched, as one labeled histogram family —
                # answers "which program got slow" without a profiler run.
                # Host-side dispatch time only (the pump never blocks on the
                # result here); cold entries include XLA compile time.
                for name, h in sorted(stats.program_histograms().items()):
                    r.histogram("lmstudio_program_ms", h.snapshot(),
                                labels={**labels, "program": name})
                for name, h in sorted(stats.program_token_histograms().items()):
                    r.histogram("lmstudio_program_tokens", h.snapshot(),
                                labels={**labels, "program": name})
            if efficiency_enabled() and hasattr(stats, "cost_counters"):
                # compute-efficiency plane (obs/roofline.py): per-program
                # roofline totals, rolling MFU/MBU split by program class
                # (prefill is compute-bound → MFU headline; decode is
                # bandwidth-bound → MBU headline), and the device-time
                # ledger attributing every dispatch's ms to an outcome
                flops, bytes_ = stats.cost_counters()
                for name, v in sorted(flops.items()):
                    r.counter("lmstudio_program_flops_total", v,
                              labels={**labels, "program": name},
                              help="XLA cost-analysis flops dispatched, "
                                   "by program")
                for name, v in sorted(bytes_.items()):
                    r.counter("lmstudio_program_bytes_total", v,
                              labels={**labels, "program": name},
                              help="XLA cost-analysis bytes accessed, "
                                   "by program")
                util = stats.utilization()
                for cls in ("prefill", "decode"):
                    cl = {**labels, "class": cls}
                    r.gauge("lmstudio_mfu", round(util[cls]["mfu"], 6),
                            labels=cl,
                            help="achieved / peak FLOP rate over a rolling "
                                 "window, by program class")
                    r.gauge("lmstudio_mbu", round(util[cls]["mbu"], 6),
                            labels=cl,
                            help="achieved / peak HBM bandwidth over a "
                                 "rolling window, by program class")
                dt = stats.device_time_snapshot()
                for cat in sorted(dt["ms"]):
                    cl = {**labels, "category": cat}
                    r.counter("lmstudio_device_ms_total",
                              round(dt["ms"][cat], 3), labels=cl,
                              help="device-dispatch milliseconds attributed "
                                   "to a request outcome category")
                    r.counter("lmstudio_device_tokens_total",
                              dt["tokens"].get(cat, 0), labels=cl,
                              help="tokens delivered, by outcome category "
                                   "of the device time that produced them")
                r.gauge("lmstudio_goodput_tokens_per_device_s",
                        round(stats.goodput_tokens_per_device_s(), 3),
                        labels=labels,
                        help="served tokens per device-second across ALL "
                             "attributed device time (waste included in "
                             "the denominator)")
            pool_stats_fn = getattr(rb, "pool_stats", None)
            pool = pool_stats_fn() if pool_stats_fn is not None else None
            if pool is not None:
                # paged-KV block pool residency: total/free/shared block
                # gauges prove the zero-copy prefix-sharing story (shared >
                # 0 while a hit decodes; free returns to total after drain)
                # and the CoW counter stays 0 under chunk-aligned sharing
                for name in ("blocks_total", "blocks_free", "blocks_shared"):
                    r.gauge(f"lmstudio_kv_pool_{name}", pool[name],
                            labels=labels)
                r.counter("lmstudio_kv_pool_cow_copies_total",
                          pool["cow_copies"], labels=labels,
                          help="copy-on-write block duplications (a shared "
                               "block written by a live slot)")
            pcache = getattr(rb, "prefix_cache", None)
            if pcache is not None:
                # two new families: lmstudio_prefix_cache_*_total counters
                # (hits/misses/full_hits/hit_tokens/inserted/evicted blocks)
                # and the lmstudio_prefix_hit_tokens histogram, plus
                # residency gauges — the cache's whole serving story
                for name, v in pcache.counters().items():
                    r.counter(f"lmstudio_prefix_cache_{name}_total", v, labels=labels)
                r.gauge("lmstudio_prefix_cache_blocks", pcache.blocks, labels=labels)
                r.gauge("lmstudio_prefix_cache_bytes", pcache.bytes, labels=labels)
                r.histogram("lmstudio_prefix_hit_tokens",
                            pcache.hit_tokens_hist.snapshot(), labels=labels)
        return r.render()

    async def on_metrics_prom(self, msg: Msg) -> None:
        """metrics.prom — the same observability surface as ``metrics`` but
        rendered as Prometheus text exposition: point any scraper at
        ``nats req lmstudio.metrics.prom ''`` (or a thin HTTP bridge) and
        the admit-delay/TTFT/prefill/decode-step histograms arrive with
        cumulative ``le`` buckets, per-model labels, and counter families.
        Replies raw text, not a JSON envelope — scrapers want the body."""
        await self._respond_json(msg, self.render_prometheus().encode())

    async def on_events(self, msg: Msg) -> None:
        """events — the structured event ring (obs/events.py): sheds,
        cancels, ring compactions, engine load/evict, slow requests.
        Payload (optional): ``{kind?, limit?}`` filters by event kind and
        caps the reply to the most recent N (default 100)."""
        if not msg.reply:
            # fire-and-forget broadcasts land here too (e.g. the aggregator's
            # slo_burn fan-out on <prefix>.events) — nothing to answer
            return
        try:
            req = json.loads(msg.payload) if msg.payload and msg.payload.strip() else {}
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in Events: {e}")
            return
        kind = req.get("kind")
        try:
            limit = int(req.get("limit", 100))
        except (TypeError, ValueError):
            await self._respond_error(msg, "'limit' must be an integer")
            return
        await self._respond_ok(
            msg,
            {
                "events": EVENTS.snapshot(kind=kind, limit=limit),
                "emitted_total": EVENTS.emitted,
                "dropped": EVENTS.dropped,
                "capacity": EVENTS.capacity,
            },
        )

    async def on_profile(self, msg: Msg) -> None:
        """profile — capture a jax.profiler device trace for ``seconds``
        (default 2) into a worker-chosen directory and reply with the trace
        path. The SURVEY.md §5 profiling endpoint: drive load through
        chat_model while this runs, then inspect the trace with the
        TensorBoard profile plugin.

        The trace directory is always worker-chosen (mkdtemp): bus clients
        are untrusted (see config.py threat model) and a client-supplied
        path would be an arbitrary-directory-write primitive on the worker
        host (round-2 advisor, medium)."""
        import tempfile

        import jax

        import math

        try:
            req = json.loads(msg.payload) if msg.payload.strip() else {}
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in Profile: {e}")
            return
        seconds = float(req.get("seconds", 2.0))
        if not math.isfinite(seconds):
            await self._respond_error(msg, "'seconds' must be finite")
            return
        seconds = max(0.0, min(seconds, 60.0))
        if self._profiling:
            await self._respond_error(msg, "a profile capture is already running")
            return
        self._profiling = True
        trace_dir = tempfile.mkdtemp(prefix="tpu_trace_")
        try:
            jax.profiler.start_trace(trace_dir)
            try:
                await asyncio.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        finally:
            self._profiling = False
        reply: dict = {"trace_dir": trace_dir, "seconds": seconds}
        # a profile captured via a directed subject on a REMOTE worker is
        # useless as a local path: zip the trace and park it in the Object
        # Store (same JetStream plumbing as kv-transfer) so the requester
        # can pull it from anywhere. Best-effort — no JetStream on the
        # broker (or any upload hiccup) keeps the local-path reply.
        try:
            blob = await asyncio.to_thread(_zip_dir, trace_dir)
            digest = hashlib.sha256(blob).hexdigest()
            from ..transport.jetstream import ObjectStore

            assert self.nc is not None
            # short API timeout: on a broker WITHOUT JetStream the $JS.API
            # probe gets no responder and would otherwise stall the reply
            # for the full window — the requester's own timeout loses first
            store = ObjectStore(self.nc, timeout=5.0)
            bucket = "profiles"
            obj = f"{self.worker_id}-{digest[:16]}.zip"
            await store.ensure_bucket(bucket)
            await store.put(bucket, obj, blob)
            reply.update(bucket=bucket, object=obj, sha256=digest,
                         bytes=len(blob))
        except Exception as e:  # noqa: BLE001 — upload is an optimization
            log.warning("profile upload failed (%s: %s); trace stays local "
                        "at %s", type(e).__name__, e, trace_dir)
        await self._respond_ok(msg, reply)

    # -- deep-debug subjects (DEBUG_SUBJECTS=1 only) -------------------------

    async def on_debug_snapshot(self, msg: Msg) -> None:
        """debug.snapshot — live internals of every loaded engine's batcher:
        per-slot positions and block tables (with refcounts), prefix-cache
        radix summary, brownout state, and the flight recorder's frame tail.
        Payload (optional): ``{model?}`` restricts to one engine. Read-only
        and point-in-time consistent per engine (the slot view is swapped
        wholesale by the owner loop), but not across engines."""
        try:
            req = json.loads(msg.payload) if msg.payload and msg.payload.strip() else {}
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in DebugSnapshot: {e}")
            return
        want = (req.get("model") or "").strip() or None
        engines = {}
        for mid, eng in self.registry.loaded_engines().items():
            if want is not None and mid != want:
                continue
            snap_fn = getattr(getattr(eng, "batcher", None), "debug_snapshot", None)
            if snap_fn is not None:
                engines[mid] = snap_fn()
        if want is not None and not engines:
            await self._respond_error(msg, f"model not loaded: {want}")
            return
        await self._respond_ok(msg, {
            "worker_id": self.worker_id,
            "role": getattr(self.config, "worker_role", ""),
            "engines": engines,
        })

    async def on_debug_dump(self, msg: Msg) -> None:
        """debug.dump — force a flight-recorder dump for every loaded engine
        (or ``{model?}``) and reply with the written paths. The dump
        directory is always the worker's OBS_DUMP_DIR — a client-supplied
        path would be an arbitrary-directory-write primitive (same threat
        model as on_profile's mkdtemp)."""
        try:
            req = json.loads(msg.payload) if msg.payload and msg.payload.strip() else {}
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
        except ValueError as e:
            await self._respond_error(msg, f"invalid JSON in DebugDump: {e}")
            return
        want = (req.get("model") or "").strip() or None
        paths = {}
        for mid, eng in self.registry.loaded_engines().items():
            if want is not None and mid != want:
                continue
            recorder = getattr(getattr(eng, "batcher", None), "recorder", None)
            if recorder is not None:
                path = recorder.dump("debug_request", force=True,
                                     extra={"model": mid})
                if path:
                    paths[mid] = path
        if not paths:
            await self._respond_error(
                msg,
                "no dump written (recorder disabled, OBS_DUMP_DIR unset, "
                "or no engine loaded)",
            )
            return
        await self._respond_ok(msg, {"dumps": paths})

"""Multi-tenant QoS primitives: API keys, fair share, and tenant rollups.

The stack served one anonymous tenant until PR 20 — overload shed
newest-first with no notion of who was asking, so one runaway client
degraded every user equally (ROADMAP item 4). This module holds the
policy pieces the QoS plane is assembled from; each is deliberately
dumb and synchronous so the enforcement points stay cheap:

* ``parse_api_keys`` / ``ApiKeySpec`` — the ``API_KEYS`` env spec
  mapping a bearer key to (tenant, priority class, weight, rate, quota).
  The gateway authenticates against it and stamps the resolved tenant/
  class onto the bus headers (transport/protocol.py TENANT_HEADER).
* ``TokenBucket`` — per-key request rate limiting at the front door
  (monotonic-clock refill, burst = 2 s of rate, ``retry_after_s`` for
  the 429 header).
* ``TenantUsage`` — per-tenant monthly token accounting; the gateway
  charges completion usage after each chat and refuses keys past their
  quota with a typed 429.
* ``DrrScheduler`` — deficit round-robin over per-tenant queues,
  weighted by priority class. The batcher owner loop reorders its
  waitlist through this before each admission pass, so admission
  converges to weighted fair share instead of FIFO arrival order.
  Single-tenant traffic degenerates to exact FIFO (backcompat: every
  pre-QoS test and raw-NATS client sees unchanged ordering).
* ``cap_tenant_rows`` — top-K + ``other`` rollup for every exposition
  that carries a ``tenant`` label, so a key-guessing client cannot blow
  up Prometheus cardinality (worker renderer, gateway, aggregator).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

# Priority classes, weakest first. Rank order is the SHED order: brownout
# and preemption consume batch before standard before premium, never the
# reverse. Weights are the DRR quantum multipliers — a premium tenant
# drains ~16x the tokens per round of a batch tenant under contention.
PRIORITY_CLASSES = ("batch", "standard", "premium")

_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}
_WEIGHT = {"batch": 1, "standard": 4, "premium": 16}

# identity of every unauthenticated / raw-NATS caller: existing clients
# and tests that never heard of tenancy keep working at standard priority
ANON_TENANT = "anonymous"
DEFAULT_PRIORITY = "standard"


def class_rank(priority: str) -> int:
    """0 = batch (shed first) .. 2 = premium (shed last). Unknown class
    strings map to standard — a garbled header must not grant premium."""
    return _RANK.get(priority, _RANK[DEFAULT_PRIORITY])


def class_weight(priority: str) -> int:
    return _WEIGHT.get(priority, _WEIGHT[DEFAULT_PRIORITY])


def normalize_priority(priority) -> str:
    """Clamp any wire value to a known class (headers are attacker-ish
    input: raw-NATS callers can claim anything; unknown claims become
    ``standard``, never ``premium``)."""
    p = str(priority or "").strip().lower()
    return p if p in _RANK else DEFAULT_PRIORITY


def format_priority_header(priority: str, weight: float = 0.0) -> str:
    """Wire encoding for ``PRIORITY_HEADER``: ``class`` or
    ``class:weight`` when the API key carries an explicit fair-share
    weight — so a per-key weight override survives the gateway -> router
    -> worker hop instead of collapsing back to the class default."""
    p = normalize_priority(priority)
    return f"{p}:{weight:g}" if weight > 0 else p


def parse_priority_header(value) -> tuple[str, float]:
    """Decode ``PRIORITY_HEADER``: ``(class, weight)`` with weight 0.0
    meaning "derive from class". Tolerates any garbage (raw-NATS callers
    set arbitrary headers): unknown class -> standard, bad weight -> 0."""
    raw = str(value or "").strip()
    p, _, w = raw.partition(":")
    try:
        weight = max(0.0, float(w)) if w else 0.0
    except ValueError:
        weight = 0.0
    return normalize_priority(p), weight


@dataclass(frozen=True)
class ApiKeySpec:
    """One parsed ``API_KEYS`` entry."""

    key: str
    tenant: str
    priority: str = DEFAULT_PRIORITY
    weight: float = 0.0  # 0 = derive from class
    rps: float = 0.0  # requests/s token-bucket rate; 0 = unlimited
    monthly_tokens: int = 0  # monthly completion-token quota; 0 = unlimited


def parse_api_keys(spec: str) -> dict[str, ApiKeySpec]:
    """Parse the ``API_KEYS`` spec: comma-separated
    ``key:tenant:class[:weight[:rps[:monthly_tokens]]]`` entries, e.g.
    ``sk-a:acme:premium:0:50:1000000,sk-b:hobby:batch``.

    Malformed entries raise (a half-configured auth table silently
    admitting everyone is worse than failing the gateway at boot).
    """
    keys: dict[str, ApiKeySpec] = {}
    for raw in (spec or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = [p.strip() for p in raw.split(":")]
        if len(parts) < 2 or not parts[0] or not parts[1]:
            raise ValueError(
                f"API_KEYS entry {raw!r}: want key:tenant:class"
                f"[:weight[:rps[:monthly_tokens]]]"
            )
        priority = parts[2].lower() if len(parts) > 2 and parts[2] else DEFAULT_PRIORITY
        if priority not in _RANK:
            raise ValueError(
                f"API_KEYS entry {raw!r}: class {priority!r} not in "
                f"{'/'.join(PRIORITY_CLASSES)}"
            )
        try:
            weight = float(parts[3]) if len(parts) > 3 and parts[3] else 0.0
            rps = float(parts[4]) if len(parts) > 4 and parts[4] else 0.0
            quota = int(parts[5]) if len(parts) > 5 and parts[5] else 0
        except ValueError:
            raise ValueError(
                f"API_KEYS entry {raw!r}: weight/rps/monthly_tokens must be numeric"
            ) from None
        if parts[0] in keys:
            raise ValueError(f"API_KEYS: duplicate key {parts[0]!r}")
        keys[parts[0]] = ApiKeySpec(
            key=parts[0], tenant=parts[1], priority=priority,
            weight=max(0.0, weight), rps=max(0.0, rps),
            monthly_tokens=max(0, quota),
        )
    return keys


class TokenBucket:
    """Classic token bucket over the monotonic clock. ``rate`` tokens/s
    refill up to a burst of ``max(1, 2 s of rate)``; one ``take()`` per
    request. Thread-safe (the gateway serves connections concurrently)."""

    def __init__(self, rate: float, burst: float | None = None):
        self.rate = max(0.0, float(rate))
        self.burst = float(burst) if burst else max(1.0, self.rate * 2.0)
        self._level = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        """True = admitted. A zero-rate bucket admits everything."""
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._level = min(self.burst, self._level + (now - self._t) * self.rate)
            self._t = now
            if self._level >= n:
                self._level -= n
                return True
            return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (429 Retry-After)."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            deficit = n - self._level
        return max(0.0, deficit / self.rate)


class TenantUsage:
    """Per-tenant monthly completion-token accounting. The month key is
    wall-clock UTC ``YYYY-MM`` — crossing the boundary implicitly resets
    every counter (old months are dropped, this is accounting not
    billing-grade bookkeeping). Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._month = ""
        self._tokens: dict[str, int] = {}
        self._requests: dict[str, int] = {}

    @staticmethod
    def _now_month() -> str:
        return time.strftime("%Y-%m", time.gmtime())

    def _roll(self) -> None:
        m = self._now_month()
        if m != self._month:
            self._month = m
            self._tokens = {}
            self._requests = {}

    def charge(self, tenant: str, tokens: int) -> int:
        """Add ``tokens`` to the tenant's month; returns the new total."""
        with self._lock:
            self._roll()
            self._requests[tenant] = self._requests.get(tenant, 0) + 1
            t = self._tokens.get(tenant, 0) + max(0, int(tokens))
            self._tokens[tenant] = t
            return t

    def tokens_used(self, tenant: str) -> int:
        with self._lock:
            self._roll()
            return self._tokens.get(tenant, 0)

    def over_quota(self, tenant: str, monthly_tokens: int) -> bool:
        if monthly_tokens <= 0:
            return False
        return self.tokens_used(tenant) >= monthly_tokens

    def snapshot(self) -> dict[str, dict[str, int]]:
        with self._lock:
            self._roll()
            return {
                t: {"tokens": self._tokens.get(t, 0),
                    "requests": self._requests.get(t, 0)}
                for t in set(self._tokens) | set(self._requests)
            }


class DrrScheduler:
    """Deficit round-robin across tenants, weighted by priority class.

    ``order(items, tenant_of, cost_of, weight_of)`` returns the items
    re-ordered into DRR service order WITHOUT consuming them — the
    batcher re-runs it over whatever is still waiting each admission
    pass, and per-tenant deficit counters persist across passes so a
    heavy tenant's over-service in one round is repaid in the next.
    FIFO order within a tenant is always preserved; with a single
    tenant the output equals the input (exact FIFO backcompat).

    Owner-thread only (the batcher calls it from ``_run``); the quantum
    is denominated in the same unit as ``cost_of`` (prompt tokens).
    """

    def __init__(self, quantum: float = 256.0):
        self.quantum = max(1.0, float(quantum))
        self._deficit: dict[str, float] = {}

    def order(self, items, tenant_of, cost_of, weight_of) -> list:
        if len(items) <= 1:
            return list(items)
        queues: dict[str, list] = {}
        weights: dict[str, float] = {}
        for it in items:
            t = tenant_of(it)
            queues.setdefault(t, []).append(it)
            # a tenant mixing classes (several keys) gets its best weight
            weights[t] = max(weights.get(t, 0.0), float(weight_of(it)))
        if len(queues) == 1:
            return list(items)
        # drop deficit state for tenants no longer queued: an absent
        # tenant must not bank unbounded credit while idle (classic DRR
        # resets the counter when the queue empties)
        for t in list(self._deficit):
            if t not in queues:
                del self._deficit[t]
        # round-robin visit order: stable by first arrival in `items`
        # (dict preserves insertion order), so equal-weight tenants
        # alternate rather than starving on name sort
        out: list = []
        active = list(queues)
        while active:
            next_active = []
            for t in active:
                self._deficit[t] = (
                    self._deficit.get(t, 0.0)
                    + self.quantum * max(1.0, weights.get(t, 1.0))
                )
                q = queues[t]
                while q and self._deficit[t] >= float(cost_of(q[0])):
                    self._deficit[t] -= float(cost_of(q[0]))
                    out.append(q.pop(0))
                if q:
                    next_active.append(t)
                else:
                    # emptied queue: no banked credit while idle
                    self._deficit[t] = 0.0
            active = next_active
        return out

    def forget(self, tenant: str) -> None:
        self._deficit.pop(tenant, None)


def cap_tenant_rows(rows: dict, top_k: int, key_of=None) -> dict:
    """Roll everything past the top-K tenants (by total value) into one
    ``other`` row. ``rows`` maps tenant -> number OR tenant -> dict of
    numeric counters (summed for ranking, merged key-wise into ``other``).
    A tenant literally named ``other`` merges into the rollup too. The
    anonymous tenant is ranked like any other. top_k <= 0 disables."""
    if top_k <= 0 or len(rows) <= top_k:
        return dict(rows)

    def total(v):
        if isinstance(v, dict):
            return sum(float(x) for x in v.values())
        return float(v)

    ranked = sorted(rows.items(), key=lambda kv: (-total(kv[1]), kv[0]))
    out: dict = {}
    other = None
    for i, (t, v) in enumerate(ranked):
        if i < top_k and t != "other":
            out[t] = v
        elif other is None:
            other = dict(v) if isinstance(v, dict) else v
        elif isinstance(v, dict):
            for k, x in v.items():
                other[k] = other.get(k, 0) + x
        else:
            other += v
    if other is not None:
        out["other"] = other
    return out


@dataclass
class TenantStats:
    """Per-tenant serving counters, shared between the batcher threads
    (submit-side sheds run on event loops; serves on the owner thread) —
    every mutation takes the lock, same discipline as
    ``BatcherStats.record_shed``."""

    _lock: threading.Lock = field(default_factory=threading.Lock)
    _rows: dict = field(default_factory=dict)

    def _row(self, tenant: str) -> dict:
        row = self._rows.get(tenant)
        if row is None:
            row = {"requests": 0, "served": 0, "shed": 0, "preempted": 0,
                   "tokens": 0, "queue_age_ms_sum": 0.0}
            self._rows[tenant] = row
        return row

    def record_request(self, tenant: str) -> None:
        with self._lock:
            self._row(tenant)["requests"] += 1

    def record_served(self, tenant: str, tokens: int, queue_age_ms: float) -> None:
        with self._lock:
            row = self._row(tenant)
            row["served"] += 1
            row["tokens"] += int(tokens)
            row["queue_age_ms_sum"] += float(queue_age_ms)

    def record_shed(self, tenant: str) -> None:
        with self._lock:
            self._row(tenant)["shed"] += 1

    def record_preempted(self, tenant: str) -> None:
        with self._lock:
            self._row(tenant)["preempted"] += 1

    def snapshot(self, top_k: int = 0) -> dict[str, dict]:
        with self._lock:
            rows = {t: dict(r) for t, r in self._rows.items()}
        return cap_tenant_rows(rows, top_k) if top_k else rows

"""LocalRegistry: the in-process replacement for LM Studio + the `lms` CLI.

Wires the four reference capabilities (list/pull/delete/chat —
/root/reference/nats_llm_studio.go:22-179) to the in-tree stack: ModelStore
(cache + Object Store), GGUF loader, and the JAX Generator. Model listings
are LM-Studio-shaped (README.md:66-80) so existing clients keep working.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import replace as dc_replace
from typing import Any, AsyncIterator

import jax

from ..engine.generator import GenStats, SamplingParams
from ..gguf.reader import open_gguf
from ..gguf.tokenizer import GGUFTokenizer
from ..models.config import ModelConfig
from ..models.llama import load_params_from_gguf
from ..obs import FlightRecorder, HbmLedger, LogHistogram, efficiency_enabled
from ..obs import emit as obs_emit
from ..parallel.sharding import validate_mesh_for_config
from ..store.manager import ModelStore, StoreError
from ..utils.nuid import next_nuid
from . import constrain as constrain_mod
from .api import ChatEngine, EngineError, ModelNotFound, Registry
from .batcher import (
    LOGPROBS_K,
    BatcherOverloaded,
    BatcherStopped,
    ContinuousBatcher,
)
from .brownout import BrownoutConfig
from .constrain import ConstraintError, compile_token_dfa, validate_response_format
from .qos import ANON_TENANT, DEFAULT_PRIORITY, parse_priority_header
from .template import render_chat_template, stop_token_ids

log = logging.getLogger(__name__)


def _hbm_budget_bytes() -> int | None:
    """Per-device memory budget for admission (None = unknown, no check).
    TPU backends report ``bytes_limit`` via memory_stats(); the env override
    exists for CPU-backed tests and for operators reserving headroom."""
    env = os.environ.get("TPU_HBM_BUDGET_BYTES", "").strip()
    if env:
        return int(env) or None
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — backend without memory stats
        return None
    if stats and "bytes_limit" in stats:
        return int(stats["bytes_limit"])
    return None


def _prefix_cache_blocks_env(default: int = 64) -> int:
    """Per-engine prefix-cache budget in blocks (serve/prefix_cache.py).
    ``PREFIX_CACHE=0`` (or false/off) is the hard off-switch; otherwise
    ``PREFIX_CACHE_BLOCKS`` sizes the radix cache (0 also disables)."""
    if os.environ.get("PREFIX_CACHE", "").strip().lower() in ("0", "false", "off"):
        return 0
    env = os.environ.get("PREFIX_CACHE_BLOCKS", "").strip()
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            log.warning("ignoring non-integer PREFIX_CACHE_BLOCKS=%r", env)
    return default


def _kv_paged_env(default: bool = True) -> bool:
    """Paged-KV master switch: one refcounted block pool instead of
    contiguous per-slot rings (serve/block_pool.py). Default ON;
    ``KV_PAGED=0`` (or false/off) restores the pre-paged layout."""
    env = os.environ.get("KV_PAGED", "").strip().lower()
    if not env:
        return default
    return env not in ("0", "false", "off")


def _kv_block_tokens_env(default: int = 16) -> int:
    """Tokens per pool block (``KV_BLOCK_TOKENS``). The batcher snaps this
    down (pow2 halving) until it divides the serving prefill chunk."""
    env = os.environ.get("KV_BLOCK_TOKENS", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            log.warning("ignoring non-integer KV_BLOCK_TOKENS=%r", env)
    return default


def _kv_pool_blocks_env(default: int = 0) -> int:
    """Pool population override (``KV_POOL_BLOCKS``). 0 = auto: every slot
    at max_seq plus the whole prefix-cache budget (zero starvation).
    Deployments under-provision here to pack more slots into the same HBM
    — blocks only materialize per-token, which is the point of paging."""
    env = os.environ.get("KV_POOL_BLOCKS", "").strip()
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            log.warning("ignoring non-integer KV_POOL_BLOCKS=%r", env)
    return default


def _kv_host_pool_bytes_env(default: int = 256 << 20) -> int:
    """Host-RAM KV tier budget in bytes (serve/kv_tiers.py,
    ``KV_HOST_POOL_BYTES``). 0 disables tiering — demoted prefix chunks
    are dropped instead of swapped to host memory."""
    env = os.environ.get("KV_HOST_POOL_BYTES", "").strip()
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            log.warning("ignoring non-integer KV_HOST_POOL_BYTES=%r", env)
    return default


def _kv_tier_policy_env() -> tuple[int, float, int]:
    """(promote_chunks, demote_free_frac, spill_max_objects) from the env
    — the KVTierManager policy knobs (KV_PROMOTE_CHUNKS /
    KV_DEMOTE_FREE_FRAC / KV_SPILL_MAX_OBJECTS)."""
    try:
        promote = max(1, int(os.environ.get("KV_PROMOTE_CHUNKS", "").strip() or 64))
    except ValueError:
        promote = 64
    try:
        frac = float(os.environ.get("KV_DEMOTE_FREE_FRAC", "").strip() or 0.10)
    except ValueError:
        frac = 0.10
    try:
        max_obj = max(1, int(os.environ.get("KV_SPILL_MAX_OBJECTS", "").strip() or 512))
    except ValueError:
        max_obj = 512
    return promote, min(max(frac, 0.0), 0.9), max_obj


def _spec_decode_env(default_k: int = 6) -> tuple[int, int]:
    """(spec_decode_k, spec_max_active) from the env (serve/spec.py).
    ``SPEC_DECODE=0`` (or false/off) is the hard off-switch; otherwise
    ``SPEC_DECODE_K`` sizes the draft (0 also disables) and
    ``SPEC_DECODE_MAX_ACTIVE`` bounds the occupancy at which verify
    dispatches still run."""
    k = default_k
    if os.environ.get("SPEC_DECODE", "").strip().lower() in ("0", "false", "off"):
        k = 0
    else:
        env = os.environ.get("SPEC_DECODE_K", "").strip()
        if env:
            try:
                k = max(0, int(env))
            except ValueError:
                log.warning("ignoring non-integer SPEC_DECODE_K=%r", env)
    max_active = 4
    env = os.environ.get("SPEC_DECODE_MAX_ACTIVE", "").strip()
    if env:
        try:
            max_active = max(1, int(env))
        except ValueError:
            log.warning("ignoring non-integer SPEC_DECODE_MAX_ACTIVE=%r", env)
    return k, max_active


def _env_float(name: str, default: float) -> float:
    env = os.environ.get(name, "").strip()
    if env:
        try:
            return float(env)
        except ValueError:
            log.warning("ignoring non-numeric %s=%r", name, env)
    return default


def _brownout_env(enabled: bool | None = None) -> BrownoutConfig | None:
    """Adaptive-brownout config from the env (serve/brownout.py), or None
    when disabled. ``BROWNOUT=0`` (or false/off) is the hard off-switch
    (default on); the BROWNOUT_* threshold knobs tune the hysteresis."""
    if enabled is None:
        enabled = os.environ.get("BROWNOUT", "").strip().lower() not in (
            "0", "false", "off",
        )
    if not enabled:
        return None
    return BrownoutConfig(
        depth_hi=_env_float("BROWNOUT_DEPTH_HI", 0.75),
        depth_lo=_env_float("BROWNOUT_DEPTH_LO", 0.40),
        age_hi_ms=_env_float("BROWNOUT_AGE_HI_MS", 1500.0),
        age_lo_ms=_env_float("BROWNOUT_AGE_LO_MS", 500.0),
        hbm_lo_frac=_env_float("BROWNOUT_HBM_LO", 0.05),
        dwell_s=_env_float("BROWNOUT_DWELL_S", 2.0),
    )


def _pull_precompile_env(default: bool = True) -> bool:
    v = os.environ.get("PULL_PRECOMPILE", "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off")


def _compile_cache_dir_configured() -> bool:
    """Whether a persistent XLA compile cache is active in this process
    (WorkerConfig.configure_jax or the JAX env knob). Pull-time precompile
    only pays off when the compiled grid lands somewhere a replacement
    worker can replay it from."""
    try:
        return bool(jax.config.jax_compilation_cache_dir)
    except AttributeError:
        return False


def _deadline_min_tokens_env(default: int = 1) -> int:
    """Feasibility floor for deadline-aware admission: a request that cannot
    deliver this many tokens before its deadline skips prefill and is shed
    retryably (DEADLINE_MIN_TOKENS, default 1 = just the first token)."""
    env = os.environ.get("DEADLINE_MIN_TOKENS", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            log.warning("ignoring non-integer DEADLINE_MIN_TOKENS=%r", env)
    return default


class JaxChatEngine(ChatEngine):
    """One loaded model: tokenizer + continuous batcher. Concurrent chats
    join the shared fixed-width decode step; the batcher's dedicated owner
    thread is the only mutator of device state (SURVEY.md §5)."""

    def __init__(
        self,
        model_id: str,
        batcher: ContinuousBatcher,
        tokenizer: GGUFTokenizer,
        cfg: ModelConfig,
        meta: dict[str, Any],
        quantization: str = "",
    ):
        self.model_id = model_id
        self.batcher = batcher
        self.tokenizer = tokenizer
        self.cfg = cfg
        self.meta = meta
        self.quantization = quantization
        self._stop_ids = stop_token_ids(tokenizer)

    # -- internals -----------------------------------------------------------

    def _sampling(self, payload: dict) -> SamplingParams:
        return SamplingParams(
            temperature=float(payload.get("temperature", 0.8)),
            top_p=float(payload.get("top_p", 1.0)),
            top_k=int(payload.get("top_k", 0)),
            max_tokens=int(payload.get("max_tokens") or payload.get("max_completion_tokens") or 256),
            seed=payload.get("seed"),
            stop_ids=self._stop_ids,
        )

    def _encode_prompt(self, payload: dict) -> list[int]:
        messages = payload.get("messages") or []
        prompt = render_chat_template(self.meta, messages, add_generation_prompt=True)
        return self.tokenizer.encode(prompt)

    def _completion(self, text: str, n_prompt: int, n_out: int, finish: str,
                    stats=None, logprobs=None) -> dict:
        """OpenAI-style body with LM Studio's stats block
        (/root/reference/README.md:208-231)."""
        choice: dict[str, Any] = {
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": finish,
        }
        if logprobs is not None:
            choice["logprobs"] = logprobs
        out: dict[str, Any] = {
            "id": f"chatcmpl-{next_nuid()[:12].lower()}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": self.model_id,
            "choices": [choice],
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_out,
                "total_tokens": n_prompt + n_out,
            },
        }
        if stats is not None:
            out["stats"] = {
                "tokens_per_second": round(stats.decode_tok_s, 2),
                "time_to_first_token": round(stats.ttft_s, 4),
                "generation_time": round(stats.total_s, 4),
            }
        return out

    def _lp_entry(self, item: tuple, top_n: int) -> dict:
        """One OpenAI ``logprobs.content`` element from a batcher
        (tok, logprob, top_ids, top_logprobs) tuple."""
        tok, lp, top_ids, top_lps = item
        s = self.tokenizer.decode([int(tok)])
        entry: dict[str, Any] = {
            "token": s,
            "logprob": float(lp) if lp is not None else 0.0,
            "bytes": list(s.encode("utf-8")),
            "top_logprobs": [],
        }
        if top_n and top_ids:
            for tid, tlp in list(zip(top_ids, top_lps))[:top_n]:
                ts = self.tokenizer.decode([int(tid)])
                entry["top_logprobs"].append({
                    "token": ts,
                    "logprob": float(tlp),
                    "bytes": list(ts.encode("utf-8")),
                })
        return entry

    # -- ChatEngine ----------------------------------------------------------

    async def chat(self, payload: dict) -> dict:
        parts = []
        final = None
        async for chunk in self.chat_stream(payload):
            if chunk.get("object") == "chat.completion":
                final = chunk
            else:
                parts.append(chunk["choices"][0]["delta"].get("content", ""))
        return final if final is not None else self._completion("".join(parts), 0, 0, "stop")

    def _parse_ext(self, payload: dict):
        """Parse the engine-layer OpenAI extensions out of the payload:
        returns (token_dfa, want_logprobs, top_logprobs, n_choices).
        Raises EngineError with a client-facing message on bad values —
        the worker envelope carries it back as a 400-shaped error."""
        try:
            schema = validate_response_format(payload.get("response_format"))
        except ValueError as e:
            raise EngineError(f"invalid response_format: {e}") from e
        dfa = None
        if schema is not None:
            if not constrain_mod.enabled():
                raise EngineError(
                    "invalid response_format: constrained decoding is "
                    "disabled on this worker (CONSTRAIN=0)"
                )
            try:
                dfa = compile_token_dfa(
                    schema, self.tokenizer, self.cfg.vocab_size,
                    eos_ids=self._stop_ids,
                )
            except ConstraintError as e:
                raise EngineError(f"invalid response_format: {e}") from e
        try:
            top_n = int(payload.get("top_logprobs") or 0)
            n_choices = int(payload.get("n") or 1)
        except (TypeError, ValueError) as e:
            raise EngineError(f"invalid request: {e}") from e
        if not 0 <= top_n <= LOGPROBS_K:
            raise EngineError(
                f"invalid top_logprobs: must be between 0 and {LOGPROBS_K}"
            )
        want_lp = bool(payload.get("logprobs")) or top_n > 0
        if not 1 <= n_choices <= self.batcher.max_slots:
            raise EngineError(
                f"invalid n: must be between 1 and {self.batcher.max_slots}"
            )
        return dfa, want_lp, top_n, n_choices

    async def _stream_one(
        self, index: int, prompt_ids: list[int], sp: SamplingParams,
        trace, deadline, dfa, want_lp: bool, top_n: int, result: dict,
        waste_tag: str | None = None, qos: tuple | None = None,
    ) -> AsyncIterator[dict]:
        """Drive ONE choice through the batcher: yields OpenAI chunk dicts
        tagged with choice ``index`` and fills ``result`` with the
        aggregate (text / finish / stats / logprobs) on clean completion."""
        stats = GenStats(prompt_tokens=len(prompt_ids))
        t0 = time.perf_counter()
        toks: list[int] = []
        lp_entries: list[dict] = []
        pending_lp: list[dict] = []  # entries held with incomplete UTF-8 text
        emitted = 0
        end_info: dict = {}
        # batched iteration: a decode burst's tokens land as ONE chunk
        # message (the delta simply carries more text) — per-message
        # publish overhead is a real share of throughput at 64+ streams
        tenant, priority, weight = qos or (ANON_TENANT, DEFAULT_PRIORITY, 0.0)
        async for tok_batch in self.batcher.submit_batched(
            prompt_ids, sp, info=end_info, trace=trace, deadline=deadline,
            constrain=dfa, want_logprobs=want_lp, top_logprobs=top_n,
            waste_tag=waste_tag, tenant=tenant, priority=priority,
            weight=weight,
        ):
            if not toks:
                stats.ttft_s = time.perf_counter() - t0
            if want_lp:
                # ext deliveries are (tok, logprob, top_ids, top_lps) tuples
                entries = [self._lp_entry(t, top_n) for t in tok_batch]
                lp_entries.extend(entries)
                pending_lp.extend(entries)
                tok_batch = [t[0] for t in tok_batch]
            toks.extend(tok_batch)
            stats.completion_tokens += len(tok_batch)
            # decode incrementally; emit only completed UTF-8 text
            text = self.tokenizer.decode(toks)
            if len(text) > emitted and not text.endswith("�"):
                choice: dict[str, Any] = {
                    "index": index,
                    "delta": {"role": "assistant", "content": text[emitted:]},
                    "finish_reason": None,
                }
                if want_lp:
                    choice["logprobs"] = {"content": pending_lp}
                    pending_lp = []
                yield {
                    "object": "chat.completion.chunk",
                    "model": self.model_id,
                    "choices": [choice],
                }
                emitted = len(text)
        stats.total_s = time.perf_counter() - t0
        text = self.tokenizer.decode(toks)
        if len(text) > emitted or pending_lp:
            # flush text held back by the incomplete-UTF-8 guard so the chunk
            # stream concatenates to exactly the aggregate completion
            choice = {
                "index": index,
                "delta": {"role": "assistant", "content": text[emitted:]},
                "finish_reason": None,
            }
            if want_lp:
                choice["logprobs"] = {"content": pending_lp}
            yield {
                "object": "chat.completion.chunk",
                "model": self.model_id,
                "choices": [choice],
            }
        # the batcher's end reason covers max_tokens *and* cache-capacity
        # terminations ("length"); a worker-drain truncation surfaces as an
        # error when nothing was generated, or an explicit "shutdown"
        # finish_reason on a partial completion — never as a clean "stop"
        reason = end_info.get("finish_reason", "stop")
        if reason == "shutdown" and not toks:
            raise EngineError("worker draining, retry on another worker")
        result.update(
            text=text,
            n_out=len(toks),
            finish=reason if reason in ("length", "shutdown") else "stop",
            stats=stats,
            logprobs={"content": lp_entries} if want_lp else None,
        )

    async def chat_stream(self, payload: dict) -> AsyncIterator[dict]:
        # trace context injected by the worker (serve/worker.py): popped so
        # the engine-facing payload stays the verbatim OpenAI body, handed
        # to the batcher so its owner thread stamps the admit/prefill/
        # first-token transitions on the same record
        trace = payload.pop("_trace", None)
        # monotonic deadline injected by the worker from the client's
        # X-Deadline-Ms header, capped by the per-op timeout ladder; popped
        # for the same stays-verbatim reason as the trace
        deadline = payload.pop("_deadline", None)
        # waste attribution tag injected by the worker (e.g. a failed
        # disagg KV prefetch forcing a local re-prefill): popped so the
        # engine-facing payload stays the verbatim OpenAI body, handed to
        # the batcher which charges this request's prefill device-ms to
        # that category instead of "served"
        waste_tag = payload.pop("_waste_tag", None)
        # tenant identity + priority class injected by the worker from the
        # gateway-stamped X-Tenant/X-Priority bus headers: popped for the
        # same stays-verbatim reason; raw-NATS callers that set neither
        # serve as the anonymous tenant at standard priority (backcompat)
        tenant = str(payload.pop("_tenant", None) or ANON_TENANT)
        priority, weight = parse_priority_header(payload.pop("_priority", None))
        qos = (tenant, priority, weight)
        prompt_ids = self._encode_prompt(payload)
        sp = self._sampling(payload)
        dfa, want_lp, top_n, n_choices = self._parse_ext(payload)
        results = [dict() for _ in range(n_choices)]
        try:
            if n_choices == 1:
                async for chunk in self._stream_one(
                    0, prompt_ids, sp, trace, deadline, dfa, want_lp, top_n,
                    results[0], waste_tag=waste_tag, qos=qos,
                ):
                    yield chunk
            else:
                async for chunk in self._stream_n(
                    prompt_ids, sp, trace, deadline, dfa, want_lp, top_n,
                    results, waste_tag=waste_tag, qos=qos,
                ):
                    yield chunk
        except BatcherOverloaded as e:
            # honest overload envelope: the client (or the bus) retries on a
            # queue-group peer instead of waiting out an invisible queue
            raise EngineError(f"overloaded: {e}") from e
        except BatcherStopped as e:
            # raced a drain or an idle-eviction (HBM admission): same
            # retry-on-another-worker shape, not a generic crash envelope
            raise EngineError(str(e)) from e
        except ValueError as e:  # e.g. prompt longer than max_seq
            raise EngineError(str(e)) from e
        r0 = results[0]
        out = self._completion(
            r0["text"], len(prompt_ids),
            sum(r["n_out"] for r in results), r0["finish"],
            r0["stats"], logprobs=r0.get("logprobs"),
        )
        for i, r in enumerate(results[1:], start=1):
            choice: dict[str, Any] = {
                "index": i,
                "message": {"role": "assistant", "content": r["text"]},
                "finish_reason": r["finish"],
            }
            if r.get("logprobs") is not None:
                choice["logprobs"] = r["logprobs"]
            out["choices"].append(choice)
        yield out

    async def _stream_n(
        self, prompt_ids, sp, trace, deadline, dfa, want_lp, top_n, results,
        waste_tag: str | None = None, qos: tuple | None = None,
    ) -> AsyncIterator[dict]:
        """n>1 fan-out: each choice is its own batcher request. Choice 0
        launches alone; the rest launch after its first chunk, so choice
        0's admit has harvested the prompt into the radix prefix cache —
        under paged KV the siblings' identical prompts then admit as
        zero-copy block SHARES (copy-on-write on divergence) instead of n
        prefills and n block sets. Chunks from all choices interleave on
        one stream, tagged by ``choices[0].index``."""
        done = object()
        queue: asyncio.Queue = asyncio.Queue()
        started = asyncio.Event()

        def sp_for(i: int) -> SamplingParams:
            # distinct per-choice seeds keep choices distinct AND replayable;
            # with no seed every choice draws its own random stream anyway
            if i == 0 or sp.seed is None:
                return sp
            return dc_replace(sp, seed=sp.seed + i)

        async def drive(i: int) -> None:
            try:
                async for chunk in self._stream_one(
                    i, prompt_ids, sp_for(i), trace if i == 0 else None,
                    deadline, dfa, want_lp, top_n, results[i],
                    waste_tag=waste_tag if i == 0 else None, qos=qos,
                ):
                    await queue.put(chunk)
                    if i == 0:
                        started.set()
            except Exception as e:  # noqa: BLE001 — re-raised by the merger
                results[i]["error"] = e
            finally:
                if i == 0:
                    started.set()
                await queue.put(done)

        tasks = [asyncio.ensure_future(drive(0))]
        try:
            await started.wait()
            tasks += [
                asyncio.ensure_future(drive(i)) for i in range(1, len(results))
            ]
            finished = 0
            while finished < len(results):
                item = await queue.get()
                if item is done:
                    finished += 1
                    continue
                yield item
        finally:
            for t in tasks:
                t.cancel()
        for r in results:
            if "error" in r:
                # a missing choice makes the whole completion wrong: fail
                # the request honestly rather than return a short n
                raise r["error"]

    # -- disaggregated prefill/decode (serve/kv_transfer.py) -----------------

    async def export_prefix(self, payload: dict) -> dict | None:
        """Prefill-role half of disaggregated serving: ensure this chat
        payload's prompt KV is prefilled and harvested into the local
        radix prefix cache, then gather the cached blocks to host memory
        for shipment to a decode peer. Returns the ``serve.kv_transfer``
        export dict, or None when there is nothing chunk-aligned worth
        shipping (short prompt, harvest paused under brownout, cache
        pressure) — the decode side then serves with local prefill,
        which is always correct."""
        payload = dict(payload)
        trace = payload.pop("_trace", None)
        deadline = payload.pop("_deadline", None)
        prompt_ids = self._encode_prompt(payload)
        C = self.batcher.prefill_chunk
        if len(prompt_ids) < C:
            return None
        n_cover = (len(prompt_ids) // C) * C
        export = await asyncio.to_thread(
            self.batcher.export_prefix_blocks, prompt_ids
        )
        if export is None or len(export["token_ids"]) < n_cover:
            # cold cache: run the chunked prefill HERE (that is this
            # worker's whole job) — admit harvests the blocks into the
            # prefix cache, the single greedy token is discarded — then
            # re-gather. The decode peer samples the real first token
            # from the shipped chunk-end logits with the request's own
            # sampling params, so the throwaway settings don't leak.
            sp = SamplingParams(temperature=0.0, max_tokens=1)
            async for _ in self.batcher.submit(
                prompt_ids, sp, trace=trace, deadline=deadline
            ):
                pass
            export = await asyncio.to_thread(
                self.batcher.export_prefix_blocks, prompt_ids
            )
        return export

    async def import_prefix(self, export: dict) -> dict:
        """Decode-role half: drop transferred blocks into the local block
        pool and seed the prefix cache, so the chat that triggered the
        transfer admits as a prefix hit (full hit ⇒ zero prefill work).
        Raises on pool exhaustion or layout mismatch; the worker counts
        the failure and falls back to local prefill."""
        return await asyncio.to_thread(
            self.batcher.import_prefix_blocks, export
        )

    def info(self) -> dict:
        return {
            "id": self.model_id,
            "object": "model",
            "type": "llm",
            "publisher": self.model_id.split("/")[0] if "/" in self.model_id else "local",
            "arch": self.cfg.arch,
            "quantization": self.quantization,
            "state": "loaded",
            "max_context_length": self.cfg.max_seq_len,
            "loaded_context_length": self.batcher.max_seq,
            "batch_slots": self.batcher.max_slots,
        }

    async def unload(self) -> None:
        await asyncio.to_thread(self.batcher.stop)


class LocalRegistry(Registry):
    """Model lifecycle over a ModelStore + JAX engines."""

    def __init__(
        self,
        store: ModelStore,
        mesh=None,
        dtype: str | None = None,
        max_seq_len: int | None = None,
        max_batch_slots: int = 8,
        quant: str = "none",
        kv_quant: str = "none",
        wquant_group: int = 32,
        admit_queue_limit: int = 0,
        admit_max_age_ms: float = 0.0,
        prefix_cache_blocks: int | None = None,
        spec_decode_k: int | None = None,
        spec_max_active: int | None = None,
        restart_backoff_s: float = 0.5,
        restart_backoff_max_s: float = 30.0,
        max_restarts: int = 3,
        restart_window_s: float = 120.0,
        brownout: bool | None = None,
        deadline_min_tokens: int | None = None,
        kv_paged: bool | None = None,
        kv_block_tokens: int | None = None,
        kv_pool_blocks: int | None = None,
        prefill_chunk: int | None = None,
        obs_recorder: bool | None = None,
        obs_recorder_interval_ms: float | None = None,
        obs_dump_dir: str | None = None,
        worker_id: str = "",
        pull_precompile: bool | None = None,
        kv_host_pool_bytes: int | None = None,
        kv_spill_factory=None,
        qos_quantum_tokens: int | None = None,
        qos_preempt: bool | None = None,
    ):
        self.store = store
        self.mesh = mesh
        self.dtype = dtype or ("float32" if jax.default_backend() == "cpu" else "bfloat16")
        self.max_seq_len = max_seq_len
        self.max_batch_slots = max_batch_slots
        self.quant = quant
        # rows per int4 scale/zero-point group (only read when quant="int4")
        self.wquant_group = wquant_group
        # "int8": store the serving KV cache quantized (ops/kvcache.py) —
        # halves decode cache traffic and per-slot HBM, so the same chip
        # serves ~2x the concurrent slots
        self.kv_quant = kv_quant
        # overload bounds handed to every batcher (0 = off): depth sheds at
        # submit, age sheds at admit — see ContinuousBatcher.max_queue
        self.admit_queue_limit = admit_queue_limit
        self.admit_max_age_ms = admit_max_age_ms
        # per-engine prefix KV cache budget in chunk blocks (0 = off);
        # None = read PREFIX_CACHE / PREFIX_CACHE_BLOCKS from the env
        # speculative decoding knobs handed to every batcher (k 0 = off);
        # None = read SPEC_DECODE / SPEC_DECODE_K / SPEC_DECODE_MAX_ACTIVE
        env_k, env_ma = _spec_decode_env()
        self.spec_decode_k = spec_decode_k if spec_decode_k is not None else env_k
        self.spec_max_active = (
            spec_max_active if spec_max_active is not None else env_ma
        )
        self.prefix_cache_blocks = (
            prefix_cache_blocks
            if prefix_cache_blocks is not None
            else _prefix_cache_blocks_env()
        )
        # paged KV (serve/block_pool.py): one refcounted block pool shared
        # by live slots, the prefix cache, and spec decode. HBM admission
        # prices the POOL (not per-slot worst-case rows + a separate prefix
        # budget) — see _estimate_load_bytes. None = read KV_PAGED /
        # KV_BLOCK_TOKENS / KV_POOL_BLOCKS from the env.
        self.kv_paged = kv_paged if kv_paged is not None else _kv_paged_env()
        self.kv_block_tokens = (
            kv_block_tokens
            if kv_block_tokens is not None
            else _kv_block_tokens_env()
        )
        self.kv_pool_blocks = (
            kv_pool_blocks
            if kv_pool_blocks is not None
            else _kv_pool_blocks_env()
        )
        # hierarchical KV tiers (serve/kv_tiers.py): host-RAM tier budget
        # under the HBM block pool (0 disables tiering entirely).
        # kv_spill_factory() returns a SpillStore adapter for the cold
        # Object Store tier — the worker injects one over its JetStream
        # connection; None keeps the host tier terminal (no cold spill).
        self.kv_host_pool_bytes = (
            kv_host_pool_bytes
            if kv_host_pool_bytes is not None
            else _kv_host_pool_bytes_env()
        )
        self.kv_spill_factory = kv_spill_factory
        (self.kv_promote_chunks, self.kv_demote_free_frac,
         self.kv_spill_max_objects) = _kv_tier_policy_env()
        # prefill chunk size handed to every batcher (None = the batcher
        # default, clamped to max_seq_len). Tiny serving setups — tests and
        # the disagg bench — need small chunks so a short prompt still
        # covers whole chunks for KV export (serve/kv_transfer.py)
        self.prefill_chunk = prefill_chunk
        # adaptive brownout (serve/brownout.py) handed to every batcher;
        # None reads BROWNOUT from the env (default on), the BROWNOUT_*
        # threshold knobs tune the hysteresis. The HBM-headroom signal is
        # this registry's admission accounting, injected as a probe.
        self.brownout_cfg = _brownout_env(brownout)
        self.deadline_min_tokens = (
            deadline_min_tokens
            if deadline_min_tokens is not None
            else _deadline_min_tokens_env()
        )
        # multi-tenant QoS (serve/qos.py) handed to every batcher: the DRR
        # quantum (prompt tokens per fair-share round) and the premium
        # preempt-to-host-tier toggle. None reads QOS_QUANTUM_TOKENS here;
        # the batcher itself resolves a None qos_preempt from QOS_PREEMPT.
        self.qos_quantum_tokens = (
            qos_quantum_tokens
            if qos_quantum_tokens is not None
            else int(os.environ.get("QOS_QUANTUM_TOKENS", "256") or 256)
        )
        self.qos_preempt = qos_preempt
        self._engines: dict[str, JaxChatEngine] = {}
        self._load_lock = asyncio.Lock()
        self._requests = 0
        # HBM admission bookkeeping: estimated per-device bytes committed by
        # each loaded engine, and last-use times for idle-eviction order.
        # evict_grace_s: a recently-targeted engine is never evicted (see
        # _pick_idle_victim)
        self._hbm_committed: dict[str, int] = {}
        self._last_used: dict[str, float] = {}
        # slice of each engine's committed bytes that is its prefix cache's
        # budget — reclaimable under pressure WITHOUT unloading the engine
        # (_shrink_prefix_caches), unlike the weights/serving cache
        self._prefix_bytes: dict[str, int] = {}
        self.evict_grace_s = 1.0
        # engine supervision (serve/worker.py watchdog → restart_engine):
        # capped exponential restart backoff; > max_restarts crashes inside
        # restart_window_s marks the engine POISONED — further get_engine
        # calls are refused (retryable) until an operator delete/pull resets
        # it, reusing the refuse-until-reset shape of the failed-load path
        # in get_engine
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self._crash_times: dict[str, list[float]] = {}
        self._poisoned: dict[str, str] = {}  # model_id -> reason
        self.engine_restarts_total = 0
        # harvested from crashed batchers' stats at restart/teardown so the
        # Prometheus total survives the batcher object being dropped
        self.inflight_failed_retryable = 0
        self.restart_latency_ms = LogHistogram()
        # flight recorder (obs/recorder.py): per-engine frame rings sampled
        # by each batcher's owner loop; None ctor args read OBS_RECORDER /
        # OBS_RECORDER_INTERVAL_MS / OBS_DUMP_DIR from the env
        self.obs_recorder = (
            obs_recorder
            if obs_recorder is not None
            else os.environ.get("OBS_RECORDER", "1").strip().lower()
            not in ("0", "false", "off")
        )
        self.obs_recorder_interval_ms = (
            obs_recorder_interval_ms
            if obs_recorder_interval_ms is not None
            else float(os.environ.get("OBS_RECORDER_INTERVAL_MS", "").strip() or "250")
        )
        self.obs_dump_dir = (
            obs_dump_dir
            if obs_dump_dir is not None
            else os.environ.get("OBS_DUMP_DIR", "").strip()
        )
        # process-level counters merged into every recorder frame so
        # restart/reconnect counts sit on the same timeline as queue depth;
        # the worker registers its transport's reconnect counter here
        # cluster identity (serve/router.py): stamped on recorder frames and
        # anomaly dumps so N workers sharing one dump dir stay attributable
        self.worker_id = worker_id
        self.recorder_counters: dict[str, Any] = {
            "engine_restarts": lambda: self.engine_restarts_total,
        }
        # HBM ledger (obs/roofline.py): reconcile the admission accounting
        # against the allocator's bytes_in_use on every recorder tick — the
        # committed estimate already folds block pool + prefix budget, so
        # components split it for the breakdown rather than re-pricing.
        # HBM_WORKSPACE_SLACK_BYTES prices XLA scratch/workspace the
        # admission model deliberately ignores; the ledger's baseline
        # absorbs whatever constant slack remains unpriced.
        try:
            _slack = int(os.environ.get("HBM_WORKSPACE_SLACK_BYTES", "0") or 0)
        except ValueError:
            _slack = 0
        self.hbm_ledger = HbmLedger(
            {
                "engines": lambda: (
                    sum(self._hbm_committed.values())
                    - sum(self._prefix_bytes.values())
                ),
                "prefix_cache": lambda: sum(self._prefix_bytes.values()),
                "workspace_slack": lambda: _slack,
            },
            emit_fn=obs_emit,
        )
        # ticking inside the counter fn puts each reconciliation sample on
        # the recorder frame timeline for free (and into anomaly dumps).
        # EFFICIENCY=0 kills the whole plane: no ticks, no hbm_drift
        # events, and (per the worker's gates) no exposition families
        if efficiency_enabled():
            self.recorder_counters["hbm_drift_bytes"] = self.hbm_ledger.tick
        # pull-time precompile (ISSUE 15): at pull_model, compile the full
        # jit grid into the persistent compile cache so a replacement
        # worker's first request replays warm compiles. Only active when a
        # compile cache dir is configured — warming a process-local cache
        # would just tax the pull. None = read PULL_PRECOMPILE (default on).
        self.pull_precompile = (
            pull_precompile
            if pull_precompile is not None
            else _pull_precompile_env()
        )
        # elastic-drain flag (serve/worker.py begin_drain → set_draining):
        # while set, restart_engine refuses to relaunch engines — a worker
        # being scaled down must never be resurrected mid-teardown, even by
        # a supervisor restart already sleeping out its backoff
        self.draining = False

    # -- Registry ------------------------------------------------------------

    async def list_models(self) -> dict:
        entries = []
        for cm in self.store.cached():
            eng = self._engines.get(cm.model_id)
            if eng is not None:
                entries.append(eng.info())
            else:
                entries.append(
                    {
                        "id": cm.model_id,
                        "object": "model",
                        "type": "llm",
                        "publisher": cm.publisher,
                        "state": "not-loaded",
                        "size_bytes": cm.size,
                    }
                )
        return {"object": "list", "data": entries}

    async def pull(self, identifier: str) -> str:
        try:
            path, transcript = await self.store.pull(identifier)
        except StoreError as e:
            raise EngineError(str(e)) from None
        # a fresh pull is the other operator reset path for a poisoned model
        self._poisoned.pop(identifier, None)
        self._crash_times.pop(identifier, None)
        # mesh gate at pull time: a model whose head layout this worker's
        # mesh cannot shard is reported unservable NOW, in a retryable
        # cause-tagged envelope, instead of crashing the first chat_model.
        # The file stays cached — a mesh reconfig makes it servable later.
        reason = await asyncio.to_thread(self._mesh_unservable, str(path))
        if reason is not None:
            raise EngineError(
                f"pulled {identifier}, but it is {reason} — retry on "
                f"another worker"
            )
        if self.pull_precompile and _compile_cache_dir_configured():
            # reported via the pull_precompile event and the log, NOT the
            # transcript: the reply text is wire contract ("pulled")
            await self._precompile(identifier)
        return transcript

    async def _precompile(self, model_id: str) -> int:
        """Best-effort jit-grid warm at pull time: load the engine and
        compile every chunk/full-prefill program, populating the persistent
        compile cache a seconds-cold replacement worker will replay
        (PR 6/7's lmstudio_compile_cache_* counters measure the replay).
        Never fails the pull — the model IS pulled; precompile is a
        cold-start optimization. The engine load serves only the compile:
        when the model was not already resident it is unloaded again on the
        way out, so pull leaves it cached-not-loaded (the programs persist
        on disk either way)."""
        was_loaded = model_id in self._engines
        try:
            eng = await self.get_engine(model_id)
        except (EngineError, ModelNotFound) as e:
            log.warning("pull precompile skipped for %s: %s", model_id, e)
            return 0
        n = 0
        try:
            warm = getattr(
                getattr(eng, "batcher", None), "warm_chunk_programs", None
            )
            if warm is None:
                return 0
            t0 = time.perf_counter()
            try:
                n = await asyncio.to_thread(warm)
            except Exception as e:  # noqa: BLE001 — precompile is best-effort
                log.warning("pull precompile failed for %s: %s", model_id, e)
                return 0
            obs_emit("pull_precompile", model=model_id, programs=n,
                     seconds=round(time.perf_counter() - t0, 2))
            log.info("pull precompile: %d programs for %s in %.2fs",
                     n, model_id, time.perf_counter() - t0)
            return n
        finally:
            if not was_loaded and self._engines.get(model_id) is eng:
                self._engines.pop(model_id, None)
                self._hbm_committed.pop(model_id, None)
                self._prefix_bytes.pop(model_id, None)
                self._last_used.pop(model_id, None)
                await eng.unload()
                obs_emit("engine_unload", model=model_id,
                         reason="pull_precompile")

    async def delete(self, model_id: str) -> str:
        eng = self._engines.pop(model_id, None)
        self._hbm_committed.pop(model_id, None)
        self._prefix_bytes.pop(model_id, None)
        self._last_used.pop(model_id, None)
        # operator reset path for a poisoned engine
        self._poisoned.pop(model_id, None)
        self._crash_times.pop(model_id, None)
        if eng is not None:
            await eng.unload()
            obs_emit("engine_unload", model=model_id, reason="delete")
        try:
            return self.store.delete_local(model_id)
        except StoreError as e:
            err = EngineError(str(e))
            err.dir = e.dir  # surfaced in the error envelope (go :304-313)
            raise err from None

    async def sync_from_bucket(self, name: str, model_id: str | None = None) -> str:
        try:
            path, _ = await self.store.pull(name, model_id=model_id)
        except StoreError as e:
            raise EngineError(str(e)) from None
        return str(path)

    async def get_engine(self, model_id: str) -> ChatEngine:
        self._requests += 1
        poisoned = self._poisoned.get(model_id)
        if poisoned is not None:
            # refuse-until-reset: delete or pull the model to clear. The
            # message carries the retryable marker so a queue-group peer
            # (whose copy may be healthy) gets the retry.
            raise EngineError(
                f"model {model_id} is poisoned ({poisoned}); delete or pull "
                f"it to reset — retry on another worker"
            )
        eng = self._engines.get(model_id)
        if eng is not None:
            self._last_used[model_id] = time.monotonic()
            return eng
        async with self._load_lock:
            eng = self._engines.get(model_id)
            if eng is not None:
                self._last_used[model_id] = time.monotonic()
                return eng
            cm = self.store.lookup(model_id)
            if cm is None:
                raise ModelNotFound(model_id)
            paths = [str(f) for f in cm.files]
            await self._admit_hbm(cm.model_id, paths)
            try:
                eng = await asyncio.to_thread(self._load, cm.model_id, paths)
            except BaseException:
                # release the reservation: a failed load (corrupt file,
                # device OOM) must not leave phantom committed bytes that
                # refuse every future load until restart
                self._hbm_committed.pop(cm.model_id, None)
                self._prefix_bytes.pop(cm.model_id, None)
                raise
            self._engines[cm.model_id] = eng
            self._last_used[cm.model_id] = time.monotonic()
            return eng

    # -- HBM admission (VERDICT r4 missing #3) -------------------------------

    async def _admit_hbm(self, model_id: str, paths: list[str]) -> None:
        """Refuse (or free room for) a load that would blow the per-device
        HBM budget — BEFORE touching the device, so a second model cannot
        OOM mid-serving and take the first engine's dispatches with it. The
        reference delegates this to LM Studio's loader
        (/root/reference/nats_llm_studio.go:46-59 shells out); in-process
        it is ours. Estimates come from parallel.memory.estimate_device_bytes
        (the same math the 70B budget test pins); idle engines are evicted
        LRU-first to make room; an engine actively serving is never evicted."""
        budget = _hbm_budget_bytes()
        if budget is None:
            return
        evictable = True
        try:
            need = await asyncio.to_thread(self._estimate_load_bytes, paths)
        except Exception:  # noqa: BLE001 — keep admitting with a floor, not blind
            # an unexpected estimator failure must not silently disable
            # admission (the engine would serve with ZERO committed bytes
            # and the next load could OOM live serving). Fall back to the
            # file sizes — a floor on the real footprint — and log loudly.
            # Such a load may well fail outright in _load, so it is never
            # allowed to EVICT a healthy engine to make its room.
            need = sum(os.path.getsize(p) for p in paths if os.path.exists(p))
            evictable = False
            log.warning(
                "HBM estimate failed for %s; admitting with file-size floor "
                "%d MiB (no eviction)", model_id, need >> 20, exc_info=True,
            )
        pbytes = 0
        # paged mode: the prefix cache holds POOL block ids — its HBM is the
        # pool's, already inside _estimate_load_bytes; pricing it separately
        # would double-count (and _shrink_prefix_caches would then credit
        # bytes the pool never gives back to the OS)
        if self.prefix_cache_blocks > 0 and not self.kv_paged:
            try:
                pbytes = await asyncio.to_thread(self._estimate_prefix_bytes, paths)
            except Exception:  # noqa: BLE001 — cache stays block-bounded anyway
                log.warning(
                    "prefix-cache estimate failed for %s; admitting its cache "
                    "unpriced", model_id, exc_info=True,
                )
        need += pbytes
        self._hbm_committed.pop(model_id, None)  # reloading: don't double count
        self._prefix_bytes.pop(model_id, None)
        while sum(self._hbm_committed.values()) + need > budget:
            # cheapest eviction tier first: dropping another engine's prefix
            # cache frees its whole block budget without unloading anything
            if evictable and self._shrink_prefix_caches(exclude=model_id):
                continue
            victim = self._pick_idle_victim() if evictable else None
            if victim is None and evictable:
                # an idle engine inside the eviction grace may become
                # evictable within a second — wait a short remainder out
                # rather than bounce the load with a hard error
                wait = self._grace_remaining_s()
                if wait is not None and wait <= 1.5:
                    await asyncio.sleep(wait + 0.05)
                    victim = self._pick_idle_victim()
            if victim is None:
                committed = sum(self._hbm_committed.values())
                raise EngineError(
                    f"insufficient device memory to load {model_id}: needs "
                    f"~{need >> 20} MiB, {committed >> 20} MiB committed to "
                    f"{sorted(self._hbm_committed)} of {budget >> 20} MiB "
                    f"budget, and no loaded engine is idle to evict"
                )
            log.info("evicting idle engine %s to fit %s", victim, model_id)
            freed = self._hbm_committed.pop(victim, 0)
            self._prefix_bytes.pop(victim, None)
            eng = self._engines.pop(victim)
            self._last_used.pop(victim, None)
            await eng.unload()
            obs_emit("engine_evict", model=victim, for_model=model_id,
                     freed_bytes=freed)
        self._hbm_committed[model_id] = need
        if pbytes:
            self._prefix_bytes[model_id] = pbytes

    def _estimate_load_bytes(self, paths: list[str]) -> int:
        """Per-device estimate for serving this file with the registry's
        settings (mesh sharding, weight/KV quant, slot count, seq len).
        Paged KV replaces the per-slot worst-case cache term with the ONE
        pool's footprint (blocks x kv_pool_block_bytes) — the prefix cache
        lives inside the same pool and is not priced separately."""
        from ..gguf.reader import is_split_shard
        from ..parallel.memory import estimate_device_bytes

        split = sorted(p for p in paths if is_split_shard(p))
        with open_gguf(split[0] if split else paths[0]) as reader:
            cfg = ModelConfig.from_gguf_metadata(reader.metadata).with_(dtype=self.dtype)
        mesh_shape = dict(self.mesh.shape) if self.mesh is not None else {}
        seq = min(self.max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
        est = estimate_device_bytes(
            cfg, mesh_shape, quant=self.quant, batch=self.max_batch_slots,
            seq_len=seq, cache_dtype_bytes=1 if self.kv_quant == "int8" else None,
            group=self.wquant_group,
        )
        if not self.kv_paged:
            return est["total"]
        from ..parallel.memory import kv_pool_block_bytes
        from .prefix_cache import serving_chunk

        # mirror the batcher's block-size snap (T | serving chunk) and its
        # auto pool population, +1 for the permanent null block
        chunk = serving_chunk(seq)
        T = max(1, self.kv_block_tokens)
        while T > 1 and chunk % T:
            T //= 2
        nb = 1 + (
            self.kv_pool_blocks
            if self.kv_pool_blocks > 0
            else self.max_batch_slots * max(1, seq // T)
            + max(0, self.prefix_cache_blocks)
        )
        pool = nb * kv_pool_block_bytes(
            cfg, T, kv_quant=self.kv_quant, tp=self._kv_tp(cfg)
        )
        return est["total"] - est["kv_cache"] + pool

    def _mesh_unservable(self, path: str) -> str | None:
        """Reason this worker's mesh cannot serve the GGUF at ``path``
        (the validate_mesh_for_config message), or None when servable or
        the check cannot run. Best-effort: a failure to *check* is not a
        failure to *serve* — _load retells any real problem."""
        if self.mesh is None:
            return None
        from pathlib import Path

        from ..gguf.reader import is_split_shard

        p = Path(path)
        paths = sorted(str(f) for f in p.glob("*.gguf")) if p.is_dir() else [str(p)]
        if not paths:
            return None
        split = sorted(q for q in paths if is_split_shard(q))
        try:
            with open_gguf(split[0] if split else paths[0]) as reader:
                cfg = ModelConfig.from_gguf_metadata(reader.metadata)
            validate_mesh_for_config(self.mesh, cfg)
        except ValueError as e:
            return str(e)
        except Exception:  # noqa: BLE001 — gate is best-effort
            return None
        return None

    def _kv_tp(self, cfg: ModelConfig) -> int:
        """The tp factor actually applied to KV rings and prefix blocks:
        the mesh's tp when it divides the KV heads, else 1 (the
        replicated-KV GQA fallback keeps whole KV per chip)."""
        if self.mesh is None:
            return 1
        tp = dict(self.mesh.shape).get("tp", 1)
        return tp if tp > 1 and cfg.n_kv_heads % tp == 0 else 1

    def _shrink_prefix_caches(self, exclude: str | None = None) -> bool:
        """Reclaim HBM by dropping the least-recently-used engine's prefix
        cache — no unload, serving state untouched; blocks pinned by an
        in-flight admit are freed when that admit releases them (the
        refcount contract in serve/prefix_cache.py). Returns True when
        committed bytes decreased, so the admit loop retries the budget
        check before escalating to whole-engine eviction."""
        cands = [
            mid for mid in self._engines
            if mid != exclude and self._prefix_bytes.get(mid, 0) > 0
        ]
        if not cands:
            return False
        mid = min(cands, key=lambda m: self._last_used.get(m, 0.0))
        eng = self._engines[mid]
        freed = self._prefix_bytes.pop(mid, 0)
        self._hbm_committed[mid] = max(0, self._hbm_committed.get(mid, 0) - freed)
        dropped = eng.batcher.drop_prefix_cache() if eng.batcher is not None else 0
        log.info(
            "dropped %s prefix cache under HBM pressure (%d blocks, ~%d MiB)",
            mid, dropped, freed >> 20,
        )
        obs_emit("prefix_cache_drop", model=mid, freed_bytes=freed, blocks=dropped)
        return True

    def _estimate_prefix_bytes(self, paths: list[str]) -> int:
        """Worst-case device bytes of this engine's prefix-cache budget:
        blocks x the block footprint at the chunk size the batcher will
        actually serve with (serve/prefix_cache.serving_chunk mirrors the
        batcher's chunk halving)."""
        from .prefix_cache import prefix_block_bytes, serving_chunk

        from ..gguf.reader import is_split_shard

        split = sorted(p for p in paths if is_split_shard(p))
        with open_gguf(split[0] if split else paths[0]) as reader:
            cfg = ModelConfig.from_gguf_metadata(reader.metadata).with_(dtype=self.dtype)
        seq = min(self.max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
        chunk = serving_chunk(seq)
        return self.prefix_cache_blocks * prefix_block_bytes(
            cfg, chunk, kv_quant=self.kv_quant, tp=self._kv_tp(cfg)
        )

    def _pick_idle_victim(self) -> str | None:
        # grace window: an engine targeted within the last second is never
        # evicted even if its batcher looks idle — get_engine bumps
        # _last_used BEFORE the caller submits, so this closes the
        # check-then-act gap where a request is in flight toward a
        # momentarily-idle batcher (and damps mutual-eviction loops when
        # two models alternate under a one-model budget)
        now = time.monotonic()
        idle = [
            mid for mid, eng in self._engines.items()
            if eng.batcher is not None and eng.batcher.idle
            and now - self._last_used.get(mid, 0.0) > self.evict_grace_s
        ]
        if not idle:
            return None
        return min(idle, key=lambda mid: self._last_used.get(mid, 0.0))

    def _grace_remaining_s(self) -> float | None:
        """Shortest time until some currently-idle engine exits the
        eviction grace (None when no idle engine is inside it)."""
        now = time.monotonic()
        waits = [
            self.evict_grace_s - (now - self._last_used.get(mid, 0.0))
            for mid, eng in self._engines.items()
            if eng.batcher is not None and eng.batcher.idle
        ]
        waits = [w for w in waits if w > 0]
        return min(waits) if waits else None

    def _hbm_headroom_frac(self) -> float | None:
        """Free fraction of the HBM admission budget (brownout signal),
        or None when no budget is known. Called from batcher owner threads:
        one dict sum under the GIL, no lock needed for a pressure signal."""
        budget = _hbm_budget_bytes()
        if not budget:
            return None
        committed = sum(self._hbm_committed.values())
        return max(0.0, (budget - committed) / budget)

    def _load(self, model_id: str, paths: list[str]) -> JaxChatEngine:
        t0 = time.perf_counter()
        from ..gguf.reader import is_split_shard

        split = sorted(p for p in paths if is_split_shard(p))
        # a -NNNNN-of-MMMMM split set loads as one model (open_gguf verifies
        # every sibling exists, so a partial download fails loudly instead of
        # serving a third of the weights); otherwise keep the long-standing
        # behavior of serving the first .gguf in the dir
        reader = open_gguf(split[0] if split else paths[0])
        cfg = ModelConfig.from_gguf_metadata(reader.metadata).with_(
            dtype=self.dtype,
            use_flash_attention=jax.default_backend() == "tpu",  # prefill TTFT
            use_routed_moe=True,  # sparse dispatch (parallel/moe.py)
            kv_quant=self.kv_quant,
        )
        tokenizer = GGUFTokenizer.from_metadata(reader.metadata)
        quant = {t.ggml_type.name for t in reader.tensors.values()}
        submeshes: list[Any] = [self.mesh]
        if self.mesh is not None:
            # stream tensors straight onto the mesh: peak host memory is one
            # tensor, so 70B-class files load on small-RAM workers
            from ..parallel.loader import load_params_sharded
            from ..parallel.mesh import dp_submeshes

            validate_mesh_for_config(self.mesh, cfg)
            # a dp axis means batcher REPLICAS: one submesh per dp slice
            # (disjoint devices, ep/sp/tp intact). The GGUF streams onto
            # slice 0; the other slices get device-to-device re-placements
            # of the same tree below — weights replicated ALONG dp, sharded
            # WITHIN each slice, one host read total
            submeshes = dp_submeshes(self.mesh)
            params = load_params_sharded(
                reader, cfg, submeshes[0], quant=self.quant, group=self.wquant_group
            )
        elif self.quant in ("int8", "int4"):
            from ..models.llama import ensure_lm_head
            from ..ops.wquant import quantize_params

            params = quantize_params(
                ensure_lm_head(load_params_from_gguf(reader, cfg)),
                mode=self.quant, group=self.wquant_group,
            )
        else:
            from ..models.llama import ensure_lm_head

            params = ensure_lm_head(load_params_from_gguf(reader, cfg))
        meta = dict(reader.metadata)
        reader.close()
        n_dp = len(submeshes)
        replicas = []
        for i, sub in enumerate(submeshes):
            counters = dict(self.recorder_counters)
            if n_dp > 1:
                # every recorder frame of this replica carries its dp index
                # (frames already carry the replica-local queue_depth), so
                # a merged dump timeline stays attributable per slice
                counters["dp_replica"] = lambda _i=i: _i
            recorder = FlightRecorder(
                enabled=self.obs_recorder,
                interval_ms=self.obs_recorder_interval_ms,
                dump_dir=self.obs_dump_dir,
                engine=model_id if n_dp == 1 else f"{model_id}#dp{i}",
                worker_id=self.worker_id,
                counter_fns=counters,
            )
            if i == 0:
                rep_params = params
            else:
                from ..parallel.sharding import shard_params

                rep_params = shard_params(params, sub, cfg)
            b = ContinuousBatcher(
                rep_params, cfg, max_slots=self.max_batch_slots,
                max_seq_len=self.max_seq_len,
                mesh=sub, max_queue=self.admit_queue_limit,
                max_queue_age_ms=self.admit_max_age_ms,
                prefix_cache_blocks=self.prefix_cache_blocks,
                spec_decode_k=self.spec_decode_k,
                spec_max_active=self.spec_max_active,
                brownout=self.brownout_cfg,
                hbm_headroom_fn=self._hbm_headroom_frac,
                deadline_min_tokens=self.deadline_min_tokens,
                paged=self.kv_paged,
                kv_block_tokens=self.kv_block_tokens,
                kv_pool_blocks=self.kv_pool_blocks,
                recorder=recorder,
                qos_quantum_tokens=self.qos_quantum_tokens,
                qos_preempt=self.qos_preempt,
                **({"prefill_chunk": self.prefill_chunk}
                   if self.prefill_chunk else {}),
            )
            # hierarchical KV tier manager, attached AFTER construction so
            # chunk_tokens matches the batcher's (possibly halved) prefill
            # chunk exactly — the tier is keyed by whole prefix-cache
            # chunks, and a mismatch would poison every demote/promote.
            # Per-replica managers: demote/promote stay owner-thread-local,
            # and per-replica spill namespaces keep the Object Store index
            # single-writer.
            if (
                self.kv_host_pool_bytes > 0
                and b.paged
                and b.prefix_cache is not None
            ):
                from .kv_tiers import KVTierManager

                spill = None
                if self.kv_spill_factory is not None:
                    try:
                        spill = self.kv_spill_factory()
                    except Exception:  # noqa: BLE001
                        log.warning(
                            "kv spill store unavailable for %s; host tier "
                            "only", model_id, exc_info=True,
                        )
                ns = f"kv/{model_id}" if n_dp == 1 else f"kv/{model_id}/dp{i}"
                b.kv_tiers = KVTierManager(
                    self.kv_host_pool_bytes,
                    chunk_tokens=b.prefill_chunk,
                    spill=spill,
                    namespace=ns,
                    max_spill_objects=self.kv_spill_max_objects,
                    promote_chunks=self.kv_promote_chunks,
                    demote_free_frac=self.kv_demote_free_frac,
                )
            replicas.append(b)
        if n_dp > 1:
            from .dp import DataParallelBatcher

            batcher = DataParallelBatcher(replicas)
        else:
            batcher = replicas[0]
        if os.environ.get("TPU_WARM_ON_LOAD", "").strip() in ("1", "true"):
            # opt-in: compile every chunk/full-prefill program at load time
            # instead of pairing multi-second XLA compiles with the first
            # unlucky long requests (adds ~minutes to an 8B load on TPU,
            # which is why it is not the default)
            n_warm = batcher.warm_chunk_programs()
            log.info("warmed %d prefill programs for %s", n_warm, model_id)
        batcher.start()
        # restart-with-warm-cache: the Object Store tier survived the old
        # process, so re-import the deepest spilled chains without a live
        # donor. Best-effort — a full pool or a torn blob just means this
        # engine starts cold, exactly like before tiering existed.
        for r in replicas:
            tier = getattr(r, "kv_tiers", None)
            if tier is None:
                continue
            warmed = 0
            for export in tier.warm_exports(limit=4):
                try:
                    warmed += int(r.import_prefix_blocks(export).get("tokens", 0))
                except Exception:  # noqa: BLE001
                    break
            if warmed:
                log.info("warm-imported %d cached prefix tokens for %s",
                         warmed, model_id)
                obs_emit("kv_warm_import", model=model_id, tokens=warmed)
        load_s = time.perf_counter() - t0
        log.info("loaded %s in %.1fs (%s, %s)", model_id, load_s, cfg.arch, self.dtype)
        obs_emit("engine_load", model=model_id, seconds=round(load_s, 2),
                 arch=cfg.arch, dtype=self.dtype)
        return JaxChatEngine(
            model_id, batcher, tokenizer, cfg, meta, quantization="/".join(sorted(quant))
        )

    # -- engine supervision ---------------------------------------------------

    async def restart_engine(self, model_id: str, reason: str = "crash") -> str:
        """Tear down and relaunch one engine (the worker supervisor's action
        on a crashed or hung batcher). Returns "restarted", "poisoned" (too
        many crashes inside the window — refuse-until-reset), or "gone" (the
        engine was already unloaded by a concurrent delete/evict). A reload
        failure propagates as EngineError after the teardown."""
        if self.draining:
            return "draining"
        t0 = time.monotonic()
        async with self._load_lock:
            eng = self._engines.pop(model_id, None)
            if eng is None:
                return "gone"
            self._hbm_committed.pop(model_id, None)
            self._prefix_bytes.pop(model_id, None)
            self._last_used.pop(model_id, None)
            b = eng.batcher
            recorder = None
            if b is not None:
                from .dp import batcher_replicas

                # keep the Prometheus total alive past this batcher object
                # (summed over dp replicas — each keeps its own stats)
                self.inflight_failed_retryable += sum(
                    getattr(r.stats, "inflight_failed_retryable", 0)
                    for r in batcher_replicas(b)
                )
                # the dying batcher's flight recorder holds the pre-crash
                # timeline; keep it past unload so the restart dump below
                # can write it out
                recorder = getattr(b, "recorder", None)
            await eng.unload()
            obs_emit("engine_unload", model=model_id, reason=reason)
            now = time.monotonic()
            times = [
                t for t in self._crash_times.get(model_id, [])
                if now - t <= self.restart_window_s
            ]
            times.append(now)
            self._crash_times[model_id] = times
            if len(times) > self.max_restarts:
                why = (
                    f"{len(times)} crashes in {self.restart_window_s:.0f}s "
                    f"(last: {reason})"
                )
                self._poisoned[model_id] = why
                log.error("engine %s poisoned: %s", model_id, why)
                obs_emit("engine_poisoned", model=model_id, reason=why)
                return "poisoned"
            backoff = min(
                self.restart_backoff_s * (2 ** (len(times) - 1)),
                self.restart_backoff_max_s,
            )
        # backoff + reload OUTSIDE the load lock: a long XLA reload must not
        # block unrelated loads, and get_engine takes the lock itself
        await asyncio.sleep(backoff)
        if self.draining:
            # the drain began while we slept out the backoff — a worker
            # being scaled down must not resurrect its engine mid-teardown
            return "draining"
        await self.get_engine(model_id)
        self.engine_restarts_total += 1
        latency_ms = (time.monotonic() - t0) * 1e3
        self.restart_latency_ms.record(latency_ms)
        log.info("engine %s restarted in %.0f ms (reason: %s)",
                 model_id, latency_ms, reason)
        obs_emit("engine_restart", model=model_id, reason=reason,
                 ms=round(latency_ms, 1))
        if recorder is not None:
            # after the engine_restart emit, so the dump's event tail
            # contains the restart itself; force past the rate limiter —
            # the crash dump seconds earlier must not suppress this one
            recorder.dump(
                "engine_restart",
                force=True,
                extra={"model": model_id, "restart_reason": reason,
                       "restart_ms": round(latency_ms, 1)},
            )
        return "restarted"

    def engine_health(self) -> dict[str, dict[str, Any]]:
        """Per-engine liveness/readiness for the health subject: ``alive``
        (owner thread running, no crash), ``ready`` (alive and accepting
        submits), ``heartbeat_age_s`` (staleness; only meaningful when the
        batcher is not idle — an idle owner blocks on its inbox)."""
        mesh_shape = dict(self.mesh.shape) if self.mesh is not None else {}
        out: dict[str, dict[str, Any]] = {}
        for mid, eng in self._engines.items():
            b = eng.batcher
            if b is None or not hasattr(b, "alive"):
                continue
            out[mid] = {
                "alive": bool(b.alive),
                "ready": bool(b.alive and not b._stopping),
                "idle": bool(b.idle),
                "heartbeat_age_s": round(b.heartbeat_age_s(), 3),
                "brownout_level": int(getattr(b, "brownout_level", 0)),
            }
            reps = getattr(b, "replicas", None)
            if reps:
                # dp facade: aggregates above (alive=all, brownout=max,
                # heartbeat=min) plus per-replica routed load for the
                # health subject's drill-down
                out[mid]["dp"] = len(reps)
                out[mid]["replica_loads"] = b.replica_loads()
            if mesh_shape:
                out[mid]["mesh"] = mesh_shape
        return out

    def set_draining(self, flag: bool = True) -> None:
        """Raise (or clear) the elastic-drain flag: while set,
        ``restart_engine`` refuses to relaunch engines, so a supervisor
        restart racing a scale-down drain cannot resurrect the worker."""
        self.draining = bool(flag)

    def poisoned_models(self) -> dict[str, str]:
        return dict(self._poisoned)

    def loaded_engines(self) -> dict[str, Any]:
        return dict(self._engines)

    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "models_cached": len(self.store.cached()),
            "models_loaded": len(self._engines),
            "engine_requests": self._requests,
            "backend": jax.default_backend(),
            "hbm_committed_bytes": sum(self._hbm_committed.values()),
            "hbm_ledger": self.hbm_ledger.last_sample(),
        }
        if self.mesh is not None:
            out["mesh"] = dict(self.mesh.shape)
        if self.engine_restarts_total:
            out["engine_restarts"] = self.engine_restarts_total
        if self._poisoned:
            out["poisoned"] = dict(self._poisoned)
        from .dp import batcher_replicas

        batchers: dict[str, Any] = {}
        prefix: dict[str, Any] = {}
        for mid, eng in self._engines.items():
            if eng.batcher is None:
                continue
            reps = batcher_replicas(eng.batcher)
            for i, r in enumerate(reps):
                # dp>1 snapshots key per replica so per-slice load shows
                key = mid if len(reps) == 1 else f"{mid}#dp{i}"
                batchers[key] = r.stats.snapshot()
                if r.prefix_cache is not None:
                    prefix[key] = r.prefix_cache.stats()
        if batchers:
            out["batcher"] = batchers
        if prefix:
            out["prefix_cache"] = prefix
        return out

"""Automatic prefix KV cache: radix-tree prompt reuse across requests.

The `chat_model` contract renders every request through the GGUF chat
template, so real traffic shares long common prefixes — the system prompt
plus the resent conversation history is re-prefilled on every turn, and the
r5 bench put admit+prefill p95 in the seconds under load. SGLang's
RadixAttention and vLLM's PagedAttention showed block-granular KV reuse
across requests is the single largest serving win for templated chat
workloads; this module is that capability for the continuous batcher.

Design:

* A radix tree keyed on **token-id chunks** of exactly ``prefill_chunk``
  tokens — the chunk the batcher's chunked-prefill program already uses, so
  every cached block boundary is a boundary the prefill pipeline can resume
  from (``prefill1`` with ``uniform_start`` continues from any chunk edge).
  Fixed-size edges make the "radix tree" a trie over chunk tuples: one dict
  hop per chunk, no partial-edge splitting ever needed.
* Each node owns one **already-materialized KV block pair** — the
  ``[1, L, Hkv, C, D]`` slice of a prefilled transient row cache, bf16 array
  or ``ops.kvcache.KVQ`` pytree depending on ``ModelConfig.kv_quant``. A
  quantized serving cache stores quantized blocks: a hit re-inserts the
  exact codes+scales a full prefill would have written, so greedy outputs
  are bit-identical with the cache on or off.
* Nodes may also hold the **chunk-end logits row** (``[1, 1, vocab]``): a
  prompt whose every token is covered by cached chunks samples its first
  token straight from the stored logits and skips prefill entirely. Nodes
  harvested from the single-dispatch flash path lack intermediate logits;
  a full-length match against such a node degrades to a partial hit (the
  final chunk re-prefills) rather than guessing.
* **Refcounted eviction.** ``match`` pins every node on the returned hit;
  the batcher releases the pin after the copy dispatches are enqueued.
  Eviction (capacity pressure, ``resize``, the registry's HBM-pressure
  drop) detaches pinned nodes from the tree but must never free their
  arrays — a detached-while-pinned node is marked dead and freed at
  ``release`` time instead. LRU order is a monotonic use tick; only leaves
  are evictable, so an interior block shared by live descendants outlives
  them.

Thread-safety: the batcher owner thread does match/insert/release; the
registry's event loop may clear/resize under HBM pressure and metrics
handlers read the stats — everything mutating takes the one lock. Device
arrays themselves are immutable; the lock only guards the tree.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

from ..obs import LogHistogram
from ..obs import emit as obs_emit
from ..ops.kvcache import kv_nbytes


def serving_chunk(max_seq: int, prefill_chunk: int = 256) -> int:
    """The chunk size a batcher with these settings actually serves with
    (mirrors ``ContinuousBatcher.__init__``: halved until it divides the
    ring) — the registry's HBM estimate must price the same block shape
    the batcher will cache."""
    chunk = max(8, prefill_chunk)
    while max_seq % chunk and chunk > 8:
        chunk //= 2
    return chunk


def prefix_block_bytes(cfg, chunk: int, kv_quant: str | None = None,
                       tp: int = 1) -> int:
    """Worst-case PER-DEVICE bytes of ONE cached entry: the K+V block pair
    for ``chunk`` positions plus the optional chunk-end logits row. Used by
    the registry's HBM admission to commit the cache's budget up front.
    ``tp`` is the tensor-parallel factor actually sharding the block's head
    axis (1 under the replicated-KV GQA fallback) — blocks live split
    across the mesh, so each chip holds 1/tp of the KV bytes."""
    quant = (kv_quant if kv_quant is not None else cfg.kv_quant) == "int8"
    dtype_bytes = 4 if cfg.dtype == "float32" else 2
    per_pos = (
        cfg.head_dim * (1 if quant else dtype_bytes) + (4 if quant else 0)
    )
    kv = 2 * cfg.n_layers * cfg.n_kv_heads * chunk * per_pos // max(1, tp)
    return kv + 4 * cfg.vocab_size  # + [1, 1, vocab] f32 end-logits


class _Node:
    """One chunk edge: the KV block for tokens [depth*C, (depth+1)*C).

    In paged mode the node owns no arrays: ``payload`` is an opaque handle
    (the batcher passes ``(pool_epoch, [block ids])``), ``units`` is how
    many pool blocks it pins, and ``free_fn`` (the pool decref) runs when
    the node is truly freed — i.e. the same deferred point at which the
    legacy mode nulls its arrays, so eviction-under-pin stays safe."""

    __slots__ = ("key", "parent", "children", "kb", "vb", "logits", "refs",
                 "tick", "dead", "nbytes", "payload", "units", "free_fn")

    def __init__(self, key, parent, kb, vb, logits, payload=None,
                 units=1, nbytes=None, free_fn=None):
        self.key = key
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.kb = kb
        self.vb = vb
        self.logits = logits
        self.payload = payload
        self.units = units
        self.free_fn = free_fn
        self.refs = 0
        self.tick = 0
        self.dead = False
        self.nbytes = nbytes if nbytes is not None else kv_nbytes(kb) + kv_nbytes(vb)

    def free(self) -> None:
        if self.free_fn is not None and self.payload is not None:
            self.free_fn(self.payload)
        self.kb = self.vb = self.logits = self.payload = None


@dataclass
class PrefixHit:
    """A pinned longest-prefix match. ``blocks`` are alive until
    ``PrefixCache.release`` — even if eviction detaches the nodes first."""

    tokens: int  # chunk-aligned covered length, > 0
    nodes: list = field(default_factory=list)

    @property
    def blocks(self) -> list[tuple[Any, Any]]:
        return [(nd.kb, nd.vb) for nd in self.nodes]

    @property
    def payloads(self) -> list:
        """Per-node opaque payloads (paged mode: (epoch, block ids))."""
        return [nd.payload for nd in self.nodes]

    @property
    def end_logits(self):
        """Chunk-end logits of the deepest matched node (None unless the
        harvesting prefill computed them)."""
        return self.nodes[-1].logits if self.nodes else None


class PrefixCache:
    """Radix (chunk-trie) cache of prefilled KV blocks with LRU eviction.

    Two ownership modes share one tree:

    * legacy (default): each node owns a materialized ``[1, L, Hkv, C, D]``
      block pair; capacity counts nodes.
    * paged (``acquire_fn``/``free_fn`` given): nodes hold pool block-id
      payloads. ``acquire_fn(payload)`` runs when a node is created (the
      batcher increfs the pool) and ``free_fn(payload)`` when it is freed
      (decref), so harvest is a refcount bump and eviction a decref — no
      KV bytes move. Capacity, ``inserted_blocks`` and ``evicted_blocks``
      are denominated in POOL BLOCKS (``node_blocks`` per node).
    """

    def __init__(self, chunk: int, capacity_blocks: int, *,
                 node_blocks: int = 1, node_bytes: int | None = None,
                 acquire_fn=None, free_fn=None):
        if chunk <= 0:
            raise ValueError("chunk must be positive")
        self.chunk = chunk
        self.capacity = max(0, capacity_blocks)
        self.node_blocks = max(1, node_blocks)
        self.node_bytes = node_bytes
        self.acquire_fn = acquire_fn
        self.free_fn = free_fn
        self.paged = free_fn is not None
        # tiered-KV hook (serve/kv_tiers.py): when set by the batcher,
        # owner-thread eviction paths call ``demote_fn(token_ids, payload,
        # logits)`` BEFORE freeing a node, turning LRU eviction into
        # demotion to the host tier. Only owner-thread call sites pass
        # ``demote=True`` — the fn reads device pool blocks, which only the
        # owner thread may do; registry-side clear/resize never demote.
        self.demote_fn = None
        self._root: dict[tuple, _Node] = {}
        self._lock = threading.Lock()
        self._tick = 0
        self._blocks = 0
        self._bytes = 0
        # counters for Prometheus exposition (serve/worker.py) and the
        # bench's shared-prefix phase; hit_tokens is the acceptance metric
        self.hits = 0
        self.misses = 0
        self.full_hits = 0
        self.hit_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0
        self.demoted_blocks = 0
        self.demote_failures = 0
        self.hit_tokens_hist = LogHistogram(lo=1.0, hi=131072.0, growth=1.5)

    # -- lookup ---------------------------------------------------------------

    def _chunks(self, token_ids) -> list[tuple]:
        C = self.chunk
        return [
            tuple(token_ids[i : i + C])
            for i in range(0, len(token_ids) - C + 1, C)
        ]

    def peek(self, token_ids) -> int:
        """Matched-token count without pinning (group-admit routing: a
        request with a usable hit is admitted alone so the hit path runs)."""
        with self._lock:
            nodes = self._walk(token_ids)
            return len(nodes) * self.chunk

    def _walk(self, token_ids) -> list[_Node]:
        """Longest cached full-chunk prefix (lock held). A match covering
        the WHOLE prompt needs the last node's logits to produce the first
        token; without them the final chunk is dropped so the batcher
        re-prefills it (and backfills the logits on insert)."""
        nodes: list[_Node] = []
        level = self._root
        for key in self._chunks(token_ids):
            nd = level.get(key)
            if nd is None:
                break
            nodes.append(nd)
            level = nd.children
        if nodes and len(nodes) * self.chunk == len(token_ids) and nodes[-1].logits is None:
            nodes.pop()
        return nodes

    def match(self, token_ids) -> PrefixHit | None:
        """Longest cached prefix, PINNED. Caller must ``release`` the hit
        once the blocks' copy dispatches are enqueued (or on any failure)."""
        with self._lock:
            nodes = self._walk(token_ids)
            if not nodes:
                self.misses += 1
                return None
            self._tick += 1
            for nd in nodes:
                nd.refs += 1
                nd.tick = self._tick
            covered = len(nodes) * self.chunk
            self.hits += 1
            self.hit_tokens += covered
            if covered == len(token_ids):
                self.full_hits += 1
            self.hit_tokens_hist.record(float(covered))
            return PrefixHit(tokens=covered, nodes=nodes)

    def release(self, hit: PrefixHit) -> None:
        """Unpin a hit; frees blocks that were evicted while pinned."""
        with self._lock:
            for nd in hit.nodes:
                nd.refs -= 1
                if nd.dead and nd.refs <= 0:
                    nd.free()
        hit.nodes = []

    # -- insertion / eviction -------------------------------------------------

    def insert(self, token_ids, blocks, logits_list=None) -> int:
        """Insert the prompt's full-chunk blocks along one tree path.

        ``blocks[j]`` is the (k, v) block pair for chunk j — or, in paged
        mode, the opaque payload handed back to acquire_fn/free_fn — or None
        when the caller skipped materializing it (the chunk was just
        matched, so its node already exists). ``logits_list[j]`` is the
        chunk-end logits row or None; existing nodes missing logits are
        backfilled, which is how a flash-harvested path later earns
        full-hit capability. Returns the number of NEW nodes inserted."""
        if self.capacity <= 0:
            return 0
        chunks = self._chunks(token_ids)
        added = 0
        with self._lock:
            self._tick += 1
            level = self._root
            parent = None
            for j, key in enumerate(chunks):
                nd = level.get(key)
                if nd is None:
                    if j >= len(blocks) or blocks[j] is None:
                        break  # nothing to create this node from
                    lg = logits_list[j] if logits_list else None
                    if self.paged:
                        payload = blocks[j]
                        if self.acquire_fn is not None:
                            self.acquire_fn(payload)
                        nd = _Node(key, parent, None, None, lg,
                                   payload=payload, units=self.node_blocks,
                                   nbytes=self.node_bytes or 0,
                                   free_fn=self.free_fn)
                    else:
                        kb, vb = blocks[j]
                        nd = _Node(key, parent, kb, vb, lg)
                    level[key] = nd
                    self._blocks += nd.units
                    self._bytes += nd.nbytes
                    self.inserted_blocks += nd.units
                    added += 1
                elif nd.logits is None and logits_list and j < len(logits_list):
                    nd.logits = logits_list[j]
                nd.tick = self._tick
                parent = nd
                level = nd.children
            # insert runs on the owner thread, so capacity overflow demotes
            # (LRU → host tier) instead of dropping when the hook is wired
            evicted = self._evict_to_locked(self.capacity, demote=True)
        if evicted:
            obs_emit("prefix_evict", blocks=evicted, resident=self.blocks)
        return added

    def _evict_to_locked(self, capacity: int, demote: bool = False) -> int:
        """Detach LRU leaves until at most ``capacity`` blocks remain
        (lock held). A pinned leaf is detached but NOT freed — an admit in
        flight still reads its arrays; ``release`` frees it. Interior
        nodes become leaves as their children go, so repeated passes drain
        arbitrarily deep chains."""
        evicted = 0
        while self._blocks > capacity:
            leaf = self._lru_leaf_locked()
            if leaf is None:
                break
            evicted += self._detach_locked(leaf, demote=demote)
        return evicted

    def _lru_leaf_locked(self, unpinned_only: bool = False):
        leaf = None
        stack = list(self._root.values())
        while stack:
            nd = stack.pop()
            if nd.children:
                stack.extend(nd.children.values())
            elif unpinned_only and nd.refs > 0:
                continue
            elif leaf is None or nd.tick < leaf.tick:
                leaf = nd
        return leaf

    def _detach_locked(self, leaf, demote: bool = False) -> int:
        if demote and self.demote_fn is not None and leaf.payload is not None:
            # hand the node's KV to the lower tier BEFORE the refcount drop
            # below can recycle its pool blocks. Reconstructed path =
            # concatenated chunk keys root→leaf (the hot_prefixes shape).
            # Any failure falls back to plain eviction — the free below
            # still runs either way, so pool books stay exact.
            chain = []
            nd = leaf
            while nd is not None:
                chain.append(nd.key)
                nd = nd.parent
            tokens = [t for key in reversed(chain) for t in key]
            try:
                if self.demote_fn(tokens, leaf.payload, leaf.logits):
                    self.demoted_blocks += leaf.units
            except Exception:  # noqa: BLE001 — demotion is strictly best-effort
                self.demote_failures += 1
        owner = leaf.parent.children if leaf.parent is not None else self._root
        owner.pop(leaf.key, None)
        self._blocks -= leaf.units
        self._bytes -= leaf.nbytes
        self.evicted_blocks += leaf.units
        leaf.dead = True
        if leaf.refs <= 0:
            leaf.free()
        return leaf.units

    def reclaim(self, n_units: int, demote: bool = False) -> int:
        """Evict UNPINNED LRU leaves until ~``n_units`` capacity units have
        actually been freed (paged mode: pool blocks returned to the free
        list right now, not deferred behind a pin). The batcher calls this
        when the pool runs dry — cached prefixes are the reclaimable tier,
        live slots are not. With ``demote=True`` (owner thread only) each
        reclaimed node's KV is handed to the tier hook first, so pressure
        relief swaps instead of discarding. Returns units freed."""
        freed = 0
        with self._lock:
            while freed < n_units:
                leaf = self._lru_leaf_locked(unpinned_only=True)
                if leaf is None:
                    break
                freed += self._detach_locked(leaf, demote=demote)
        if freed:
            obs_emit("prefix_evict", blocks=freed, resident=self.blocks,
                     reclaim=True)
        return freed

    def resize(self, capacity_blocks: int) -> int:
        """Shrink (or grow) the block budget; evicts immediately. The
        registry's HBM-pressure hook calls ``resize(0)`` to drop the cache
        without touching blocks an in-flight admit has pinned."""
        with self._lock:
            self.capacity = max(0, capacity_blocks)
            evicted = self._evict_to_locked(self.capacity)
        if evicted:
            obs_emit("prefix_evict", blocks=evicted, resident=self.blocks,
                     resized_to=self.capacity)
        return evicted

    def clear(self) -> int:
        with self._lock:
            return self._evict_to_locked(0)

    def hot_prefixes(self, limit: int = 4) -> list[list[int]]:
        """The hottest cached prefix paths, most-recently-used first: each
        entry is the full token-id list root→leaf (concatenated chunk keys),
        exactly the shape ``export_prefix_blocks`` takes. A draining worker
        enumerates these to warm-hand its cache to a replacement (ISSUE 15);
        enumeration does not pin, touch ticks, or count as hits — handoff
        must not perturb the LRU it is reading."""
        if limit <= 0:
            return []
        with self._lock:
            leaves: list[_Node] = []
            stack = list(self._root.values())
            while stack:
                nd = stack.pop()
                if nd.children:
                    stack.extend(nd.children.values())
                else:
                    leaves.append(nd)
            leaves.sort(key=lambda nd: nd.tick, reverse=True)
            out: list[list[int]] = []
            for leaf in leaves[:limit]:
                chain = []
                nd = leaf
                while nd is not None:
                    chain.append(nd.key)
                    nd = nd.parent
                out.append([t for key in reversed(chain) for t in key])
            return out

    # -- introspection --------------------------------------------------------

    @property
    def blocks(self) -> int:
        return self._blocks

    @property
    def bytes(self) -> int:
        return self._bytes

    def counters(self) -> dict[str, int]:
        """Monotonic counters for Prometheus exposition
        (``lmstudio_prefix_cache_<name>_total``)."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "full_hits": self.full_hits,
            "hit_tokens": self.hit_tokens,
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
            "demoted_blocks": self.demoted_blocks,
            "demote_failures": self.demote_failures,
        }

    def stats(self) -> dict[str, Any]:
        snap = self.hit_tokens_hist.snapshot()
        return {
            **self.counters(),
            "blocks": self._blocks,
            "capacity_blocks": self.capacity,
            "bytes": self._bytes,
            "hit_tokens_p50": round(snap.percentile(0.5), 1),
        }

"""Data-parallel batcher replicas: one ``ContinuousBatcher`` per dp slice.

A serving mesh with a dp axis ("dp=2,tp=2") is NOT batch-sharding inside one
jit grid — it is N independent replicas, each owning a disjoint device slice
(``parallel.mesh.dp_submeshes``) with the remaining (ep, sp, tp) axes intact,
its own slot table, KV pool, prefix cache, and compiled program grid. Weights
are replicated along dp (placed once per slice), so the whole worker serves
dp x ``max_batch_slots`` concurrent streams at one replica's per-chip HBM
cost. The reference gets extra throughput only by adding whole worker
processes (SURVEY.md §3 queue groups); dp replicas get it inside one process
sharing one host checkpoint read and one NATS connection.

``DataParallelBatcher`` is the facade the registry/worker/engine layers see:
it quacks like a ``ContinuousBatcher`` (submit, stop, stats via replica
iteration, capacity as the SUM of replica slots) and routes each request to
the least-loaded replica at submit time. Cross-layer consumers that need
per-replica detail (Prometheus, flight recorder, stats snapshots) iterate
``batcher_replicas(b)`` instead of guessing the facade's internals.
"""

from __future__ import annotations

import threading
from typing import Any, AsyncIterator


def batcher_replicas(b: Any) -> list[Any]:
    """The underlying ``ContinuousBatcher`` list of any engine batcher:
    ``[b]`` for a plain single-mesh batcher, the replica list for a
    :class:`DataParallelBatcher`. Metrics/stats call sites iterate this so
    one code path covers dp=1 and dp>1."""
    reps = getattr(b, "replicas", None)
    return list(reps) if reps else [b]


class DataParallelBatcher:
    """Facade over dp batcher replicas with least-loaded submit routing.

    Load per replica = its admitted-but-unscheduled ``queue_depth`` plus
    this facade's own in-flight count (streams routed here that may not
    have reached the replica's inbox yet — the counter closes the window
    where a burst of concurrent submits would all see depth 0 and pile
    onto replica 0). Ties break round-robin so an idle worker still
    spreads warm-up load across every slice.
    """

    def __init__(self, replicas: list[Any]):
        if not replicas:
            raise ValueError("DataParallelBatcher needs at least one replica")
        self.replicas = list(replicas)
        self._inflight = [0] * len(self.replicas)
        self._rr = 0
        self._lock = threading.Lock()

    # -- replica selection ---------------------------------------------------

    def _pick(self) -> int:
        with self._lock:
            self._rr += 1
            best, best_key = 0, None
            for i, r in enumerate(self.replicas):
                depth = getattr(r, "queue_depth", 0) + self._inflight[i]
                key = (depth, (i - self._rr) % len(self.replicas))
                if best_key is None or key < best_key:
                    best, best_key = i, key
            self._inflight[best] += 1
            return best

    def _done(self, i: int) -> None:
        with self._lock:
            self._inflight[i] = max(0, self._inflight[i] - 1)

    def replica_loads(self) -> list[int]:
        """Per-replica queue depth + routed in-flight count (metrics)."""
        with self._lock:
            return [
                getattr(r, "queue_depth", 0) + self._inflight[i]
                for i, r in enumerate(self.replicas)
            ]

    # -- request path --------------------------------------------------------

    async def submit_batched(self, *args, **kwargs) -> AsyncIterator[list]:
        i = self._pick()
        try:
            async for batch in self.replicas[i].submit_batched(*args, **kwargs):
                yield batch
        finally:
            self._done(i)

    async def submit(self, *args, **kwargs) -> AsyncIterator[int]:
        async for batch in self.submit_batched(*args, **kwargs):
            for tok in batch:
                yield tok

    # -- prefix / KV transfer ------------------------------------------------

    def export_prefix_blocks(self, prompt_ids: list[int],
                             timeout: float = 30.0) -> dict | None:
        """First replica with cached blocks wins — the prefill that seeded
        the prefix may have run on any replica."""
        for r in self.replicas:
            out = r.export_prefix_blocks(prompt_ids, timeout=timeout)
            if out is not None:
                return out
        return None

    def import_prefix_blocks(self, export: dict, timeout: float = 30.0) -> dict:
        """Seed EVERY replica so the matching request hits regardless of
        which slice ``_pick`` routes it to. Per-replica pool exhaustion is
        tolerated as long as one import lands; only a total wipeout
        re-raises (the caller then falls back to local prefill)."""
        result: dict | None = None
        err: Exception | None = None
        for r in self.replicas:
            try:
                out = r.import_prefix_blocks(export, timeout=timeout)
            except Exception as e:  # noqa: BLE001 — per-replica best effort
                err = e
                continue
            if result is None:
                result = out
        if result is None:
            if err is not None:
                raise err
            return {"tokens": 0, "blocks": 0}
        return result

    def drop_prefix_cache(self) -> int:
        return sum(r.drop_prefix_cache() for r in self.replicas)

    def suspend_harvest_to_cache(self, timeout: float = 30.0) -> dict:
        """Every replica harvests its own slots (disjoint slot tables)."""
        out = {"slots": 0, "tokens": 0}
        for r in self.replicas:
            got = r.suspend_harvest_to_cache(timeout=timeout)
            out["slots"] += int(got.get("slots", 0))
            out["tokens"] += int(got.get("tokens", 0))
        return out

    def tier_stats(self) -> dict | None:
        """Numeric tier/suspend counters summed across replicas (None when
        no replica has tiering or suspend on) — advert + metrics surface."""
        merged: dict | None = None
        for r in self.replicas:
            s = r.tier_stats()
            if not s:
                continue
            if merged is None:
                merged = {}
            for k, v in s.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    merged[k] = merged.get(k, 0) + v
        return merged

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for r in self.replicas:
            r.start()

    def stop(self) -> None:
        for r in self.replicas:
            r.stop()

    def warm_chunk_programs(self, widths: tuple[int, ...] | None = None) -> int:
        return sum(r.warm_chunk_programs(widths) for r in self.replicas)

    # -- aggregate health/capacity (quacks like one batcher) -----------------

    @property
    def max_slots(self) -> int:
        """The advertised capacity: replicas hold disjoint slot tables, so
        the worker really serves the sum concurrently."""
        return sum(r.max_slots for r in self.replicas)

    @property
    def max_seq(self) -> int:
        return min(r.max_seq for r in self.replicas)

    @property
    def max_group_admit(self) -> int:
        """Per-replica group-admit width: a burst wider than one replica's
        group grid still lands as one group per replica."""
        return min(getattr(r, "max_group_admit", 1) for r in self.replicas)

    @property
    def prefill_chunk(self):
        return self.replicas[0].prefill_chunk

    @property
    def prefix_cache(self):
        return self.replicas[0].prefix_cache

    @property
    def stats(self):
        """Replica 0's stats — sites that need the full picture iterate
        :func:`batcher_replicas` (registry.stats, worker Prometheus)."""
        return self.replicas[0].stats

    @property
    def recorder(self):
        return self.replicas[0].recorder

    @property
    def decode_kernel(self) -> str:
        return getattr(self.replicas[0], "decode_kernel", "xla")

    @property
    def queue_depth(self) -> int:
        return sum(getattr(r, "queue_depth", 0) for r in self.replicas)

    @property
    def brownout_level(self) -> int:
        return max(r.brownout_level for r in self.replicas)

    @property
    def alive(self) -> bool:
        return all(r.alive for r in self.replicas)

    @property
    def idle(self) -> bool:
        return all(r.idle for r in self.replicas)

    @property
    def _stopping(self) -> bool:
        return any(r._stopping for r in self.replicas)

    def heartbeat_age_s(self) -> float:
        return min(r.heartbeat_age_s() for r in self.replicas)

    def pool_stats(self) -> dict | None:
        """Summed pool counters across replicas (each owns its own pool)."""
        per = [r.pool_stats() for r in self.replicas]
        per = [p for p in per if p]
        if not per:
            return None
        out: dict = {}
        for p in per:
            for k, v in p.items():
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
                else:
                    out.setdefault(k, v)
        return out

    def debug_snapshot(self) -> dict:
        return {
            "dp": len(self.replicas),
            "queue_depth": self.queue_depth,
            "replica_loads": self.replica_loads(),
            "replicas": {
                f"dp{i}": r.debug_snapshot()
                for i, r in enumerate(self.replicas)
            },
        }

"""Self-speculative decoding: prompt-lookup (n-gram) draft proposal.

Batched ring decode is memory-bound — every burst step reads the whole
int8 weight tree to emit ONE token per slot (serve/batcher.py header).
Speculative decoding (Leviathan et al.) converts that bandwidth into
several tokens per forward pass by guessing a short continuation and
verifying all of it in one width-``k+1`` dispatch. The draft source here
is *prompt lookup* (Saxena): chat traffic re-emits long spans of its own
prompt (code edits, summaries, quoted RAG passages), so the best zero-cost
draft model is the request's own token history — no extra HBM, no second
model, no draft forward.

This module is the host-side half: a per-slot incremental n-gram index
over prompt + generated tokens that proposes up to ``k`` draft tokens in
O(max_ngram) per call. The device-side half (the batched verify forward
and the acceptance rule) lives in serve/batcher.py and engine/sampling.py.

Why no KV rollback is needed on rejection: speculative serving runs the
cache in POSITIONAL layout (slot s of a row holds that row's token at
sequence position s — the ``ring_slot=None`` path of models.llama.forward).
A verify dispatch writes k+1 fresh KV entries at positions pos..pos+k; if
only ``a`` drafts are accepted, host ``pos`` simply resets to pos+a+1 and
the entries above it are dead weight: decode attention masks strictly by
position (``key_pos <= query position``), so they are never read, and the
row's NEXT write lands at pos+a+1 — exactly on top of the first stale
entry. Stale state is overwritten before it can ever become visible.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knobs (config.py env contract: SPEC_DECODE_*)."""

    k: int = 6  # max draft tokens per slot per verify (verify width = k+1)
    max_ngram: int = 3  # longest lookup key (matched first)
    min_ngram: int = 1  # shortest lookup key tried
    # verify dispatches stop above this many active slots: wide batches are
    # compute-bound (the weight read is already amortized over the batch),
    # so burning k× lm_head + attention FLOPs per slot on drafts stops
    # paying — decode falls back to plain bursts until occupancy drops
    max_active: int = 4


class NGramIndex:
    """Incremental n-gram → last-occurrence index over one slot's tokens.

    For each n in [min_ngram, max_ngram] the index maps every n-gram to its
    two most recent END positions. ``propose`` takes the current tail
    n-gram (which always has its latest occurrence at the tail itself) and
    drafts the tokens that followed its PREVIOUS occurrence — longest n
    first, so a 3-gram match beats a 1-gram match. Append is O(max_ngram);
    memory is O(len(history) * ngram orders), bounded by max_seq.
    """

    def __init__(
        self,
        token_ids: list[int],
        max_ngram: int = 3,
        min_ngram: int = 1,
    ):
        self.max_ngram = max(1, max_ngram)
        self.min_ngram = max(1, min(min_ngram, self.max_ngram))
        self.hist: list[int] = []
        # per order n: ngram tuple -> (latest end pos, previous end pos|None)
        self._maps: dict[int, dict[tuple, tuple[int, int | None]]] = {
            n: {} for n in range(self.min_ngram, self.max_ngram + 1)
        }
        for t in token_ids:
            self.append(t)

    def append(self, tok: int) -> None:
        """Register ``tok`` and every n-gram that now ends at it."""
        self.hist.append(tok)
        i = len(self.hist) - 1
        for n, m in self._maps.items():
            if i + 1 < n:
                continue
            g = tuple(self.hist[i - n + 1 : i + 1])
            old = m.get(g)
            m[g] = (i, old[0] if old is not None else None)

    def extend(self, toks) -> None:
        for t in toks:
            self.append(t)

    def propose(self, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the current tail, or []."""
        L = len(self.hist)
        if k <= 0 or L < self.min_ngram + 1:
            return []
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if L < n:
                continue
            g = tuple(self.hist[L - n :])
            ent = self._maps[n].get(g)
            if ent is None:
                continue
            last, prev = ent
            # the tail itself is always the latest occurrence; draft from
            # the one before it (an earlier span that continued past g)
            src = prev if last == L - 1 else last
            if src is None or src >= L - 1:
                continue
            return self.hist[src + 1 : src + 1 + k]
        return []


@dataclass
class SpecSlot:
    """Per-slot speculative state the batcher owner thread maintains:
    the n-gram index doubles as the slot's token history (prompt + every
    delivered token, INCLUDING the one still riding the device carry)."""

    index: NGramIndex
    drafted: int = 0
    accepted: int = 0


def make_slot(prompt_ids: list[int], first_token: int, cfg: SpecConfig) -> SpecSlot:
    """Slot state right after an admit: history = prompt + the admit's
    sampled first token (on device in ``tok_dev``, not yet written to KV —
    the same invariant the ring batcher keeps host-side)."""
    idx = NGramIndex(prompt_ids, cfg.max_ngram, cfg.min_ngram)
    idx.append(first_token)
    return SpecSlot(index=idx)

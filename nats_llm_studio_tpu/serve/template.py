"""Chat prompt construction from GGUF metadata.

The reference passes the OpenAI-style ``messages`` payload verbatim to LM
Studio, which applies the model's chat template internally
(nats_llm_studio.go:161). Here the template embedded in the GGUF
(``tokenizer.chat_template`` — a jinja template, the industry convention) is
rendered in-process when jinja2 is importable, with hand-rolled fallbacks for
the north-star families (llama-3 header tags, granite/chatml role tags) and a
generic role-prefix format otherwise.
"""

from __future__ import annotations

import logging
from typing import Any

from ..gguf.constants import KEY_CHAT_TEMPLATE
from ..gguf.tokenizer import GGUFTokenizer

log = logging.getLogger(__name__)

try:
    import jinja2

    _JINJA: jinja2.Environment | None = jinja2.Environment(
        loader=jinja2.BaseLoader(), keep_trailing_newline=True
    )
except ImportError:  # pragma: no cover
    _JINJA = None

# stop-string candidates looked up in the vocab (model families use different
# end-of-turn markers; anything present becomes a stop id)
STOP_TOKEN_STRINGS = (
    "</s>",
    "<|eot_id|>",
    "<|end_of_text|>",
    "<|im_end|>",
    "<|end_of_turn|>",
    "<|endoftext|>",
    "<|end_of_role|>",  # granite uses start/end role tags; end_of_text stops
)


def stop_token_ids(tok: GGUFTokenizer) -> frozenset[int]:
    ids = set()
    if tok.eos_id is not None:
        ids.add(int(tok.eos_id))
    for s in STOP_TOKEN_STRINGS:
        tid = tok.vocab.get(s)
        if tid is not None:
            ids.add(tid)
    return frozenset(ids)


def _render_jinja(template: str, messages: list[dict], add_generation_prompt: bool,
                  md: dict[str, Any]) -> str | None:
    if _JINJA is None:
        return None
    try:
        tokens = md.get("tokenizer.ggml.tokens")
        bos_id = md.get("tokenizer.ggml.bos_token_id")
        eos_id = md.get("tokenizer.ggml.eos_token_id")
        bos = tokens[bos_id] if tokens is not None and bos_id is not None else ""
        eos = tokens[eos_id] if tokens is not None and eos_id is not None else ""
        out = _JINJA.from_string(template).render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=bos,
            eos_token=eos,
        )
        return out
    except Exception as e:  # noqa: BLE001 — fall back to built-in formats
        log.warning("chat template render failed (%s); using fallback", e)
        return None


def _llama3_format(messages: list[dict], add_generation_prompt: bool) -> str:
    parts = ["<|begin_of_text|>"]
    for m in messages:
        parts.append(
            f"<|start_header_id|>{m.get('role', 'user')}<|end_header_id|>\n\n"
            f"{m.get('content', '')}<|eot_id|>"
        )
    if add_generation_prompt:
        parts.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(parts)


def _granite_format(messages: list[dict], add_generation_prompt: bool) -> str:
    parts = []
    for m in messages:
        parts.append(
            f"<|start_of_role|>{m.get('role', 'user')}<|end_of_role|>"
            f"{m.get('content', '')}<|end_of_text|>\n"
        )
    if add_generation_prompt:
        parts.append("<|start_of_role|>assistant<|end_of_role|>")
    return "".join(parts)


def _chatml_format(messages: list[dict], add_generation_prompt: bool) -> str:
    parts = []
    for m in messages:
        parts.append(f"<|im_start|>{m.get('role', 'user')}\n{m.get('content', '')}<|im_end|>\n")
    if add_generation_prompt:
        parts.append("<|im_start|>assistant\n")
    return "".join(parts)


def _generic_format(messages: list[dict], add_generation_prompt: bool) -> str:
    parts = []
    for m in messages:
        parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}\n")
    if add_generation_prompt:
        parts.append("assistant:")
    return "".join(parts)


def render_chat_template(
    md: dict[str, Any], messages: list[dict], add_generation_prompt: bool = True
) -> str:
    """messages -> prompt string, using (in order): the GGUF-embedded jinja
    template, a family-specific fallback keyed off vocab markers, generic."""
    template = md.get(KEY_CHAT_TEMPLATE)
    if template:
        out = _render_jinja(str(template), messages, add_generation_prompt, md)
        if out is not None:
            return out
    tokens = md.get("tokenizer.ggml.tokens")
    vocab = set(tokens) if tokens is not None else set()
    if "<|start_header_id|>" in vocab:
        return _llama3_format(messages, add_generation_prompt)
    if "<|start_of_role|>" in vocab:
        return _granite_format(messages, add_generation_prompt)
    if "<|im_start|>" in vocab:
        return _chatml_format(messages, add_generation_prompt)
    return _generic_format(messages, add_generation_prompt)

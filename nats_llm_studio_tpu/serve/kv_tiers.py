"""Hierarchical KV tiers below the HBM block pool.

Three tiers, coldest last:

    HBM block pool (serve/block_pool.py)  — live slots + radix prefix cache
      ↓ demote (owner-thread device_get)        ↑ promote (pool write + insert)
    host RAM (this module)                — byte-budgeted LRU of chunk entries
      ↓ spill (background thread, KVX1)         ↑ fetch (decode + re-host)
    JetStream Object Store                — KVX1 blobs; survives process death

Granularity is one **prefill chunk** (``C`` tokens), keyed by the full
token-id prefix ending at that chunk — exactly the radix prefix-cache node
granularity, so demotion maps 1:1 from evicted cache nodes and promotion
re-inserts at chunk boundaries the chunked-prefill pipeline can resume from.

Ownership/threading contract:

* ``demote``/``lookup`` are called from the batcher owner thread (the only
  thread that may touch the device pool); both only move **host** bytes and
  take the manager lock briefly. The device readback itself happens in the
  batcher *before* calling ``demote`` — this module never sees device arrays.
* Host-tier eviction hands entries to a daemon spill thread; Object Store
  I/O (via any :class:`SpillStore`) never runs on the owner thread.
* Every spill/fetch failure is contained: a failed spill just loses the cold
  copy (the entry was already LRU-out of every hotter tier — an honest miss
  later), a failed fetch is a miss. Neither can corrupt the pool: the
  manager never holds pool block ids, only host byte copies.

Restart-with-warm-cache: spilled blobs are single-chunk KVX1 exports plus a
JSON index object mapping path-hash → token ids. A respawned worker (no
live donor) lists the index, reassembles complete root→leaf chains, and
feeds them to ``ContinuousBatcher.import_prefix_blocks`` — the same entry
point warm handoff uses.
"""

from __future__ import annotations

import hashlib
import json
import queue
import threading

import numpy as np

from ..ops.kvcache import host_kv_nbytes
from ..transport import faults as _faults
from .kv_transfer import KVTransferFormatError, decode_kv_blob, encode_kv_blob


def path_hash(token_ids) -> str:
    """Stable content address for one chunk-aligned token prefix."""
    h = hashlib.sha256(np.asarray(list(token_ids), np.int64).tobytes())
    return h.hexdigest()[:32]


def _host_logits(lg):
    """Normalize chunk-end logits to a float32 ``[1, 1, vocab]`` ndarray
    (the shape ``_sample_first`` was compiled for), or None."""
    if lg is None:
        return None
    return np.asarray(lg, np.float32).reshape(1, 1, -1)


class _Entry:
    __slots__ = ("key", "k", "v", "logits", "nbytes")

    def __init__(self, key, k, v, logits):
        self.key = key
        self.k = k
        self.v = v
        self.logits = _host_logits(logits)
        self.nbytes = (
            host_kv_nbytes(k)
            + host_kv_nbytes(v)
            + (self.logits.nbytes if self.logits is not None else 0)
        )


class MemorySpillStore:
    """Dict-backed :class:`SpillStore` for tests and local bench runs.

    Persists across batcher/tier-manager instances within one process —
    the in-process stand-in for the Object Store's survives-restart
    property."""

    def __init__(self):
        self._objects: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, name: str, data: bytes) -> None:
        with self._lock:
            self._objects[name] = bytes(data)

    def get(self, name: str) -> bytes | None:
        with self._lock:
            return self._objects.get(name)

    def delete(self, name: str) -> None:
        with self._lock:
            self._objects.pop(name, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


class KVTierManager:
    """Host-RAM LRU tier with optional Object-Store spill underneath.

    ``spill`` is any object with blocking ``put(name, bytes)``,
    ``get(name) -> bytes | None`` and ``delete(name)`` — the worker wires a
    JetStream Object Store adapter, tests use :class:`MemorySpillStore`.
    """

    def __init__(
        self,
        host_budget_bytes: int,
        *,
        chunk_tokens: int,
        spill=None,
        namespace: str = "kv",
        max_spill_objects: int = 512,
        promote_chunks: int = 64,
        demote_free_frac: float = 0.10,
        spill_queue_depth: int = 64,
    ):
        self.host_budget = max(0, int(host_budget_bytes))
        self.chunk = int(chunk_tokens)
        self.namespace = namespace
        self.max_spill_objects = max(1, int(max_spill_objects))
        # batcher-consumed policy knobs (carried here so the batcher
        # signature stays small)
        self.promote_chunks = max(0, int(promote_chunks))
        self.demote_free_frac = max(0.0, float(demote_free_frac))
        self._lock = threading.Lock()
        # insertion-ordered dict as the LRU: MRU at the end
        self._entries: dict[tuple, _Entry] = {}
        self._bytes = 0
        self.counters = {
            "demoted_chunks": 0,
            "promoted_chunks": 0,  # bumped by the batcher on pool re-entry
            "host_hits": 0,
            "host_misses": 0,
            "host_evictions": 0,
            "spilled_blobs": 0,
            "spill_failures": 0,
            "spill_dropped": 0,
            "fetched_blobs": 0,
            "fetch_failures": 0,
            "demote_failures": 0,  # bumped by the prefix cache's demote hook
        }
        self._spill = spill
        self._index: dict[str, dict] | None = None
        self._q: queue.Queue | None = None
        self._pending = 0
        self._idle = threading.Condition(self._lock)
        self._thread: threading.Thread | None = None
        if spill is not None:
            self._q = queue.Queue(maxsize=max(1, int(spill_queue_depth)))
            self._thread = threading.Thread(
                target=self._spill_loop, name="kv-spill", daemon=True
            )
            self._thread.start()

    # -- owner-thread API ----------------------------------------------------

    def demote(self, token_ids, k, v, logits) -> bool:
        """Accept one evicted chunk (host k/v leaves: ndarray or
        ``(codes, scales)``). Returns True once the entry is owned by a
        lower tier (host RAM, or queued for spill)."""
        key = tuple(int(t) for t in token_ids)
        ent = _Entry(key, k, v, logits)
        with self._lock:
            self.counters["demoted_chunks"] += 1
            if ent.nbytes > self.host_budget:
                # bigger than the whole host budget: straight to spill
                return self._enqueue_spill_locked(ent)
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = ent
            self._bytes += ent.nbytes
            self._evict_host_locked()
        return True

    def lookup(self, token_ids) -> _Entry | None:
        """Chunk entry for this exact prefix, or None. A host hit refreshes
        recency; a spill hit decodes the blob and re-hosts it (promotion
        through the tiers — the pool write is the batcher's half)."""
        key = tuple(int(t) for t in token_ids)
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._entries[key] = ent  # move to MRU
                self.counters["host_hits"] += 1
                return ent
            self.counters["host_misses"] += 1
        if self._spill is None:
            return None
        return self._fetch(key)

    def _fetch(self, key) -> _Entry | None:
        if _faults.ACTIVE is not None:
            f = _faults.ACTIVE.check(_faults.TIER_FETCH)
            if f is not None:
                with self._lock:
                    self.counters["fetch_failures"] += 1
                if f.kind == "raise":
                    raise f.exception()
                return None
        name = f"{self.namespace}/{path_hash(key)}"
        try:
            data = self._spill.get(name)
            if data is None:
                return None
            export = decode_kv_blob(data)
            if (
                tuple(export["token_ids"]) != key
                or int(export["chunk_tokens"]) != self.chunk
                or not export["chunks"]
            ):
                raise KVTransferFormatError("spilled blob does not match key")
            ch = export["chunks"][0]
            ent = _Entry(key, ch["k"], ch["v"], ch.get("logits"))
        except Exception:  # noqa: BLE001 — any fetch failure is a miss
            with self._lock:
                self.counters["fetch_failures"] += 1
            return None
        with self._lock:
            self.counters["fetched_blobs"] += 1
            self._entries[key] = ent
            self._bytes += ent.nbytes
            self._evict_host_locked(skip=key)
        return ent

    def note_promoted(self, n_chunks: int) -> None:
        with self._lock:
            self.counters["promoted_chunks"] += n_chunks

    def note_demote_failure(self) -> None:
        with self._lock:
            self.counters["demote_failures"] += 1

    # -- host-tier eviction → spill ------------------------------------------

    def _evict_host_locked(self, skip=None) -> None:
        while self._bytes > self.host_budget and self._entries:
            key = next(iter(self._entries))  # LRU end
            if key == skip and len(self._entries) > 1:
                # never immediately re-spill the entry a fetch just hosted
                ent = self._entries.pop(key)
                self._entries[key] = ent
                key = next(iter(self._entries))
            ent = self._entries.pop(key)
            self._bytes -= ent.nbytes
            self.counters["host_evictions"] += 1
            self._enqueue_spill_locked(ent)
            if key == skip:
                break

    def _enqueue_spill_locked(self, ent) -> bool:
        if self._q is None:
            return False
        try:
            self._q.put_nowait(ent)
        except queue.Full:
            self.counters["spill_dropped"] += 1
            return False
        self._pending += 1
        return True

    # -- spill thread --------------------------------------------------------

    def _spill_loop(self) -> None:
        while True:
            ent = self._q.get()
            if ent is None:
                return
            try:
                self._spill_one(ent)
            finally:
                with self._lock:
                    self._pending -= 1
                    self._idle.notify_all()

    def _spill_one(self, ent) -> None:
        try:
            if _faults.ACTIVE is not None:
                f = _faults.ACTIVE.check(_faults.TIER_SPILL)
                if f is not None:
                    # sever/drop/raise all mean the store is gone mid-
                    # demotion: the blob is not written, the index is not
                    # touched — the chunk is simply lost from the cold tier
                    raise f.exception() if f.kind == "raise" else (
                        _faults.InjectedFault(f"tier spill {f.kind}")
                    )
            blob = encode_kv_blob({
                "token_ids": list(ent.key),
                "chunk_tokens": self.chunk,
                "chunks": [{"k": ent.k, "v": ent.v, "logits": ent.logits}],
            })
            h = path_hash(ent.key)
            self._spill.put(f"{self.namespace}/{h}", blob)
            idx = self._index_locked_load()
            idx[h] = {"t": list(ent.key), "n": len(ent.key) // self.chunk}
            self._prune_index(idx)
            self._spill.put(
                f"{self.namespace}/index",
                json.dumps(idx, separators=(",", ":")).encode(),
            )
            with self._lock:
                self.counters["spilled_blobs"] += 1
        except Exception:  # noqa: BLE001 — spill is best-effort by contract
            with self._lock:
                self.counters["spill_failures"] += 1

    def _index_locked_load(self) -> dict:
        # only the spill thread mutates the index; load lazily so a fresh
        # manager sees objects a previous process spilled
        if self._index is None:
            self._index = {}
            try:
                raw = self._spill.get(f"{self.namespace}/index")
                if raw:
                    self._index = json.loads(raw)
            except Exception:  # noqa: BLE001 — missing/corrupt index = empty
                self._index = {}
        return self._index

    def _prune_index(self, idx: dict) -> None:
        while len(idx) > self.max_spill_objects:
            # drop the shallowest chains first: deep suffix chunks are
            # useless without their ancestors, so depth is the cheapest
            # usefulness proxy the index carries
            victim = min(idx, key=lambda h: idx[h].get("n", 0))
            idx.pop(victim)
            try:
                self._spill.delete(f"{self.namespace}/{victim}")
            except Exception:  # noqa: BLE001 — purge is best-effort
                pass

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until queued spills have been written (tests/bench)."""
        if self._q is None:
            return True
        with self._idle:
            return self._idle.wait_for(lambda: self._pending == 0, timeout)

    def close(self) -> None:
        if self._q is not None and self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=5.0)

    # -- restart path --------------------------------------------------------

    def warm_exports(self, limit: int = 4) -> list[dict]:
        """Reassemble the deepest complete root→leaf chains from the spill
        tier into ``import_prefix_blocks`` export dicts — the no-live-donor
        restart path. Chains with a missing or unreadable ancestor blob are
        skipped; nothing here can raise."""
        if self._spill is None or limit <= 0:
            return []
        try:
            raw = self._spill.get(f"{self.namespace}/index")
            idx = json.loads(raw) if raw else {}
        except Exception:  # noqa: BLE001
            return []
        paths = sorted(
            (tuple(v["t"]) for v in idx.values() if v.get("t")),
            key=len, reverse=True,
        )
        # leaves only: a path that is a strict prefix of an already-chosen
        # deeper path is covered by it
        leaves: list[tuple] = []
        for p in paths:
            if not any(q[: len(p)] == p for q in leaves):
                leaves.append(p)
        out: list[dict] = []
        C = self.chunk
        for path in leaves[:limit]:
            chunks = []
            ok = True
            for d in range(len(path) // C):
                ent = self.lookup(path[: (d + 1) * C])
                if ent is None:
                    ok = False
                    break
                chunks.append({"k": ent.k, "v": ent.v, "logits": ent.logits})
            if ok and chunks:
                out.append({
                    "token_ids": list(path[: len(chunks) * C]),
                    "chunk_tokens": C,
                    "chunks": chunks,
                })
        return out

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["host_entries"] = len(self._entries)
            out["host_bytes"] = self._bytes
            out["host_budget_bytes"] = self.host_budget
            out["spill_pending"] = self._pending
            out["spill_enabled"] = int(self._spill is not None)
        return out

from .api import ChatEngine, EngineError, ModelNotFound, Registry
from .autoscaler import Autoscaler
from .router import (
    ClusterRouter,
    RouterExhausted,
    RouterProcess,
    WorkerAdvert,
    prompt_head_hash,
)
from .worker import Worker

__all__ = [
    "Autoscaler",
    "ChatEngine",
    "ClusterRouter",
    "EngineError",
    "ModelNotFound",
    "Registry",
    "RouterExhausted",
    "RouterProcess",
    "Worker",
    "WorkerAdvert",
    "prompt_head_hash",
]

from .api import ChatEngine, EngineError, ModelNotFound, Registry
from .worker import Worker

__all__ = ["ChatEngine", "EngineError", "ModelNotFound", "Registry", "Worker"]

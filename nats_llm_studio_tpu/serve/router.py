"""Cluster membership + failover routing (ROADMAP item 3: queue-group
scale-out made fault-tolerant).

Every worker periodically publishes a compact advert on
``{prefix}.cluster.adverts`` — worker id, queue depth, brownout level, HBM
headroom, loaded models, draining flag, and the head hashes of recently
served prompts. A :class:`ClusterRouter` subscribes, keeps a live member
table, and steers chat requests at the *directed* per-worker subject
(``{prefix}.worker.<id>.chat_model``) by advertised load and prefix-cache
locality, falling back to the plain queue-group subject when no advert is
live (a router with an empty table degrades to exactly the pre-cluster
behavior — random queue-group delivery — never to an outage).

Usable two ways:

* **in-process**: attach to a ``NatsClient`` and call
  :meth:`ClusterRouter.request_chat` instead of ``nc.request`` — the retry
  loop re-picks a different worker per attempt and carries the
  ``X-Excluded-Workers`` header so a shed/crashed worker is never retried
  immediately.
* **standalone**: :class:`RouterProcess` (``python -m nats_llm_studio_tpu
  route``) forwards ``{prefix}.route.chat_model`` requests to the picked
  worker and relays the reply — a thin L7 balancer for clients that want
  steering without importing this package.

Prefix-cache locality is approximated with a *text* head hash
(:func:`prompt_head_hash`): the server-side radix cache keys on token-id
chunks, but the router has no tokenizer — hashing the first N chars of the
prompt is cheap, tokenizer-free, and identical on both sides. Equal text
heads tokenize equally, so a head-hash hit implies real prefix-cache reuse
on the sticky worker; a miss merely loses the locality bonus.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field
from hashlib import blake2b

from ..obs import (
    Span,
    new_span_id,
    new_trace_id,
    parse_span_context,
    span_context_value,
)
from ..transport import ConnectionClosedError, Msg, NatsClient, RetryPolicy
from ..transport import protocol as p
from ..transport.envelope import (
    deadline_header_value,
    deadline_remaining_s,
    is_retryable_envelope,
)

log = logging.getLogger(__name__)

ADVERT_SUBJECT = "cluster.adverts"  # published under the subject prefix
ROUTE_SUBJECT = "route.chat_model"  # RouterProcess's forwarding subject
DEFAULT_HEAD_CHARS = 256
# seq-ordering guard bounds (ingest): a backward seq step within
# SEQ_REORDER_WINDOW is a stale/reordered packet and is dropped; a jump
# further back than that — or an advert numbered within SEQ_RESTART_MAX
# while we hold a higher seq — is a RESPAWNED worker whose counter
# restarted at 1, and must replace the dead incarnation's advert NOW
# instead of being ignored until staleness ages it out (ISSUE 15).
SEQ_REORDER_WINDOW = 64
SEQ_RESTART_MAX = 3


class RouterExhausted(asyncio.TimeoutError):
    """Retry budget exhausted without a served reply.

    Subclasses :class:`asyncio.TimeoutError` so existing ``except
    asyncio.TimeoutError`` handlers keep working, but carries structure an
    HTTP front end needs to render an honest 503: the final *retryable*
    envelope (if one was received), the last worker that shed the request,
    and a retry-after hint derived from the retry policy's backoff — instead
    of flattening all of that into a bare exception string.
    """

    def __init__(
        self,
        message: str,
        *,
        envelope: dict | None = None,
        worker_id: str | None = None,
        retry_after_s: float = 1.0,
    ):
        super().__init__(message)
        self.envelope = envelope if isinstance(envelope, dict) else None
        self.worker_id = worker_id
        self.retry_after_s = max(0.0, float(retry_after_s))

    def detail(self) -> str:
        """The most specific human-readable cause available."""
        if self.envelope is not None:
            err = self.envelope.get("error")
            if isinstance(err, str) and err:
                return err
        return str(self) or "retry budget exhausted"


def prompt_head_hash(model: str, messages, chars: int = DEFAULT_HEAD_CHARS) -> str:
    """Hash of the prompt head, for prefix-cache locality steering.

    Computed identically by the worker (recording heads it served) and the
    router (steering new requests): blake2b-64 over the model name and the
    first ``chars`` characters of the concatenated message contents. Role
    and content are length-delimited so ("ab","c") can't collide with
    ("a","bc") across message boundaries.
    """
    h = blake2b(digest_size=8)
    h.update(model.encode())
    budget = max(0, chars)
    for m in messages if isinstance(messages, list) else []:
        if budget <= 0:
            break
        if not isinstance(m, dict):
            continue
        role = str(m.get("role", ""))
        content = str(m.get("content", ""))[:budget]
        budget -= len(content)
        h.update(f"\x1f{len(role)}:{role}\x1f{len(content)}:".encode())
        h.update(content.encode())
    return h.hexdigest()


class RecentHeads:
    """Bounded LRU of recently served prompt-head hashes. The worker records
    a head per admitted chat and adverts the set; the router treats a match
    as prefix-cache locality. Plain dict insertion order is the LRU."""

    def __init__(self, capacity: int = 32):
        self.capacity = capacity
        self._heads: dict[str, None] = {}

    def add(self, head: str) -> None:
        self._heads.pop(head, None)
        self._heads[head] = None
        while len(self._heads) > self.capacity:
            del self._heads[next(iter(self._heads))]

    def snapshot(self) -> list[str]:
        return list(self._heads)


# coarse chars-per-token for the router's long-prompt heuristic: it has no
# tokenizer (tokenization happens on the worker), so ring-prefill preference
# keys off character length
_CHARS_PER_TOKEN = 4


def _ring_min_tokens() -> int:
    """Mirror of parallel.ring_attention.ring_prefill_min_tokens without the
    jax import (the router is pure control plane)."""
    try:
        return int(os.environ.get("RING_PREFILL_MIN_TOKENS", "4096"))
    except ValueError:
        return 4096


def _prompt_chars(messages) -> int:
    n = 0
    try:
        for m in messages or ():
            c = m.get("content") if isinstance(m, dict) else None
            if isinstance(c, str):
                n += len(c)
    except TypeError:
        return 0
    return n


@dataclass
class WorkerAdvert:
    """One worker's most recent cluster advert, as the router sees it."""

    worker_id: str
    role: str = ""  # "" monolithic / "prefill" / "decode" (ISSUE 13)
    queue_depth: int = 0
    slots: int = 0  # advertised concurrent-stream capacity (dp x per-replica)
    brownout: int = 0  # 0 NORMAL / 1 BROWNOUT / 2 SHED_ONLY
    hbm_headroom: float = 1.0
    mesh: dict = field(default_factory=dict)  # named axis factoring, e.g. {"dp": 2, "tp": 2}
    models: tuple[str, ...] = ()
    kv_tier_depth: int = 0  # host-tier KV entries (warm-cache tiebreak)
    draining: bool = False
    heads: frozenset[str] = frozenset()
    seq: int = 0
    mono: float = 0.0  # ingest time (router clock; staleness = now - mono)

    @property
    def load(self) -> float:
        """Queue depth normalized by advertised slot capacity: a dp=2
        worker with 8 slots and depth 2 is LESS loaded than a dp=1 worker
        with 4 slots and depth 2. Raw depth when capacity is unknown
        (pre-multi-axis adverts)."""
        if self.slots > 0:
            return self.queue_depth / self.slots
        return float(self.queue_depth)

    @property
    def sp_degree(self) -> int:
        """Ring-attention sequence-parallel width from the advertised mesh
        (1 = no sp axis — long prefills run dense on one chip's lane)."""
        try:
            return int(self.mesh.get("sp", 1) or 1)
        except (TypeError, ValueError):
            return 1

    @classmethod
    def from_dict(cls, d: dict) -> "WorkerAdvert | None":
        wid = d.get("worker_id")
        if not isinstance(wid, str) or not wid:
            return None
        role = d.get("role")
        mesh = d.get("mesh")
        return cls(
            worker_id=wid,
            role=role if isinstance(role, str) else "",
            queue_depth=int(d.get("queue_depth") or 0),
            slots=int(d.get("slots") or 0),
            brownout=int(d.get("brownout") or 0),
            hbm_headroom=float(d.get("hbm_headroom", 1.0)),
            mesh=dict(mesh) if isinstance(mesh, dict) else {},
            models=tuple(m for m in d.get("models") or () if isinstance(m, str)),
            kv_tier_depth=int(d.get("kv_tier_depth") or 0),
            draining=bool(d.get("draining")),
            heads=frozenset(h for h in d.get("heads") or () if isinstance(h, str)),
            seq=int(d.get("seq") or 0),
            mono=time.monotonic(),
        )


@dataclass
class RouterStats:
    routed_total: int = 0  # requests steered at a directed subject
    fallback_total: int = 0  # no live member: plain queue-group subject
    locality_total: int = 0  # picks won by a prefix-head match
    dead_marked_total: int = 0  # members dropped after a timeout/sever
    two_hop_total: int = 0  # picks that paired a prefill-role worker

    def as_dict(self) -> dict:
        return {
            "routed_total": self.routed_total,
            "fallback_total": self.fallback_total,
            "locality_total": self.locality_total,
            "dead_marked_total": self.dead_marked_total,
            "two_hop_total": self.two_hop_total,
        }


class ClusterRouter:
    """Live member table + steering. One per client (or per RouterProcess).

    ``start()`` subscribes to the advert subject; until the first advert
    lands every pick falls back to the queue-group subject, so attaching a
    router is always safe — it only ever *adds* steering.
    """

    def __init__(
        self,
        nc: NatsClient,
        *,
        prefix: str = "lmstudio",
        stale_after_s: float = 5.0,
        prefix_head_chars: int = DEFAULT_HEAD_CHARS,
        queue_group_fallback: bool = True,
        obs_spans: bool | None = None,
        ident: str = "router",
    ):
        self.nc = nc
        self.prefix = prefix
        self.stale_after_s = stale_after_s
        self.prefix_head_chars = prefix_head_chars
        self.queue_group_fallback = queue_group_fallback
        # per-attempt steering spans on {prefix}.obs.spans; None defers to
        # the OBS_SPANS env kill switch so bare ClusterRouter(nc) callers
        # (tests, bench) inherit the fleet-wide setting
        if obs_spans is None:
            obs_spans = os.environ.get(
                "OBS_SPANS", "1"
            ).strip().lower() not in ("0", "false", "off")
        self.obs_spans = obs_spans
        self.ident = ident  # worker_id stamped on this router's spans
        self.stats = RouterStats()
        self._members: dict[str, WorkerAdvert] = {}
        self._sub = None
        # router-local (worker_id, tenant) -> steered requests in flight:
        # the pick tie-breaker that spreads ONE tenant's burst across
        # workers instead of stacking it behind itself on the best-ranked
        # one (other tenants' picks ignore it entirely)
        self._tenant_inflight: dict[tuple[str, str], int] = {}

    def _tenant_track(self, worker_id: str | None, tenant: str | None, d: int) -> None:
        if not worker_id or not tenant:
            return
        k = (worker_id, tenant)
        n = self._tenant_inflight.get(k, 0) + d
        if n > 0:
            self._tenant_inflight[k] = n
        else:
            self._tenant_inflight.pop(k, None)

    # -- membership ----------------------------------------------------------

    async def start(self) -> "ClusterRouter":
        self._sub = await self.nc.subscribe(
            f"{self.prefix}.{ADVERT_SUBJECT}", cb=self._on_advert
        )
        return self

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None

    async def _on_advert(self, msg: Msg) -> None:
        try:
            d = msg.json()
        except ValueError:
            return
        if isinstance(d, dict):
            self.ingest(d)

    def ingest(self, d: dict) -> None:
        """Feed one advert dict (the sub callback does this; tests and the
        bench can inject directly). Out-of-order adverts from one worker are
        dropped by seq — but a drained-then-respawned worker reusing the
        same WORKER_ID restarts its counter at 1, and its fresh adverts must
        not be mistaken for reorders of the dead incarnation's stream."""
        adv = WorkerAdvert.from_dict(d)
        if adv is None:
            return
        cur = self._members.get(adv.worker_id)
        if cur is not None and adv.seq and adv.seq < cur.seq:
            restarted = (
                adv.seq <= SEQ_RESTART_MAX
                or cur.seq - adv.seq > SEQ_REORDER_WINDOW
            )
            if not restarted:
                return
        self._members[adv.worker_id] = adv

    def mark_dead(self, worker_id: str) -> None:
        """Drop a member NOW (observed timeout/sever) instead of waiting out
        the staleness window — the next pick must not re-steer at it."""
        if self._members.pop(worker_id, None) is not None:
            self.stats.dead_marked_total += 1
            log.info("router: marked worker %s dead", worker_id)

    def members(self, *, live_only: bool = True) -> list[WorkerAdvert]:
        """Live serving members. Gateway adverts (metrics-only, no chat
        subjects) are excluded — they must not count as workers in healthz
        or become steering candidates."""
        if not live_only:
            return list(self._members.values())
        cutoff = time.monotonic() - self.stale_after_s
        return [m for m in self._members.values()
                if m.mono >= cutoff and m.role != "gateway"]

    # -- steering ------------------------------------------------------------

    def worker_subject(self, worker_id: str, op: str = "chat_model") -> str:
        """The directed (non-queue-group) subject one worker listens on."""
        return f"{self.prefix}.worker.{worker_id}.{op}"

    def pick(
        self,
        model: str | None = None,
        messages=None,
        excluded: tuple[str, ...] | list[str] = (),
        tenant: str | None = None,
    ) -> str | None:
        """Best live worker id, or None (caller falls back to the queue
        group). Role-aware: see :meth:`pick_pair` (this is its first half)."""
        return self.pick_pair(
            model=model, messages=messages, excluded=excluded, tenant=tenant
        )[0]

    def pick_pair(
        self,
        model: str | None = None,
        messages=None,
        excluded: tuple[str, ...] | list[str] = (),
        tenant: str | None = None,
    ) -> tuple[str | None, str | None]:
        """Role-aware pick: ``(serving_worker_id, prefill_worker_id)``.

        Serving candidates exclude prefill-role workers whenever any
        non-prefill member is live — a prefill worker's pool churns through
        transient prefill blocks and must not also hold long decodes. With
        no live members at all the caller falls back to the queue group;
        with ONLY prefill-role members live they serve (degraded but up).
        Ranking within candidates is unchanged: prefix-head locality first
        (a sticky worker replays the cached prefill), then brownout level,
        then model-loaded, then queue depth. Draining and excluded workers
        never win.

        The second element is the best live prefill-role worker, returned
        only when the serving pick is decode-role — the caller stamps it in
        ``X-KV-Prefill-Worker`` so the decode worker pulls the prompt's KV
        blocks from it (the disaggregated two-hop). Monolithic picks never
        pair: they prefill locally anyway."""
        head = None
        if model and messages and self.prefix_head_chars > 0:
            head = prompt_head_hash(model, messages, self.prefix_head_chars)
        # ring-capable preference: a prompt long enough to take the sp
        # ring-prefill path (chars/4 >= RING_PREFILL_MIN_TOKENS) prefers a
        # worker whose advertised mesh has sp > 1 — there the prefill runs
        # sequence-parallel instead of serializing on one chip's lane
        long_prompt = (
            messages is not None
            and _prompt_chars(messages) >= _CHARS_PER_TOKEN * _ring_min_tokens()
        )
        candidates = [
            m for m in self.members()
            if not m.draining and m.worker_id not in excluded
        ]
        serving = [m for m in candidates if m.role != "prefill"] or candidates
        best: tuple | None = None
        best_id: str | None = None
        best_local = False
        best_role = ""
        for m in serving:
            local = head is not None and head in m.heads and m.brownout < 2
            key = (
                0 if local else 1,
                m.brownout,
                0 if (model and model in m.models) else 1,
                0 if (not long_prompt or m.sp_degree > 1) else 1,
                m.load,  # depth per advertised slot: dp replicas count
                m.queue_depth,
                -m.kv_tier_depth,  # equal load: prefer the warmer KV tier
                # tenant-aware tie-break: among equally loaded workers,
                # steer away from the ones this SAME tenant already has
                # steered requests in flight on — its burst spreads across
                # the fleet instead of stacking behind itself
                (self._tenant_inflight.get((m.worker_id, tenant), 0)
                 if tenant else 0),
                m.worker_id,  # total order: deterministic under ties
            )
            if best is None or key < best:
                best, best_id, best_local, best_role = key, m.worker_id, local, m.role
        if best_id is not None and best_local:
            self.stats.locality_total += 1
        prefill_id: str | None = None
        if best_id is not None and best_role == "decode":
            pbest: tuple | None = None
            for m in candidates:
                if m.role != "prefill" or m.brownout >= 2:
                    continue
                pkey = (
                    m.brownout,
                    0 if (model and model in m.models) else 1,
                    0 if (not long_prompt or m.sp_degree > 1) else 1,
                    m.load,
                    m.queue_depth,
                    -m.kv_tier_depth,
                    m.worker_id,
                )
                if pbest is None or pkey < pbest:
                    pbest, prefill_id = pkey, m.worker_id
        if prefill_id is not None:
            self.stats.two_hop_total += 1
        return best_id, prefill_id

    # -- steered request-reply ----------------------------------------------

    async def _emit_span(self, span: dict) -> None:
        """Fire-and-forget publish of one steering span. Spans are
        diagnostics, never load-bearing: a dropped connection loses the
        span, not the request."""
        if not self.obs_spans:
            return
        try:
            await self.nc.publish(
                f"{self.prefix}.obs.spans",
                json.dumps({"spans": [span]}, separators=(",", ":")).encode(),
            )
        except (ConnectionError, ValueError):
            pass

    async def request_chat(
        self,
        payload: dict | bytes,
        timeout: float = 120.0,
        headers: dict[str, str] | None = None,
        retry: RetryPolicy | None = None,
        raise_on_exhausted: bool = False,
    ) -> Msg:
        """Steered chat request: like ``nc.request(chat_subject, ...)`` with
        a retry policy, but every attempt re-picks a worker from the live
        member table, excluded workers accumulate across hops (header AND
        pick filter), and a worker that times out is marked dead so
        unrelated requests stop steering at it too.

        With ``raise_on_exhausted`` a spent retry budget raises
        :class:`RouterExhausted` (carrying the final retryable envelope and a
        retry-after hint) instead of returning the raw retryable reply —
        HTTP front ends use this to render a structured 503."""
        retry = retry or RetryPolicy()
        if isinstance(payload, bytes):
            body = payload
            try:
                obj = json.loads(payload or b"{}")
            except ValueError:
                obj = {}
        else:
            obj = payload
            body = json.dumps(payload).encode()
        model = obj.get("model") if isinstance(obj, dict) else None
        messages = obj.get("messages") if isinstance(obj, dict) else None
        headers = dict(headers) if headers else {}
        headers.setdefault(p.TRACE_HEADER, new_trace_id())
        headers.setdefault(p.DEADLINE_HEADER, deadline_header_value(timeout))
        deadline_hdr = headers[p.DEADLINE_HEADER]
        trace_id = headers[p.TRACE_HEADER]
        # the caller's span (gateway root, typically) parents every attempt
        inbound = parse_span_context(headers.get(p.TRACEPARENT_HEADER))
        parent_span_id = inbound[1] if inbound else ""
        excluded = p.parse_worker_list(headers.get(p.EXCLUDED_WORKERS_HEADER))
        # gateway-stamped tenant identity: feeds the pick tie-breaker and
        # the per-(worker, tenant) in-flight tracking below
        tenant = headers.get(p.TENANT_HEADER) or None
        fallback = f"{self.prefix}.chat_model"
        last_exc: BaseException | None = None
        last_msg: Msg | None = None
        for attempt in range(1, retry.max_attempts + 1):
            remaining = deadline_remaining_s(deadline_hdr)
            attempt_timeout = timeout if remaining is None else min(timeout, remaining)
            if attempt_timeout <= 0:
                break
            headers[p.ATTEMPT_HEADER] = str(attempt)
            if excluded:
                headers[p.EXCLUDED_WORKERS_HEADER] = p.format_worker_list(excluded)
            wid, prefill_wid = self.pick_pair(
                model=model, messages=messages, excluded=excluded,
                tenant=tenant,
            )
            if prefill_wid is not None and prefill_wid != wid:
                # disaggregated two-hop: name the prefill-role worker the
                # decode target should pull KV blocks from. Re-stamped (or
                # dropped) per attempt — the prefill peer may die mid-retry.
                headers[p.KV_PREFILL_HEADER] = prefill_wid
            else:
                headers.pop(p.KV_PREFILL_HEADER, None)
            if wid is not None:
                subject = self.worker_subject(wid)
                self.stats.routed_total += 1
            elif self.queue_group_fallback:
                subject = fallback
                self.stats.fallback_total += 1
            else:
                raise ConnectionClosedError("no live cluster members")
            # each attempt is its own span; the worker parses this header and
            # parents its serve span under the attempt that reached it, so
            # retries and excluded-worker hops stay causally separate
            span_id = new_span_id()
            span_t0 = time.time()
            headers[p.TRACEPARENT_HEADER] = span_context_value(trace_id, span_id)
            attrs: dict = {"attempt": attempt,
                           "worker": wid or "queue-group", "outcome": "ok"}
            if headers.get(p.KV_PREFILL_HEADER):
                attrs["prefill_worker"] = headers[p.KV_PREFILL_HEADER]
            self._tenant_track(wid, tenant, +1)
            try:
                try:
                    msg = await self.nc.request(
                        subject, body, timeout=attempt_timeout, headers=headers
                    )
                except ConnectionClosedError as e:
                    attrs["outcome"] = "conn_error"
                    last_exc, last_msg = e, None
                except asyncio.TimeoutError as e:
                    attrs["outcome"] = "timeout"
                    if not retry.retry_on_timeout:
                        raise
                    last_exc, last_msg = e, None
                    if wid is not None:
                        # a directed request that never answered: the worker is
                        # likely dead (adverts will confirm); steer away now
                        self.mark_dead(wid)
                        if wid not in excluded:
                            excluded.append(wid)
                else:
                    if self._retryable(msg):
                        # a retryable reply on the FINAL attempt still lands in
                        # last_msg so the exhaustion site below decides whether
                        # to return it raw or raise RouterExhausted
                        attrs["outcome"] = "retryable"
                        last_exc, last_msg = None, msg
                        if attempt >= retry.max_attempts:
                            break
                        shed_by = NatsClient._reply_worker_id(msg) or wid
                        if shed_by and NatsClient._is_excluded_bounce(msg):
                            # one-shot exclusion consumed (see client.request)
                            if shed_by in excluded:
                                excluded.remove(shed_by)
                        elif shed_by and shed_by not in excluded:
                            excluded.append(shed_by)
                        if not excluded:
                            headers.pop(p.EXCLUDED_WORKERS_HEADER, None)
                        if not await NatsClient._backoff_within_budget(
                            retry.delay_s(attempt), deadline_hdr
                        ):
                            break
                        continue
                    return msg
            finally:
                self._tenant_track(wid, tenant, -1)
                await self._emit_span(Span(
                    trace_id=trace_id, span_id=span_id, stage="router.attempt",
                    worker_id=self.ident, parent_span_id=parent_span_id,
                    t0=span_t0, t1=time.time(), attrs=attrs,
                ).to_dict())
            if attempt >= retry.max_attempts:
                break
            if not await NatsClient._backoff_within_budget(
                retry.delay_s(attempt), deadline_hdr
            ):
                break
        if last_msg is not None:
            if raise_on_exhausted:
                raise RouterExhausted(
                    "retry budget exhausted: every worker shed this request",
                    envelope=self._envelope_of(last_msg),
                    worker_id=NatsClient._reply_worker_id(last_msg),
                    retry_after_s=retry.delay_s(1),
                )
            return last_msg
        if last_exc is not None:
            if raise_on_exhausted:
                raise RouterExhausted(
                    f"retry budget exhausted: {last_exc}",
                    retry_after_s=retry.delay_s(1),
                ) from last_exc
            raise last_exc
        raise RouterExhausted(
            "deadline budget exhausted before steered chat request",
            retry_after_s=retry.delay_s(1),
        )

    async def request_chat_stream(
        self,
        payload: dict | bytes,
        timeout: float = 120.0,
        idle_timeout: float = 30.0,
        headers: dict[str, str] | None = None,
        retry: RetryPolicy | None = None,
        raise_on_exhausted: bool = False,
    ):
        """Steered *streaming* chat request: per-attempt worker pick like
        :meth:`request_chat`, yielding every reply message (chunks, then the
        ``Nats-Stream-Done`` terminal) from the winning attempt.

        Retries happen only BEFORE the first chunk reaches the caller — a
        retryable terminal or a timeout with nothing yielded re-picks a
        worker; once a chunk is out, failure surfaces honestly (a retry
        would replay tokens the caller already consumed). Closing this
        generator early propagates the consumer-gone cancel down the
        transport so the serving worker frees its batcher slot."""
        retry = retry or RetryPolicy()
        if isinstance(payload, bytes):
            body = payload
            try:
                obj = json.loads(payload or b"{}")
            except ValueError:
                obj = {}
        else:
            obj = payload
            body = json.dumps(payload).encode()
        model = obj.get("model") if isinstance(obj, dict) else None
        messages = obj.get("messages") if isinstance(obj, dict) else None
        headers = dict(headers) if headers else {}
        headers.setdefault(p.TRACE_HEADER, new_trace_id())
        headers.setdefault(p.DEADLINE_HEADER, deadline_header_value(timeout))
        deadline_hdr = headers[p.DEADLINE_HEADER]
        trace_id = headers[p.TRACE_HEADER]
        inbound = parse_span_context(headers.get(p.TRACEPARENT_HEADER))
        parent_span_id = inbound[1] if inbound else ""
        excluded = p.parse_worker_list(headers.get(p.EXCLUDED_WORKERS_HEADER))
        tenant = headers.get(p.TENANT_HEADER) or None
        fallback = f"{self.prefix}.chat_model"
        last_exc: BaseException | None = None
        last_msg: Msg | None = None
        for attempt in range(1, retry.max_attempts + 1):
            remaining = deadline_remaining_s(deadline_hdr)
            attempt_timeout = timeout if remaining is None else min(timeout, remaining)
            if attempt_timeout <= 0:
                break
            headers[p.ATTEMPT_HEADER] = str(attempt)
            if excluded:
                headers[p.EXCLUDED_WORKERS_HEADER] = p.format_worker_list(excluded)
            wid, prefill_wid = self.pick_pair(
                model=model, messages=messages, excluded=excluded,
                tenant=tenant,
            )
            if prefill_wid is not None and prefill_wid != wid:
                headers[p.KV_PREFILL_HEADER] = prefill_wid
            else:
                headers.pop(p.KV_PREFILL_HEADER, None)
            if wid is not None:
                subject = self.worker_subject(wid)
                self.stats.routed_total += 1
            elif self.queue_group_fallback:
                subject = fallback
                self.stats.fallback_total += 1
            else:
                raise ConnectionClosedError("no live cluster members")
            span_id = new_span_id()
            span_t0 = time.time()
            headers[p.TRACEPARENT_HEADER] = span_context_value(trace_id, span_id)
            attrs: dict = {"attempt": attempt,
                           "worker": wid or "queue-group", "outcome": "ok"}
            if headers.get(p.KV_PREFILL_HEADER):
                attrs["prefill_worker"] = headers[p.KV_PREFILL_HEADER]
            yielded = False
            retry_msg: Msg | None = None
            stream = self.nc.request_stream(
                subject, body, timeout=attempt_timeout,
                idle_timeout=idle_timeout, headers=headers,
            )
            self._tenant_track(wid, tenant, +1)
            try:
                async for msg in stream:
                    terminal = bool(msg.headers and "Nats-Stream-Done" in msg.headers)
                    if not yielded and terminal and self._retryable(msg):
                        # held back even on the final attempt: the
                        # exhaustion site decides raw-yield vs raise
                        retry_msg = msg
                        break
                    yielded = True
                    yield msg
                    if terminal:
                        return
            except ConnectionClosedError as e:
                attrs["outcome"] = "conn_error"
                if yielded:
                    raise
                last_exc, last_msg = e, None
            except asyncio.TimeoutError as e:
                attrs["outcome"] = "timeout"
                if yielded or not retry.retry_on_timeout:
                    raise
                last_exc, last_msg = e, None
                if wid is not None:
                    self.mark_dead(wid)
                    if wid not in excluded:
                        excluded.append(wid)
            else:
                if retry_msg is None:
                    return  # stream ended cleanly (terminal already yielded)
                attrs["outcome"] = "retryable"
                last_exc, last_msg = None, retry_msg
                shed_by = NatsClient._reply_worker_id(retry_msg) or wid
                if shed_by and NatsClient._is_excluded_bounce(retry_msg):
                    if shed_by in excluded:
                        excluded.remove(shed_by)
                elif shed_by and shed_by not in excluded:
                    excluded.append(shed_by)
                if not excluded:
                    headers.pop(p.EXCLUDED_WORKERS_HEADER, None)
            finally:
                self._tenant_track(wid, tenant, -1)
                # broke out (or the caller closed us): close the transport
                # stream so its consumer-gone cancel reaches the worker
                await stream.aclose()
                await self._emit_span(Span(
                    trace_id=trace_id, span_id=span_id, stage="router.attempt",
                    worker_id=self.ident, parent_span_id=parent_span_id,
                    t0=span_t0, t1=time.time(), attrs=attrs,
                ).to_dict())
            if attempt >= retry.max_attempts:
                break
            if not await NatsClient._backoff_within_budget(
                retry.delay_s(attempt), deadline_hdr
            ):
                break
        if last_msg is not None:
            if raise_on_exhausted:
                raise RouterExhausted(
                    "retry budget exhausted: every worker shed this request",
                    envelope=self._envelope_of(last_msg),
                    worker_id=NatsClient._reply_worker_id(last_msg),
                    retry_after_s=retry.delay_s(1),
                )
            yield last_msg
            return
        if last_exc is not None:
            if raise_on_exhausted:
                raise RouterExhausted(
                    f"retry budget exhausted: {last_exc}",
                    retry_after_s=retry.delay_s(1),
                ) from last_exc
            raise last_exc
        raise RouterExhausted(
            "deadline budget exhausted before steered chat stream",
            retry_after_s=retry.delay_s(1),
        )

    @staticmethod
    def _envelope_of(msg: Msg) -> dict | None:
        try:
            env = json.loads(msg.payload or b"null")
        except ValueError:
            return None
        return env if isinstance(env, dict) else None

    @staticmethod
    def _retryable(msg: Msg) -> bool:
        try:
            env = json.loads(msg.payload or b"null")
        except ValueError:
            return False
        return is_retryable_envelope(env)


class RouterProcess:
    """Thin standalone router: forwards ``{prefix}.route.chat_model``
    requests to the steered worker and relays the reply verbatim. Runs in a
    queue group so N router replicas split the forwarding load. Clients that
    can import this package should prefer the in-process ClusterRouter (one
    fewer hop); this process exists for everyone else."""

    def __init__(
        self,
        nc: NatsClient,
        *,
        prefix: str = "lmstudio",
        stale_after_s: float = 5.0,
        prefix_head_chars: int = DEFAULT_HEAD_CHARS,
        chat_timeout_s: float = 120.0,
        retry: RetryPolicy | None = None,
    ):
        self.nc = nc
        self.prefix = prefix
        self.chat_timeout_s = chat_timeout_s
        self.retry = retry or RetryPolicy(max_attempts=3, retry_on_timeout=True)
        self.router = ClusterRouter(
            nc,
            prefix=prefix,
            stale_after_s=stale_after_s,
            prefix_head_chars=prefix_head_chars,
        )
        self._sub = None
        self._inflight: set[asyncio.Task] = set()

    async def start(self) -> "RouterProcess":
        await self.router.start()
        self._sub = await self.nc.subscribe(
            f"{self.prefix}.{ROUTE_SUBJECT}",
            queue="lmstudio-routers",
            cb=self._on_chat,
        )
        log.info(
            "router process forwarding %s.%s -> %s.worker.<id>.chat_model",
            self.prefix, ROUTE_SUBJECT, self.prefix,
        )
        return self

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None
        await self.router.stop()
        for t in list(self._inflight):
            t.cancel()

    async def _on_chat(self, msg: Msg) -> None:
        if not msg.reply:
            return
        task = asyncio.ensure_future(self._forward(msg))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _forward(self, msg: Msg) -> None:
        headers = dict(msg.headers or {})
        remaining = deadline_remaining_s(headers.get(p.DEADLINE_HEADER))
        timeout = self.chat_timeout_s if remaining is None else remaining
        if timeout <= 0:
            return  # the caller already gave up; a reply would be unread
        try:
            resp = await self.router.request_chat(
                msg.payload, timeout=timeout, headers=headers, retry=self.retry
            )
        except (ConnectionClosedError, asyncio.TimeoutError) as e:
            from ..transport.envelope import envelope_error

            await msg.respond(envelope_error(
                f"router: no worker answered, retry on another worker ({e})"
            ))
            return
        await msg.respond(resp.payload, headers=resp.headers)

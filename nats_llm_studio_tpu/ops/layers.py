"""Transformer building blocks: RMSNorm, RoPE, GQA attention, SwiGLU.

Numerics policy (TPU-first): inputs/weights may be bf16 (MXU-native); all
reductions — norms, softmax — run in f32 and cast back. Shapes are static and
batch-major so XLA tiles matmuls onto the MXU without relayout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float = 1e-5, plus_one: bool = False
) -> jax.Array:
    """Root-mean-square layer norm (no mean subtraction, no bias).

    ``plus_one`` applies gemma's ``x * (1 + w)`` convention (the GGUF stores
    w, not 1+w — matching llama.cpp's build_gemma)."""
    xf = x.astype(jnp.float32)
    rrms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    y = (xf * rrms).astype(x.dtype)
    return y * (weight + 1) if plus_one else y * weight


def rope_cos_sin(
    positions: jax.Array, head_dim: int, theta: float = 10000.0
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for rotary position embedding.

    positions: int32 [...]; returns (cos, sin) each [..., head_dim // 2] f32.
    """
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs (x[2i], x[2i+1]) — GGUF/"NEOX" interleaving is handled by
    the weight loader, so here the pairing is (first half, second half).

    x: [B, T, H, D]; cos/sin: [B, T, D/2] (broadcast over heads).
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def gqa_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array,
    scale: float,
) -> jax.Array:
    """Grouped-query attention with f32 softmax.

    q: [B, T, Hq, D]; k, v: [B, S, Hkv, D]; mask: bool [B, T, S] (True = may
    attend). Hq must be a multiple of Hkv (the group size). Returns
    [B, T, Hq, D] in q.dtype.
    """
    b, t, hq, d = q.shape
    s, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, t, hkv, g, d)
    logits = jnp.einsum("bthgd,bshd->bhgts", qg, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    logits = jnp.where(mask[:, None, None, :, :], logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgts,bshd->bthgd", probs.astype(v.dtype), v)
    return out.reshape(b, t, hq, d)


def gqa_attention_hmajor(
    q: jax.Array,
    k,
    v,
    mask: jax.Array,
    scale: float,
) -> jax.Array:
    """gqa_attention over a heads-major cache.

    q: [B, T, Hq, D]; k, v: [B, Hkv, S, D] (the KV-cache layout — per-head
    slabs contiguous so decode DMA streams sequentially) as arrays in
    q.dtype OR int8 ``KVQ`` slabs (ops/kvcache.py). Quantized slabs never
    materialize bf16: the k scales fold onto the scores' S axis after the
    QK dot, and the v scales fold into the probabilities before the PV dot,
    so both MXU reads stream int8 codes. mask: bool [B, T, S]. Returns
    [B, T, Hq, D] in q.dtype.
    """
    from .kvcache import KVQ

    b, t, hq, d = q.shape
    hkv, s = k.shape[1], k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, t, hkv, g, d)
    if isinstance(k, KVQ):
        logits = jnp.einsum(
            "bthgd,bhsd->bhgts", qg, k.q.astype(q.dtype),
            preferred_element_type=jnp.float32,
        ) * k.s[:, :, None, None, :]
    else:
        logits = jnp.einsum(
            "bthgd,bhsd->bhgts", qg, k, preferred_element_type=jnp.float32
        )
    logits = logits * scale
    logits = jnp.where(mask[:, None, None, :, :], logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1)
    if isinstance(v, KVQ):
        pv = (probs * v.s[:, :, None, None, :]).astype(q.dtype)
        out = jnp.einsum("bhgts,bhsd->bthgd", pv, v.q.astype(q.dtype))
    else:
        out = jnp.einsum("bhgts,bhsd->bthgd", probs.astype(v.dtype), v)
    return out.reshape(b, t, hq, d)


def swiglu(x: jax.Array, w_gate, w_up, w_down, act: str = "silu") -> jax.Array:
    """Gated MLP: down( act(x @ gate) * (x @ up) ).

    ``act`` selects the gate nonlinearity — "silu" (llama/granite/mixtral/
    qwen2 SwiGLU) or "gelu" (gemma GeGLU, tanh approximation as ggml uses).
    Weights are [d_in, d_out] row-major (plain ``x @ w``), stored bf16 or
    weight-only int8 (ops.wquant.QTensor).
    """
    from .wquant import mm

    g = mm(x, w_gate)
    gate = jax.nn.silu(g) if act == "silu" else jax.nn.gelu(g, approximate=True)
    return mm(gate * mm(x, w_up), w_down)

"""Paged-attention decode as a Pallas TPU kernel.

vLLM-style PagedAttention for the decode path (SURVEY.md §7 hard part #2,
ROADMAP item 1): one grid cell per (slot, kv-head, pool-block), reading each
slot's block table directly from scalar-prefetch SMEM — the kernel walks
``[NB, L, Hkv, T, D]`` pool storage block-by-block in VMEM, dequantizes int8
KVQ codes per tile, and runs online softmax across blocks. This removes the
two costs of the XLA fallback in serve/batcher.py:

- ``kv_pool_gather_view`` materializes every slot's live window as a dense
  [B, L, Hkv, W, D] copy per decode step (HBM round-trip proportional to
  context, not to the one new token);
- the pow2 window ladder re-jits ``decode_pos_paged`` per (bucket, window)
  pair as contexts grow.

Here the grid's block axis spans the WHOLE table width (static = max_seq/T),
so one compiled program serves every context length: blocks past a slot's
live window skip compute (``pl.when``) and their DMA is elided because the
index map revisits the last live block (the same trick as the causal
revisit-skip in ops/flash_attention.py).

Queries arrive as the slot's GQA group x query-width bundle: decode is
W == 1, speculative verify passes the draft bundle W == k+1 — one kernel,
one compiled program per width. Off-TPU the kernel runs in interpreter mode
(bit-level tests on the CPU backend); ``paged_decode_eligible`` gates the
auto-downshift to the XLA path for shapes Mosaic cannot tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kvcache import is_quantized

_NEG_INF = -1e30


def paged_decode_eligible(
    t: int, d: int, itemsize: int, quantized: bool, hkv: int = 1, tp: int = 1
) -> bool:
    """Whether the Pallas paged-decode kernel can serve this pool layout on
    a real TPU. The block-token extent T is the sublane dim of every K/V
    tile (int8 codes need 32 rows, f32 8, bf16 16), the head_dim D is the
    lane dim (128 multiple), and under tensor parallelism each shard must
    own whole KV heads. Anything else downshifts to the XLA path."""
    sub = 32 if quantized else (8 if itemsize >= 4 else 16)
    return t % sub == 0 and d % 128 == 0 and hkv % tp == 0


def _paged_kernel(
    tbl_ref, pos_ref, layer_ref, q_ref, *refs,
    scale: float, t: int, group: int, w: int, quantized: bool
):
    """One grid step = one (slot, kv-head, POOL-BLOCK). Scratch carries the
    online-softmax state across the block axis; q rows are the slot's GQA
    bundle (row r = query-offset r//group within the W-wide bundle, q-head
    r%group within the group), so the causal frontier is per-row:
    ``key_pos <= pos + r//group``. Rows written this step (write-then-
    attend in models/llama.py) are already in the pool, so the frontier
    includes them. Dead blocks (j past the slot's last live block) skip
    compute; their index maps revisit the last live block so the DMA is
    elided. Slots whose table is unallocated read the null block (id 0) and
    produce finite junk the caller discards — the same contract as the XLA
    gather-view path."""
    if quantized:
        kq_ref, ks_ref, vq_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b, h, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    pos = pos_ref[b]
    last = jnp.minimum(jnp.maximum(pos + w - 1, 0) // t, pl.num_programs(2) - 1)
    rows = q_ref.shape[-2]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j <= last)
    def _compute():
        q = q_ref[0, 0]  # [rows, D]
        if quantized:
            # dequant in f32, cast after: Mosaic's minor-dim [T] -> [T, 1]
            # insertion only lowers for 32-bit vectors (ops/flash_attention.py)
            k = (kq_ref[0, 0, 0].astype(jnp.float32)
                 * ks_ref[0, 0, h].astype(jnp.float32)[:, None]).astype(q.dtype)
            v = (vq_ref[0, 0, 0].astype(jnp.float32)
                 * vs_ref[0, 0, h].astype(jnp.float32)[:, None]).astype(q.dtype)
        else:
            k = k_ref[0, 0, 0].astype(q.dtype)  # [T, D]
            v = v_ref[0, 0, 0].astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [rows, T] f32
        row = jax.lax.broadcasted_iota(jnp.int32, (rows, t), 0)
        key_pos = j * t + jax.lax.broadcasted_iota(jnp.int32, (rows, t), 1)
        s = jnp.where(key_pos <= pos + row // group, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v,
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,      # [B, W, Hq, D] — queries at positions pos..pos+W-1
    k_pool,            # [NBp, L, Hkv, T, D] array, or KVQ codes+scales
    v_pool,
    tbl: jax.Array,    # [B, NB] int32 block ids (NB static = max table width)
    pos: jax.Array,    # [B] int32 — first query position per slot
    layer,             # int32 scalar (a traced lax.scan index is fine)
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Attention for W new tokens per slot against the slot's ENTIRE paged
    history, read block-by-block straight from the pool. Returns
    [B, W, Hq, D] in q.dtype. The caller must have scattered the W new K/V
    rows into the pool first (write-then-attend); the kernel's causal mask
    then covers them exactly.

    The grid block axis is ``tbl.shape[1]`` — STATIC, so the compiled
    program is shared by every context length (dead blocks cost one elided
    grid step each, not a recompile). Per-block work is [rows, T] x [T, D];
    rows = GQA group x W (padded to the sublane multiple)."""
    b, w, hq, d = q.shape
    quantized = is_quantized(k_pool)
    kq = k_pool.q if quantized else k_pool
    hkv, t = kq.shape[2], kq.shape[3]
    group = hq // hkv
    nb = tbl.shape[1]
    rows = group * w
    mult = 8 if q.dtype.itemsize >= 4 else 16
    rows_p = -(-rows // mult) * mult

    # [B, Hkv, group*W, D]: row r = (query offset r//group, group lane
    # r%group) — head-major GQA fold, query offset outermost per group
    qh = q.reshape(b, w, hkv, group, d).transpose(0, 2, 1, 3, 4)
    qh = qh.reshape(b, hkv, rows, d)
    if rows_p != rows:
        qh = jnp.pad(qh, ((0, 0), (0, 0), (0, rows_p - rows), (0, 0)))

    def q_map(bi, hi, ji, tbl_ref, pos_ref, layer_ref):
        return (bi, hi, 0, 0)

    def kv_map(bi, hi, ji, tbl_ref, pos_ref, layer_ref):
        # dead-block revisit-skip: blocks past the slot's live frontier
        # remap to the last live block, eliding their DMA
        last = jnp.minimum(jnp.maximum(pos_ref[bi] + w - 1, 0) // t, nb - 1)
        return (tbl_ref[bi, jnp.minimum(ji, last)], layer_ref[0], hi, 0, 0)

    def s_map(bi, hi, ji, tbl_ref, pos_ref, layer_ref):
        # scale tiles block the whole head axis (a (.., 1, T) block violates
        # Mosaic's sublane rule); the cell's own head is picked in-kernel
        last = jnp.minimum(jnp.maximum(pos_ref[bi] + w - 1, 0) // t, nb - 1)
        return (tbl_ref[bi, jnp.minimum(ji, last)], layer_ref[0], 0, 0)

    if quantized:
        in_specs = [
            pl.BlockSpec((1, 1, rows_p, d), q_map),
            pl.BlockSpec((1, 1, 1, t, d), kv_map),
            pl.BlockSpec((1, 1, hkv, t), s_map),
            pl.BlockSpec((1, 1, 1, t, d), kv_map),
            pl.BlockSpec((1, 1, hkv, t), s_map),
        ]
        operands = (kq, k_pool.s, v_pool.q, v_pool.s)
    else:
        in_specs = [
            pl.BlockSpec((1, 1, rows_p, d), q_map),
            pl.BlockSpec((1, 1, 1, t, d), kv_map),
            pl.BlockSpec((1, 1, 1, t, d), kv_map),
        ]
        operands = (k_pool, v_pool)

    kernel = functools.partial(
        _paged_kernel, scale=scale, t=t, group=group, w=w, quantized=quantized
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hkv, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows_p, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((rows_p, d), jnp.float32),
            pltpu.VMEM((rows_p, 128), jnp.float32),
            pltpu.VMEM((rows_p, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rows_p, d), q.dtype),
        interpret=interpret,
    )(
        tbl.astype(jnp.int32),
        jnp.asarray(pos, jnp.int32).reshape(b),
        jnp.asarray(layer, jnp.int32).reshape(1),
        qh, *operands,
    )
    out = out[:, :, :rows].reshape(b, hkv, w, group, d)
    return out.transpose(0, 2, 1, 3, 4).reshape(b, w, hq, d)


def paged_decode_attention_auto(q, k_pool, v_pool, tbl, pos, layer,
                                scale: float) -> jax.Array:
    """paged_decode_attention with interpreter fallback off-TPU (the CPU
    backend runs the same kernel logic through the Pallas interpreter, so
    the equivalence suite exercises real kernel code paths)."""
    interpret = jax.default_backend() != "tpu"
    return paged_decode_attention(q, k_pool, v_pool, tbl, pos, layer, scale,
                                  interpret=interpret)

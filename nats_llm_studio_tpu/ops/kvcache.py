"""Quantized KV cache: int8 codes + per-(position, head) scales.

Batched decode is HBM-bound (SURVEY.md §7 hard part #5); after int8 weights
(ops/wquant.py) the next largest per-step read is the KV cache — at Llama-3-8B
batch 48 x window 512 it is ~3 GB/step of bf16. Storing K/V as int8 halves
that traffic AND halves cache capacity per slot, which is what lets the batch
grow past the b48 HBM frontier (every extra row is ~free throughput on a
memory-bound step).

Design: symmetric absmax int8 over the head_dim axis — one f32 scale per
(batch, layer, kv-head, position). Dequantization never materializes bf16
slabs: attention folds the scales OUTSIDE the dots, so the MXU reads int8
codes directly (XLA fuses convert(s8->bf16) into the dot operand read, the
same mechanism that makes weight-only int8 pay off):

    scores[b,h,t,s] = (q . codes[s]) * k_scale[s]      (scale on the S axis)
    out[b,t,d]      = sum_s (p[s] * v_scale[s]) codes[s]  (fold into probs)

``KVQ`` is a registered pytree, so a quantized cache flows through jit /
scan / donation / shard_map exactly like the bf16 arrays it replaces; the
scan's leading-axis slicing and dynamic_update_slice run per leaf via the
helpers below.

The reference reaches the same capability through llama.cpp's quantized KV
options inside LM Studio (/root/reference/README.md:3-7); here it is a
first-class device representation selected by ``ModelConfig.kv_quant``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class KVQ:
    """Quantized cache tensor: ``value ~= q * s[..., None]``.

    q: int8 codes, the cache layout [..., S, D]
    s: f32 scales [..., S] (one per position per kv-head)
    """

    q: jax.Array
    s: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def is_quantized(cache) -> bool:
    return isinstance(cache, KVQ)


def kv_zeros(shape, sdtype=jnp.float32) -> KVQ:
    """Zeroed quantized cache (codes 0 x any scale = 0; scales init to 1 so
    never-written positions stay harmless)."""
    return KVQ(q=jnp.zeros(shape, jnp.int8), s=jnp.ones(shape[:-1], sdtype))


def quantize_rows(x: jax.Array) -> KVQ:
    """Symmetric absmax int8 over the last (head_dim) axis."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    s = amax / 127.0
    safe = jnp.where(s == 0, 1.0, s)
    codes = jnp.clip(jnp.round(xf / safe), -127, 127).astype(jnp.int8)
    return KVQ(q=codes, s=safe[..., 0])


def kv_update_slice(cache, upd, idx):
    """dynamic_update_slice on a bf16 cache, or per-leaf on a KVQ (the
    update rows are quantized on write; ``idx`` indexes the CODES layout,
    the scale write drops the trailing D index)."""
    if not is_quantized(cache):
        return jax.lax.dynamic_update_slice(cache, upd.astype(cache.dtype), idx)
    uq = quantize_rows(upd)
    return KVQ(
        q=jax.lax.dynamic_update_slice(cache.q, uq.q, idx),
        s=jax.lax.dynamic_update_slice(cache.s, uq.s, idx[:-1]),
    )


def kv_copy_slice(dst, src, idx):
    """Write an ALREADY-QUANTIZED block (e.g. a prefilled row cache) into a
    larger cache at ``idx`` (codes layout indices)."""
    if not is_quantized(dst):
        return jax.lax.dynamic_update_slice(dst, src.astype(dst.dtype), idx)
    return KVQ(
        q=jax.lax.dynamic_update_slice(dst.q, src.q, idx),
        s=jax.lax.dynamic_update_slice(dst.s, src.s, idx[:-1]),
    )


def kv_slice(cache, idx, sizes):
    """dynamic_slice in the codes layout; per-leaf on a KVQ."""
    if not is_quantized(cache):
        return jax.lax.dynamic_slice(cache, idx, sizes)
    return KVQ(
        q=jax.lax.dynamic_slice(cache.q, idx, sizes),
        s=jax.lax.dynamic_slice(cache.s, idx[:-1], sizes[:-1]),
    )


def kv_nbytes(cache) -> int:
    """Device bytes a cache (bf16 array or KVQ pytree) occupies — the
    prefix cache's HBM accounting unit. 0 for None."""
    if cache is None:
        return 0
    if is_quantized(cache):
        return cache.q.size * cache.q.dtype.itemsize + cache.s.size * cache.s.dtype.itemsize
    return cache.size * cache.dtype.itemsize


def host_kv_nbytes(leaf) -> int:
    """Host bytes of one transferred/demoted KV leaf: an ndarray, a KVQ
    pytree, or the wire-normalized ``(codes, scales)`` tuple
    (serve/kv_transfer.py) — the host-tier budget's accounting unit."""
    if leaf is None:
        return 0
    if isinstance(leaf, tuple):
        q, s = leaf
        return int(q.size) * q.dtype.itemsize + int(s.size) * s.dtype.itemsize
    if is_quantized(leaf):
        return (
            int(leaf.q.size) * leaf.q.dtype.itemsize
            + int(leaf.s.size) * leaf.s.dtype.itemsize
        )
    return int(leaf.size) * leaf.dtype.itemsize


def kv_gather_block(cache, row: int, start: int, length: int):
    """Copy one row's S-axis block [start, start+length) out of a
    [B, L, H, S, D]-layout cache as a fresh [1, L, H, length, D] array (or
    KVQ pair). Static Python slicing — eager, no compiled program — so the
    prefix cache can harvest blocks from a transient row cache before the
    donating finish-admit call consumes it."""
    if not is_quantized(cache):
        return jnp.copy(cache[row : row + 1, :, :, start : start + length, :])
    return KVQ(
        q=jnp.copy(cache.q[row : row + 1, :, :, start : start + length, :]),
        s=jnp.copy(cache.s[row : row + 1, :, :, start : start + length]),
    )


def kv_roll_s(cache, shift, s_axis: int):
    """jnp.roll along the sequence axis (ring alignment / compaction)."""
    if not is_quantized(cache):
        return jnp.roll(cache, shift, axis=s_axis)
    return KVQ(
        q=jnp.roll(cache.q, shift, axis=s_axis),
        s=jnp.roll(cache.s, shift, axis=s_axis),
    )


# -- paged block pool ---------------------------------------------------------
#
# The pool layout is [NB, L, Hkv, T, D] (codes) / [NB, L, Hkv, T] (scales):
# one leading axis of fixed-size blocks of T positions, shared by every live
# slot, the prefix cache, and spec decode.  A slot's logical [B, L, Hkv, S, D]
# cache is the gather of its block table along the leading axis; after a
# decode burst only the touched blocks are scattered back.  Block id 0 is the
# null block (junk pad) — reads from it are masked by the causal mask and
# writes to it are discarded state, so duplicates of id 0 in a scatter are
# benign even though jnp scatter leaves duplicate-index order undefined.


def kv_pool_zeros(shape, dtype=None, quant: bool = False):
    """A zeroed pool leaf-set: bf16/f32 array or KVQ pair, [NB, L, H, T, D]."""
    if quant:
        return kv_zeros(shape)
    return jnp.zeros(shape, dtype if dtype is not None else jnp.bfloat16)


def _pool_take(a, tbl):
    """Gather [B, nb] block ids into a contiguous per-row view.

    a: [NB, L, H, T, ...] pool leaf;  tbl: [B, nb] int32 block ids
    returns [B, L, H, nb*T, ...] — the S axis is the concatenation of the
    row's blocks in table order.
    """
    b, nb = tbl.shape
    v = jnp.take(a, tbl.reshape(-1), axis=0).reshape((b, nb) + a.shape[1:])
    v = jnp.moveaxis(v, 1, 3)  # [B, L, H, nb, T, ...]
    return v.reshape(v.shape[:3] + (nb * a.shape[3],) + a.shape[4:])


def kv_pool_gather_view(pool, tbl):
    """Materialize the [B, L, H, nb*T, D] cache view a block table describes
    (per leaf on KVQ).  The view feeds the existing positional ``forward``
    path unchanged: its S extent IS the attention window."""
    if not is_quantized(pool):
        return _pool_take(pool, tbl)
    return KVQ(q=_pool_take(pool.q, tbl), s=_pool_take(pool.s, tbl))


def _pool_blocks_of_view(v, n_blocks, block_tokens):
    """[B, L, H, nb*T, ...] -> [B, nb, L, H, T, ...] (split S into blocks)."""
    blk = v.reshape(v.shape[:3] + (n_blocks, block_tokens) + v.shape[4:])
    return jnp.moveaxis(blk, 3, 1)


def kv_pool_scatter_view(pool, view, tbl, vb):
    """Write back the touched blocks of a gathered view.

    vb: [B, NTB] indices INTO THE VIEW's block axis (clipped to [0, nb));
    the pool block ids come from ``take_along_axis(tbl, vb)``.  Rows never
    share writable blocks (CoW guarantees it), so the only duplicate ids in
    the flattened scatter are null-block pads — benign junk writes.
    """
    b, nb = tbl.shape
    bids = jnp.take_along_axis(tbl, vb, axis=1).reshape(-1)  # [B*NTB]

    def scat(p, v):
        t = p.shape[3]
        blk = _pool_blocks_of_view(v, nb, t)  # [B, nb, L, H, T, ...]
        idx = vb.reshape(vb.shape + (1,) * (blk.ndim - 2))
        touched = jnp.take_along_axis(blk, idx, axis=1)  # [B, NTB, L, H, T, ...]
        return p.at[bids].set(touched.reshape((-1,) + touched.shape[2:]))

    if not is_quantized(pool):
        return scat(pool, view)
    return KVQ(q=scat(pool.q, view.q), s=scat(pool.s, view.s))


def kv_pool_write_row(pool, row, bids):
    """Write one prefilled row cache into the pool's blocks ``bids``.

    row: [1, L, H, S', D] (already quantized under KVQ); bids: [nblk] int32.
    S' < T writes a partial leading block via DUS; otherwise S' must be a
    multiple of T and every block scatters in one op.  Pad bids with 0 (the
    null block) when the row has fewer real blocks than ``len(bids)``.
    """

    def put(p, r):
        t = p.shape[3]
        s = r.shape[3]
        if s <= t:
            start = (bids[0],) + (jnp.int32(0),) * (p.ndim - 1)
            return jax.lax.dynamic_update_slice(p, r.astype(p.dtype), start)
        if s % t:
            raise ValueError(f"row length {s} not a multiple of block size {t}")
        blk = r[0].reshape(r.shape[1:3] + (s // t, t) + r.shape[4:])
        blk = jnp.moveaxis(blk, 2, 0)  # [nblk, L, H, T, ...]
        return p.at[bids].set(blk.astype(p.dtype))

    if not is_quantized(pool):
        return put(pool, row)
    return KVQ(q=put(pool.q, row.q), s=put(pool.s, row.s))


def kv_pool_write_rows(pool, rows, tbl, pos, layer):
    """Scatter W fresh [Hkv, D] rows per slot straight into the pool at the
    slot's logical positions pos..pos+W-1 (write-then-attend for the Pallas
    paged-decode kernel, ops/paged_attention.py — no gather view exists on
    that path, so fresh rows cannot ride a view scatter-back).

    rows: [B, W, Hkv, D] raw activations (quantized on write under KVQ);
    tbl: [B, NB] block ids; pos: [B] int32; layer: int32 scalar (traced).
    Touched indices past a slot's table resolve to the null block (id 0);
    duplicate junk writes there are benign (pool contract above).
    """
    w = rows.shape[1]
    offs = pos[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]  # [B, W]

    def put(p, r):
        t = p.shape[3]
        vb = jnp.clip(offs // t, 0, tbl.shape[1] - 1)
        bids = jnp.take_along_axis(tbl, vb, axis=1)  # [B, W]
        return p.at[bids, layer, :, offs % t].set(r.astype(p.dtype))

    if not is_quantized(pool):
        return put(pool, rows)
    rq = quantize_rows(rows)
    return KVQ(q=put(pool.q, rq.q), s=put(pool.s, rq.s))


def kv_pool_copy_block(pool, dst, src):
    """Copy-on-write: duplicate block ``src`` into ``dst`` (traced scalars)."""

    def cp(p):
        sizes = (1,) + p.shape[1:]
        zeros = (jnp.int32(0),) * (p.ndim - 1)
        blk = jax.lax.dynamic_slice(p, (src,) + zeros, sizes)
        return jax.lax.dynamic_update_slice(p, blk, (dst,) + zeros)

    if not is_quantized(pool):
        return cp(pool)
    return KVQ(q=cp(pool.q), s=cp(pool.s))


def kv_pool_read_blocks(pool, bids):
    """Gather ``bids`` [nblk] into a [1, L, H, nblk*T, D] row-cache-shaped
    chunk (per leaf on KVQ) — the partial-prefix-hit path uses this to seed
    a transient row cache from cached pool blocks."""

    def rd(a):
        v = jnp.take(a, bids, axis=0)  # [nblk, L, H, T, ...]
        v = jnp.moveaxis(v, 0, 2)  # [L, H, nblk, T, ...]
        v = v.reshape(v.shape[:2] + (v.shape[2] * v.shape[3],) + v.shape[4:])
        return v[None]  # [1, L, H, nblk*T, ...]

    if not is_quantized(pool):
        return rd(pool)
    return KVQ(q=rd(pool.q), s=rd(pool.s))

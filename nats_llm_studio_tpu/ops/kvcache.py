"""Quantized KV cache: int8 codes + per-(position, head) scales.

Batched decode is HBM-bound (SURVEY.md §7 hard part #5); after int8 weights
(ops/wquant.py) the next largest per-step read is the KV cache — at Llama-3-8B
batch 48 x window 512 it is ~3 GB/step of bf16. Storing K/V as int8 halves
that traffic AND halves cache capacity per slot, which is what lets the batch
grow past the b48 HBM frontier (every extra row is ~free throughput on a
memory-bound step).

Design: symmetric absmax int8 over the head_dim axis — one f32 scale per
(batch, layer, kv-head, position). Dequantization never materializes bf16
slabs: attention folds the scales OUTSIDE the dots, so the MXU reads int8
codes directly (XLA fuses convert(s8->bf16) into the dot operand read, the
same mechanism that makes weight-only int8 pay off):

    scores[b,h,t,s] = (q . codes[s]) * k_scale[s]      (scale on the S axis)
    out[b,t,d]      = sum_s (p[s] * v_scale[s]) codes[s]  (fold into probs)

``KVQ`` is a registered pytree, so a quantized cache flows through jit /
scan / donation / shard_map exactly like the bf16 arrays it replaces; the
scan's leading-axis slicing and dynamic_update_slice run per leaf via the
helpers below.

The reference reaches the same capability through llama.cpp's quantized KV
options inside LM Studio (/root/reference/README.md:3-7); here it is a
first-class device representation selected by ``ModelConfig.kv_quant``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class KVQ:
    """Quantized cache tensor: ``value ~= q * s[..., None]``.

    q: int8 codes, the cache layout [..., S, D]
    s: f32 scales [..., S] (one per position per kv-head)
    """

    q: jax.Array
    s: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def is_quantized(cache) -> bool:
    return isinstance(cache, KVQ)


def kv_zeros(shape, sdtype=jnp.float32) -> KVQ:
    """Zeroed quantized cache (codes 0 x any scale = 0; scales init to 1 so
    never-written positions stay harmless)."""
    return KVQ(q=jnp.zeros(shape, jnp.int8), s=jnp.ones(shape[:-1], sdtype))


def quantize_rows(x: jax.Array) -> KVQ:
    """Symmetric absmax int8 over the last (head_dim) axis."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    s = amax / 127.0
    safe = jnp.where(s == 0, 1.0, s)
    codes = jnp.clip(jnp.round(xf / safe), -127, 127).astype(jnp.int8)
    return KVQ(q=codes, s=safe[..., 0])


def kv_update_slice(cache, upd, idx):
    """dynamic_update_slice on a bf16 cache, or per-leaf on a KVQ (the
    update rows are quantized on write; ``idx`` indexes the CODES layout,
    the scale write drops the trailing D index)."""
    if not is_quantized(cache):
        return jax.lax.dynamic_update_slice(cache, upd.astype(cache.dtype), idx)
    uq = quantize_rows(upd)
    return KVQ(
        q=jax.lax.dynamic_update_slice(cache.q, uq.q, idx),
        s=jax.lax.dynamic_update_slice(cache.s, uq.s, idx[:-1]),
    )


def kv_copy_slice(dst, src, idx):
    """Write an ALREADY-QUANTIZED block (e.g. a prefilled row cache) into a
    larger cache at ``idx`` (codes layout indices)."""
    if not is_quantized(dst):
        return jax.lax.dynamic_update_slice(dst, src.astype(dst.dtype), idx)
    return KVQ(
        q=jax.lax.dynamic_update_slice(dst.q, src.q, idx),
        s=jax.lax.dynamic_update_slice(dst.s, src.s, idx[:-1]),
    )


def kv_slice(cache, idx, sizes):
    """dynamic_slice in the codes layout; per-leaf on a KVQ."""
    if not is_quantized(cache):
        return jax.lax.dynamic_slice(cache, idx, sizes)
    return KVQ(
        q=jax.lax.dynamic_slice(cache.q, idx, sizes),
        s=jax.lax.dynamic_slice(cache.s, idx[:-1], sizes[:-1]),
    )


def kv_nbytes(cache) -> int:
    """Device bytes a cache (bf16 array or KVQ pytree) occupies — the
    prefix cache's HBM accounting unit. 0 for None."""
    if cache is None:
        return 0
    if is_quantized(cache):
        return cache.q.size * cache.q.dtype.itemsize + cache.s.size * cache.s.dtype.itemsize
    return cache.size * cache.dtype.itemsize


def kv_gather_block(cache, row: int, start: int, length: int):
    """Copy one row's S-axis block [start, start+length) out of a
    [B, L, H, S, D]-layout cache as a fresh [1, L, H, length, D] array (or
    KVQ pair). Static Python slicing — eager, no compiled program — so the
    prefix cache can harvest blocks from a transient row cache before the
    donating finish-admit call consumes it."""
    if not is_quantized(cache):
        return jnp.copy(cache[row : row + 1, :, :, start : start + length, :])
    return KVQ(
        q=jnp.copy(cache.q[row : row + 1, :, :, start : start + length, :]),
        s=jnp.copy(cache.s[row : row + 1, :, :, start : start + length]),
    )


def kv_roll_s(cache, shift, s_axis: int):
    """jnp.roll along the sequence axis (ring alignment / compaction)."""
    if not is_quantized(cache):
        return jnp.roll(cache, shift, axis=s_axis)
    return KVQ(
        q=jnp.roll(cache.q, shift, axis=s_axis),
        s=jnp.roll(cache.s, shift, axis=s_axis),
    )

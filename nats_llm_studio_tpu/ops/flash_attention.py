"""Causal flash attention (prefill) as a Pallas TPU kernel.

SURVEY.md §7 hard part #1: prefill TTFT needs attention that never
materializes the [T, S] score matrix in HBM. Online-softmax accumulation over
key tiles keeps everything in VMEM; one grid cell per (batch, q-head,
query-tile), with GQA folding (q head h reads kv head h // group).

Used for prefill only (start_pos == 0, keys are the just-computed [B, T]
block); decode keeps the fused XLA path, which is already memory-bound on
weights, not attention. Falls back to interpreter mode off-TPU so tests run
on the CPU backend (SURVEY.md §4.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, block_q: int, block_k: int):
    # refs are [1, 1, T, D] blocks of the [B, H, T, D] layout (T and D in the
    # last two positions to satisfy Mosaic's (8, 128) tiling rule)
    qt = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [BQ, D]
    d = q.shape[-1]
    n_kv = k_ref.shape[2]

    q_pos = qt * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kt, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(kt * block_k, block_k), :].astype(jnp.float32)  # [BK, D]
        v = v_ref[0, 0, pl.ds(kt * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        k_pos = kt * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    # causal: key tiles strictly after this query tile are fully masked
    n_tiles = jnp.minimum((qt + 1) * block_q + block_k - 1, n_kv + block_k - 1) // block_k
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_tiles, body, (acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jax.Array,  # [B, T, Hq, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,
    scale: float,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Causal self-attention over a fresh [B, T] block. Returns q.dtype."""
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    # clamp to the sequence, then round up to the dtype's native sublane
    # tile (f32: 8 rows, bf16/f16: 16): Mosaic rejects ragged tile heights
    # on real TPU (invisible in CPU interpret-mode tests)
    mult = 8 if q.dtype.itemsize >= 4 else 16
    block_q = -(-min(block_q, max(t, mult)) // mult) * mult
    block_k = -(-min(block_k, max(t, mult)) // mult) * mult

    pad_q = (-t) % block_q
    pad_k = (-t) % block_k
    if pad_q or pad_k:
        # padded keys sit at positions >= t, which the causal mask removes
        # for every real query; padded query rows are sliced away below
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    tq, tk = q.shape[1], k.shape[1]

    # [B, H, T, D] layout: T/D in the trailing positions for Mosaic tiling
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)

    grid = (b, hq, tq // block_q)
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, tq, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return out.transpose(0, 2, 1, 3)[:, :t]


def flash_attention_auto(q, k, v, scale: float) -> jax.Array:
    """flash_attention with interpreter fallback off-TPU (tests on the CPU
    backend run the same kernel logic through the Pallas interpreter)."""
    interpret = jax.default_backend() != "tpu"
    return flash_attention(q, k, v, scale, interpret=interpret)


# ---------------------------------------------------------------------------
# decode (single-token) attention over the KV cache
# ---------------------------------------------------------------------------


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *, scale: float, block_k: int):
    """One (batch, kv-head) cell: the G grouped q-heads attend over the
    cache prefix [0, pos]. Online softmax over key tiles; everything f32 in
    VMEM."""
    pos = pos_ref[pl.program_id(0)]  # [B] vector in SMEM
    g, d = q_ref.shape[2], q_ref.shape[3]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [G, D]
    n_kv = k_ref.shape[2]

    def body(kt, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(kt * block_k, block_k), :].astype(jnp.float32)  # [BK, D]
        v = v_ref[0, 0, pl.ds(kt * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [G, BK]
        k_pos = kt * block_k + jax.lax.broadcasted_iota(jnp.int32, (g, block_k), 1)
        s = jnp.where(k_pos <= pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    # only tiles covering [0, pos] — dynamic trip count skips dead compute
    n_tiles = jnp.minimum(pos // block_k + 1, n_kv // block_k)
    acc0 = jnp.zeros((g, d), jnp.float32)
    m0 = jnp.full((g,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((g,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_tiles, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "block_k", "interpret"))
def flash_decode(
    q: jax.Array,  # [B, Hq, D] — the single new token's queries
    k_cache: jax.Array,  # [B, Hkv, S, D] (heads-major cache layout)
    v_cache: jax.Array,
    pos: jax.Array,  # int32 [B] — attend to cache[:pos+1]
    scale: float,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention: reads each (batch, kv head) cache slab exactly once
    via sequential DMA — replaces the XLA einsum path whose tiny per-head
    matmuls left cache reads ~6x below HBM speed. Returns [B, Hq, D]."""
    b, hq, d = q.shape
    hkv, s_max = k_cache.shape[1], k_cache.shape[2]
    g = hq // hkv
    block_k = min(block_k, s_max)
    # group q rows by kv head; pad the group dim to the f32 sublane tile
    gp = max(8, g)
    q4 = q.reshape(b, hkv, g, d)
    if gp != g:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, block_k=block_k),
        grid=(b, hkv),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # pos [B]
            pl.BlockSpec((1, 1, gp, d), lambda bi, hi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s_max, d), lambda bi, hi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s_max, d), lambda bi, hi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, d), lambda bi, hi: (bi, hi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
        interpret=interpret,
    )(pos.astype(jnp.int32), q4, k_cache, v_cache)
    return out[:, :, :g, :].reshape(b, hq, d)


def flash_decode_auto(q, k_cache, v_cache, pos, scale: float) -> jax.Array:
    interpret = jax.default_backend() != "tpu"
    return flash_decode(q, k_cache, v_cache, pos, scale, interpret=interpret)


# ---------------------------------------------------------------------------
# decode attention over the FULL layer-stacked cache (the serving hot path)
# ---------------------------------------------------------------------------


def _pick_block_k(s_max: int) -> int | None:
    # 256 keys x 8 kv heads x 64 dims x bf16 = 256 KB per cache per grid
    # step: big enough to amortize the ~0.5 us step overhead, small enough
    # for fine dead-tile skipping and the 16 MB scoped-VMEM budget
    for bk in (256, 512, 128):
        if s_max % bk == 0:
            return bk
    return s_max if s_max <= 512 and s_max % 16 == 0 else None


def _decode_cache_kernel(
    l_ref, pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, s_ref,
    *, scale: float, block_k: int, gp: int
):
    """One grid step = one (batch, key-tile) covering ALL kv heads — the
    per-step DMA is Hkv*block_k*D*2 bytes per cache, large enough that the
    ~0.5 us grid-step overhead is amortized. Scores are one 2D dot with the
    heads folded into rows/cols; a block-diagonal head mask (fused with the
    position mask) zeroes cross-head terms, so the combine dot can sum over
    every column. Online-softmax state persists in VMEM scratch across the
    key-tile axis; dead tiles (beyond the row's live prefix) skip compute
    (pl.when) and DMA (their index_map revisits the previous tile, which the
    Pallas pipeline elides)."""
    bi, kt = pl.program_id(0), pl.program_id(1)
    pos = pos_ref[bi]
    h, d = q_ref.shape[1], q_ref.shape[3]
    rows, cols = h * gp, h * block_k

    @pl.when(kt == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        s_ref[...] = jnp.zeros_like(s_ref)

    @pl.when(kt * block_k <= pos)
    def _compute():
        q = q_ref[0].reshape(rows, d).astype(jnp.float32) * scale
        k = k_ref[0, 0].reshape(cols, d).astype(jnp.float32)
        v = v_ref[0, 0].reshape(cols, d).astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rows, cols]
        row_h = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) // gp
        col_i = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
        col_h = col_i // block_k
        k_pos = kt * block_k + (col_i - col_h * block_k)
        s = jnp.where((row_h == col_h) & (k_pos <= pos), s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        s_new = s_ref[:, 0] * corr + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        s_ref[...] = jnp.broadcast_to(s_new[:, None], s_ref.shape)

    @pl.when(kt == pl.num_programs(1) - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(s_ref[:, :1], 1e-30)
        o_ref[0] = out.reshape(h, gp, d).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def flash_decode_cache(
    q: jax.Array,  # [B, Hq, D] — the new token's queries
    k_all: jax.Array,  # [B, L, Hkv, S, D] — the FULL layer-stacked cache
    v_all: jax.Array,
    layer: jax.Array,  # int32 scalar — which layer's slab to read
    pos: jax.Array,  # int32 [B] — attend to cache[:pos+1] per row
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention reading the cache in place.

    The layer scan carries the full cache; slicing out layer ``l`` under XLA
    materializes a copy (read+write of the whole slab) before attention even
    starts. Here the kernel indexes [b, l, tile] directly via
    scalar-prefetched index maps, so per-step HBM traffic is exactly the live
    prefix of each row — no copies, no dead-tile reads. Returns [B, Hq, D].
    """
    b, hq, d = q.shape
    hkv, s_max = k_all.shape[2], k_all.shape[3]
    g = hq // hkv
    block_k = _pick_block_k(s_max)
    assert block_k is not None, f"s_max={s_max} unsupported; caller must fall back"
    gp = max(8, g)
    q4 = q.reshape(b, hkv, g, d).astype(jnp.float32)
    if gp != g:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    def q_map(bi, kt, l_ref, pos_ref):
        return (bi, 0, 0, 0)

    def kv_map(bi, kt, l_ref, pos_ref):
        live = pos_ref[bi] // block_k
        return (bi, l_ref[0], 0, jnp.minimum(kt, live), 0)

    def o_map(bi, kt, l_ref, pos_ref):
        return (bi, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, s_max // block_k),
        in_specs=[
            pl.BlockSpec((1, hkv, gp, d), q_map),
            pl.BlockSpec((1, 1, hkv, block_k, d), kv_map),
            pl.BlockSpec((1, 1, hkv, block_k, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, hkv, gp, d), o_map),
        scratch_shapes=[
            pltpu.VMEM((hkv * gp, d), jnp.float32),
            pltpu.VMEM((hkv * gp, 128), jnp.float32),
            pltpu.VMEM((hkv * gp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_cache_kernel, scale=scale, block_k=block_k, gp=gp
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
        interpret=interpret,
    )(
        jnp.reshape(layer, (1,)).astype(jnp.int32),
        pos.astype(jnp.int32),
        q4,
        k_all,
        v_all,
    )
    return out[:, :, :g, :].reshape(b, hq, d)


def flash_decode_cache_auto(q, k_all, v_all, layer, pos, scale: float) -> jax.Array:
    interpret = jax.default_backend() != "tpu"
    return flash_decode_cache(q, k_all, v_all, layer, pos, scale, interpret=interpret)


def decode_cache_supported(s_max: int) -> bool:
    return _pick_block_k(s_max) is not None

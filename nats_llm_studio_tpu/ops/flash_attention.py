"""Causal flash attention (prefill) as a Pallas TPU kernel.

SURVEY.md §7 hard part #1: prefill TTFT needs attention that never
materializes the [T, S] score matrix in HBM. Online-softmax accumulation over
key tiles keeps everything in VMEM; one grid cell per (batch, q-head,
query-tile), with GQA folding (q head h reads kv head h // group).

Used for prefill only (start_pos == 0, keys are the just-computed [B, T]
block); decode keeps the fused XLA path, which is already memory-bound on
weights, not attention. Falls back to interpreter mode off-TPU so tests run
on the CPU backend (SURVEY.md §4.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 512x1024 tiles: at 16k the 128x128 grid is 524k cells whose per-cell
# overhead dominated (measured ~350 -> ~230 ms/layer just from fewer cells);
# VMEM per cell stays ~4.5 MB. Short prefills clamp the blocks to T below.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30


def chunk_block_multiple(quantized: bool, itemsize: int = 2) -> int:
    """Sublane multiple Mosaic requires of any cache-window extent the chunk
    kernels tile over: int8 codes need 32 rows, f32 8, bf16/f16 16. Both the
    chunk-continuation gate in models/llama.py and the paged-KV block-size
    clamp in serve/batcher.py use this floor, so a pool block is always a
    whole number of kernel tiles."""
    if quantized:
        return 32
    return 8 if itemsize >= 4 else 16


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_k: int
):
    """One grid step = one (batch, q-head, q-tile, K-TILE). K/V arrive one
    [BK, D] tile per step — VMEM stays O(block) at any sequence length (the
    whole-K-per-cell layout capped prefill at ~8k tokens). Online-softmax
    state persists in scratch across the key-tile axis; causally-dead tiles
    skip compute (pl.when) and DMA (their index map revisits the previous
    tile, which the pipeline elides)."""
    qt, kt = pl.program_id(2), pl.program_id(3)
    d = q_ref.shape[-1]

    @pl.when(kt == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(kt * block_k <= (qt + 1) * block_q - 1)
    def _compute():
        # dots run in the INPUT dtype (bf16) with f32 accumulation — casting
        # operands to f32 first would route them through the ~4x slower f32
        # MXU path (measured: the whole 16k prefill dropped from ~7 s to
        # ~3 s when these dots went bf16). Softmax statistics stay f32.
        q = q_ref[0, 0]  # [BQ, D]
        k = k_ref[0, 0]  # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK] f32
        q_pos = qt * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kt * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kt == pl.num_programs(3) - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jax.Array,  # [B, T, Hq, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,
    scale: float,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Causal self-attention over a fresh [B, T] block. Returns q.dtype."""
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    # clamp to the sequence, then round up to the dtype's native sublane
    # tile (f32: 8 rows, bf16/f16: 16): Mosaic rejects ragged tile heights
    # on real TPU (invisible in CPU interpret-mode tests)
    mult = 8 if q.dtype.itemsize >= 4 else 16
    block_q = -(-min(block_q, max(t, mult)) // mult) * mult
    block_k = -(-min(block_k, max(t, mult)) // mult) * mult

    pad_q = (-t) % block_q
    pad_k = (-t) % block_k
    if pad_q or pad_k:
        # padded keys sit at positions >= t, which the causal mask removes
        # for every real query; padded query rows are sliced away below
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    tq, tk = q.shape[1], k.shape[1]

    # [B, H, T, D] layout: T/D in the trailing positions for Mosaic tiling
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)

    def kv_map(bi, hi, qi, ki, g=group):
        # causal revisit-skip: tiles past this q-tile's last live key tile
        # remap to it, so their DMA is elided by the pipeline
        live = ((qi + 1) * block_q - 1) // block_k
        return (bi, hi // g, jnp.minimum(ki, live), 0)

    grid = (b, hq, tq // block_q, tk // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, hq, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return out.transpose(0, 2, 1, 3)[:, :t]


def flash_attention_auto(q, k, v, scale: float) -> jax.Array:
    """flash_attention with interpreter fallback off-TPU (tests on the CPU
    backend run the same kernel logic through the Pallas interpreter)."""
    interpret = jax.default_backend() != "tpu"
    return flash_attention(q, k, v, scale, interpret=interpret)


# ---------------------------------------------------------------------------
# cache-backed chunk attention (chunked prefill continuation)
# ---------------------------------------------------------------------------


def _flash_chunk_kernel(
    start_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_k: int
):
    """One grid step = one (batch, q-head, q-tile, K-TILE) of CHUNK
    CONTINUATION attention: queries sit at positions [start, start+C) while
    keys/values are the cache slab [0, KW) — history below ``start`` fully
    visible, the chunk itself causal, anything above masked. ``start`` is a
    scalar-prefetch operand, so one compiled program serves every chunk
    offset (a static start would recompile the 8B program per chunk)."""
    qt, kt = pl.program_id(2), pl.program_id(3)
    start = start_ref[0]

    @pl.when(kt == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(kt * block_k <= start + (qt + 1) * block_q - 1)
    def _compute():
        q = q_ref[0, 0]  # [BQ, D]
        k = k_ref[0, 0]  # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        q_pos = start + qt * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kt * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kt == pl.num_programs(3) - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret"))
def flash_attention_chunk(
    q: jax.Array,  # [B, C, Hq, D] — queries at positions [start, start+C)
    k: jax.Array,  # [B, Hkv, KW, D] — cache slab (heads-major, as stored)
    v: jax.Array,
    scale: float,
    start: jax.Array,  # int32 scalar, shared by every row (uniform starts)
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Chunked-prefill continuation attention without the [C, KW] f32 score
    matrix (the dense fallback materializes ~1 GB/layer at a 4.6k window —
    most of a chunk's wall time). Keys at positions >= start+C (junk beyond
    the written prefix) are masked by causality since every query position
    is < start+C. Rows whose prompt is shorter than ``start`` (pad chunks
    of a batched group admit) produce finite junk that the caller's
    end-chunk logit select discards."""
    b, c, hq, d = q.shape
    hkv, kw = k.shape[1], k.shape[2]
    group = hq // hkv
    mult = 8 if q.dtype.itemsize >= 4 else 16
    block_q = -(-min(block_q, max(c, mult)) // mult) * mult
    pad_q = (-c) % block_q
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    while kw % block_k and block_k > mult:
        block_k //= 2
    if kw % block_k:
        raise ValueError(f"cache window {kw} not tileable by {block_k}")
    qh = q.transpose(0, 2, 1, 3)  # [B, Hq, Cp, D]

    def q_map(bi, hi, qi, ki, start_ref):
        return (bi, hi, qi, 0)

    def kv_map(bi, hi, qi, ki, start_ref, g=group):
        # causal revisit-skip: tiles past the last live key tile for this
        # q tile remap to it (their DMA is elided by the pipeline)
        live = (start_ref[0] + (qi + 1) * block_q - 1) // block_k
        return (bi, hi // g, jnp.minimum(ki, live), 0)

    grid = (b, hq, qh.shape[2] // block_q, kw // block_k)
    kernel = functools.partial(
        _flash_chunk_kernel, scale=scale, block_q=block_q, block_k=block_k
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qh.shape, q.dtype),
        interpret=interpret,
    )(jnp.reshape(start, (1,)).astype(jnp.int32), qh, k, v)
    return out.transpose(0, 2, 1, 3)[:, :c]


def flash_attention_chunk_auto(q, k, v, scale: float, start) -> jax.Array:
    interpret = jax.default_backend() != "tpu"
    return flash_attention_chunk(q, k, v, scale, start, interpret=interpret)


# ---------------------------------------------------------------------------
# cache-backed chunk attention over the QUANTIZED cache (int8 KV serving)
# ---------------------------------------------------------------------------


def _flash_chunk_kvq_kernel(
    start_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref,
    acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_k: int, group: int
):
    """flash_attention_chunk over int8 KV tiles: codes dequantize per tile
    IN VMEM (k = kq * ks[:, None] in the compute dtype), so the int8 slab
    streams from HBM at half the bf16 bytes and the full-window dequant
    transient the XLA path materializes per layer per chunk (the r4 O(T^2)
    HBM tail at 16k) never exists.

    Scale tiles arrive as [1, Hkv, block_k] (ALL kv heads per cell —
    Mosaic requires the block's sublane dim to divide by 8 or equal the
    array dim, which a single-head (1, 1, bk) block violates); the cell's
    own head is selected here. The extra scale DMA is Hkv x 4 bytes/slot,
    noise next to the [bk, D] codes."""
    qt, kt = pl.program_id(2), pl.program_id(3)
    h_kv = pl.program_id(1) // group
    start = start_ref[0]

    @pl.when(kt == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(kt * block_k <= start + (qt + 1) * block_q - 1)
    def _compute():
        q = q_ref[0, 0]  # [BQ, D] (bf16)
        # dequant in f32, cast after: Mosaic only supports the [BK] -> [BK, 1]
        # minor-dim insertion for 32-bit vectors (bf16 broadcast here fails
        # to lower); the cast lands the MXU dot back in bf16
        k = (kq_ref[0, 0].astype(jnp.float32)
             * ks_ref[0, h_kv].astype(jnp.float32)[:, None]).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        q_pos = start + qt * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kt * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        v = (vq_ref[0, 0].astype(jnp.float32)
             * vs_ref[0, h_kv].astype(jnp.float32)[:, None]).astype(q.dtype)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v,
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kt == pl.num_programs(3) - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret"))
def flash_attention_chunk_kvq(
    q: jax.Array,   # [B, C, Hq, D] — queries at positions [start, start+C)
    kq: jax.Array,  # [B, Hkv, KW, D] int8 codes (cache slab, heads-major)
    ks: jax.Array,  # [B, Hkv, KW] per-slot scales
    vq: jax.Array,
    vs: jax.Array,
    scale: float,
    start: jax.Array,  # int32 scalar, shared by every row (uniform starts)
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Chunk-continuation attention reading the int8 KV cache directly.
    Same math/masking as flash_attention_chunk; the dequantized k/v exist
    only tile-by-tile in VMEM. int8 tiles need a 32-row sublane multiple,
    so block_k stays a multiple of 32 (KW is a pow2 window >= 512 in
    serving, so the halving loop never goes below it)."""
    b, c, hq, d = q.shape
    hkv, kw = kq.shape[1], kq.shape[2]
    group = hq // hkv
    mult = 8 if q.dtype.itemsize >= 4 else 16
    block_q = -(-min(block_q, max(c, mult)) // mult) * mult
    pad_q = (-c) % block_q
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    while kw % block_k and block_k > 32:
        block_k //= 2
    if kw % block_k:
        raise ValueError(f"cache window {kw} not tileable by int8 block {block_k}")
    qh = q.transpose(0, 2, 1, 3)  # [B, Hq, Cp, D]

    def q_map(bi, hi, qi, ki, start_ref):
        return (bi, hi, qi, 0)

    def kv_map(bi, hi, qi, ki, start_ref, g=group):
        live = (start_ref[0] + (qi + 1) * block_q - 1) // block_k
        return (bi, hi // g, jnp.minimum(ki, live), 0)

    def s_map(bi, hi, qi, ki, start_ref):
        # scale tiles ride the same causal revisit-skip as their codes;
        # the head axis is blocked whole (see kernel docstring)
        live = (start_ref[0] + (qi + 1) * block_q - 1) // block_k
        return (bi, 0, jnp.minimum(ki, live))

    grid = (b, hq, qh.shape[2] // block_q, kw // block_k)
    kernel = functools.partial(
        _flash_chunk_kvq_kernel, scale=scale, block_q=block_q, block_k=block_k,
        group=group,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, hkv, block_k), s_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, hkv, block_k), s_map),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qh.shape, q.dtype),
        interpret=interpret,
    )(jnp.reshape(start, (1,)).astype(jnp.int32), qh, kq, ks, vq, vs)
    return out.transpose(0, 2, 1, 3)[:, :c]


def flash_attention_chunk_kvq_auto(q, kq, ks, vq, vs, scale: float, start) -> jax.Array:
    interpret = jax.default_backend() != "tpu"
    return flash_attention_chunk_kvq(q, kq, ks, vq, vs, scale, start,
                                     interpret=interpret)

"""Causal flash attention (prefill) as a Pallas TPU kernel.

SURVEY.md §7 hard part #1: prefill TTFT needs attention that never
materializes the [T, S] score matrix in HBM. Online-softmax accumulation over
key tiles keeps everything in VMEM; one grid cell per (batch, q-head,
query-tile), with GQA folding (q head h reads kv head h // group).

Used for prefill only (start_pos == 0, keys are the just-computed [B, T]
block); decode keeps the fused XLA path, which is already memory-bound on
weights, not attention. Falls back to interpreter mode off-TPU so tests run
on the CPU backend (SURVEY.md §4.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, block_q: int, block_k: int):
    # refs are [1, 1, T, D] blocks of the [B, H, T, D] layout (T and D in the
    # last two positions to satisfy Mosaic's (8, 128) tiling rule)
    qt = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [BQ, D]
    d = q.shape[-1]
    n_kv = k_ref.shape[2]

    q_pos = qt * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kt, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(kt * block_k, block_k), :].astype(jnp.float32)  # [BK, D]
        v = v_ref[0, 0, pl.ds(kt * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        k_pos = kt * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    # causal: key tiles strictly after this query tile are fully masked
    n_tiles = jnp.minimum((qt + 1) * block_q + block_k - 1, n_kv + block_k - 1) // block_k
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_tiles, body, (acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jax.Array,  # [B, T, Hq, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,
    scale: float,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Causal self-attention over a fresh [B, T] block. Returns q.dtype."""
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    # clamp to the sequence, then round up to the dtype's native sublane
    # tile (f32: 8 rows, bf16/f16: 16): Mosaic rejects ragged tile heights
    # on real TPU (invisible in CPU interpret-mode tests)
    mult = 8 if q.dtype.itemsize >= 4 else 16
    block_q = -(-min(block_q, max(t, mult)) // mult) * mult
    block_k = -(-min(block_k, max(t, mult)) // mult) * mult

    pad_q = (-t) % block_q
    pad_k = (-t) % block_k
    if pad_q or pad_k:
        # padded keys sit at positions >= t, which the causal mask removes
        # for every real query; padded query rows are sliced away below
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    tq, tk = q.shape[1], k.shape[1]

    # [B, H, T, D] layout: T/D in the trailing positions for Mosaic tiling
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)

    grid = (b, hq, tq // block_q)
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, tq, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return out.transpose(0, 2, 1, 3)[:, :t]


def flash_attention_auto(q, k, v, scale: float) -> jax.Array:
    """flash_attention with interpreter fallback off-TPU (tests on the CPU
    backend run the same kernel logic through the Pallas interpreter)."""
    interpret = jax.default_backend() != "tpu"
    return flash_attention(q, k, v, scale, interpret=interpret)

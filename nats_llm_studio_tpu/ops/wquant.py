"""Weight-only int8 and grouped int4 quantization for serving.

Decode throughput is bound by streaming the weights from HBM once per step
(SURVEY.md §7 hard part #5); storing matmul weights as int8 with a
per-output-channel scale halves that traffic vs bf16 and is what makes
Llama-3-70B fit on a v5e-8 (BASELINE.md config 3: 8 x 16 GB HBM cannot hold
140 GB of bf16). The reference gets the same capability from llama.cpp's
quantized GGUF kernels inside LM Studio (/root/reference/README.md:3-7);
here it is a first-class device representation, not a file format.

``QTensor`` is a pytree (int8 codes + broadcastable scale), so quantized
params flow through jit / lax.scan / shard_map unchanged — scan slices the
leading [L] axis off both leaves. ``mm``/``q_einsum`` dequantize on the fly:
XLA fuses convert(s8->bf16)*scale into the matmul's operand read, so HBM
moves int8 bytes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclass
class QTensor:
    """Symmetric per-output-channel int8 weight: ``w ≈ q * s``.

    q: int8, the original weight shape [..., in, out]
    s: f32, [..., 1, out] — broadcastable over the contraction axis
    """

    q: jax.Array
    s: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.s).astype(dtype)


@jax.tree_util.register_dataclass
@dataclass
class QTensor4:
    """Asymmetric grouped int4 weight: ``w ≈ (q - z) * s`` per group.

    AWQ/GPTQ-style storage: the contraction axis is cut into groups of
    ``group`` rows, each with its own scale and zero point, and two 4-bit
    codes pack into one byte (even row in the low nibble, odd in the high).

    q: uint8, [..., in/2, out] — packed nibble pairs along the contraction axis
    s: f32,   [..., in/group, out] — per-group scale
    z: f32,   [..., in/group, out] — per-group zero point, in code units
    group: static metadata (rows per group), not a pytree leaf
    """

    q: jax.Array
    s: jax.Array
    z: jax.Array
    group: int = field(metadata=dict(static=True), default=32)

    @property
    def shape(self):
        # logical (unpacked) weight shape
        return (*self.q.shape[:-2], self.q.shape[-2] * 2, self.q.shape[-1])

    @property
    def ndim(self):
        return self.q.ndim

    def codes(self) -> jax.Array:
        """Unpack nibbles back to int32 codes in [0, 15], shape [..., in, out]."""
        lo = (self.q & 0x0F).astype(jnp.int32)
        hi = (self.q >> 4).astype(jnp.int32)
        # rows 2i came from the low nibble, 2i+1 from the high nibble
        both = jnp.stack([lo, hi], axis=-2)  # [..., in/2, 2, out]
        return both.reshape(self.shape)

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        c = self.codes().astype(jnp.float32)
        s = jnp.repeat(self.s, self.group, axis=-2)
        z = jnp.repeat(self.z, self.group, axis=-2)
        return ((c - z) * s).astype(dtype)


def effective_group(in_dim: int, group: int) -> int:
    """Largest even group <= ``group`` that divides ``in_dim``.

    Tiny test models (d_model 64) cannot honor the production default of
    128, so the group degrades instead of erroring; 2 always divides an
    even contraction axis (packing already requires in_dim % 2 == 0).
    """
    g = max(2, min(group, in_dim))
    while in_dim % g or g % 2:
        g -= 1
        if g < 2:
            raise ValueError(f"no valid int4 group for in_dim={in_dim}")
    return g


def quantize_weight4(w: np.ndarray | jax.Array, group: int = 32,
                     device: bool = False) -> QTensor4:
    """Asymmetric min/max int4 over groups of the contraction axis.

    Host-side NumPy by default (streaming loaders quantize one tensor at a
    time); ``device=True`` runs the same math in jnp.
    """
    xp = jnp if device else np
    w = w.astype(xp.float32) if device else np.asarray(w, dtype=np.float32)
    in_dim = w.shape[-2]
    if in_dim % 2:
        raise ValueError(f"int4 packing needs an even contraction axis, got {in_dim}")
    g = effective_group(in_dim, group)
    ng = in_dim // g
    wg = w.reshape(*w.shape[:-2], ng, g, w.shape[-1])
    wmin = xp.min(wg, axis=-2)
    wmax = xp.max(wg, axis=-2)
    s = (wmax - wmin) / 15.0
    safe = xp.where(s == 0, 1.0, s)
    z = xp.clip(xp.round(-wmin / safe), 0.0, 15.0)
    q = xp.clip(xp.round(wg / safe[..., None, :]) + z[..., None, :], 0.0, 15.0)
    q = q.reshape(w.shape).astype(xp.uint8)
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    packed = (lo | (hi << 4)).astype(xp.uint8)
    return QTensor4(q=packed, s=safe.astype(xp.float32),
                    z=z.astype(xp.float32), group=g)


def _mm4(x: jax.Array, w: QTensor4) -> jax.Array:
    """Fused grouped dequant-matmul: HBM streams packed int4 bytes.

    Expands ``x @ ((q - z) * s)`` into per-group partial dots so the codes
    feed the matmul directly (no [in, out] float weight is materialized):
    ``sum_g s_g * (x_g @ q_g) - sum_g (s_g * z_g) * sum(x_g)``.
    """
    in_dim, out = w.shape[-2], w.shape[-1]
    ng = in_dim // w.group
    xr = x.reshape(*x.shape[:-1], ng, w.group)
    cg = w.codes().astype(x.dtype).reshape(ng, w.group, out)
    t = jnp.einsum("...ng,ngo->...no", xr, cg)
    y = jnp.sum(t * w.s.astype(x.dtype), axis=-2)
    corr = jnp.einsum("...n,no->...o", xr.sum(axis=-1),
                      (w.s * w.z).astype(x.dtype))
    return y - corr


def quantize_weight(w: np.ndarray | jax.Array, device: bool = False) -> QTensor:
    """Symmetric absmax int8 over the contraction (second-to-last) axis.

    Host-side NumPy by default so the streaming 70B loader can quantize one
    tensor at a time without touching the device; ``device=True`` runs the
    same math in jnp for already-placed arrays.
    """
    xp = jnp if device else np
    w = w if device else np.asarray(w, dtype=np.float32)
    amax = xp.max(xp.abs(w.astype(xp.float32) if device else w), axis=-2, keepdims=True)
    s = amax / 127.0
    safe = xp.where(s == 0, 1.0, s)
    q = xp.clip(xp.round(w / safe), -127, 127).astype(xp.int8)
    return QTensor(q=q, s=safe.astype(xp.float32))


def mm(x: jax.Array, w) -> jax.Array:
    """``x @ w`` for plain arrays, QTensor, or QTensor4 (dequant-in-matmul)."""
    if isinstance(w, QTensor):
        y = jnp.matmul(x, w.q.astype(x.dtype))
        return y * w.s.astype(x.dtype)
    if isinstance(w, QTensor4):
        if w.q.ndim == 2:
            return _mm4(x, w)
        # leading batch axes (unsliced stacks): plain dequant matmul — XLA
        # still fuses the unpack into the operand read
        return jnp.matmul(x, w.dequant(x.dtype))
    return x @ w


def q_einsum(spec: str, x: jax.Array, w) -> jax.Array:
    """``einsum(spec, x, w)`` with QTensor/QTensor4 support.

    Requires the weight's contraction axis to be its second-to-last (where
    the scale has extent 1). The scale is permuted/broadcast to the output
    label order, so any output layout works ("btd,edf->btef",
    "ecd,edf->ecf", ...).
    """
    if isinstance(w, QTensor4):
        # grouped scales don't broadcast over arbitrary einsum layouts; the
        # unpack+dequant chain is elementwise so it fuses into the einsum
        return jnp.einsum(spec, x, w.dequant(x.dtype))
    if not isinstance(w, QTensor):
        return jnp.einsum(spec, x, w)
    y = jnp.einsum(spec, x, w.q.astype(x.dtype))
    ins, out = spec.split("->")
    wsub = ins.split(",")[1]
    kept = [l for l in out if l in wsub]
    # the reduced labels all have extent 1 in the scale, so this einsum is a
    # squeeze+permute into output label order
    s = jnp.einsum(f"{wsub}->{''.join(kept)}", w.s)
    shape = [s.shape[kept.index(l)] if l in kept else 1 for l in out]
    return y * s.reshape(shape).astype(x.dtype)


_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
     "w_gate_e", "w_up_e", "w_down_e", "lm_head"}
)


def quantizable(key: str) -> bool:
    """Whether a params-pytree leaf (by last path segment) should be int8.

    Norms and the router stay high precision (tiny, accuracy-critical); the
    embedding stays bf16 because it is read by gather, not matmul.
    """
    return key.rsplit(".", 1)[-1] in _QUANT_KEYS


def quantize_params(params: dict, device: bool = False, mode: str = "int8",
                    group: int = 32) -> dict:
    """Quantize every eligible leaf of a materialized params pytree.

    ``mode``: "int8" (per-output-channel QTensor) or "int4" (grouped
    QTensor4, ``group`` rows per scale/zero-point).
    """
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown weight quant mode: {mode!r}")

    def quant_one(v):
        if mode == "int4":
            return quantize_weight4(v if device else np.asarray(v),
                                    group=group, device=device)
        return quantize_weight(v if device else np.asarray(v), device=device)

    def walk(node: dict, prefix: str = "") -> dict:
        out = {}
        for k, v in node.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                out[k] = walk(v, f"{path}.")
            elif quantizable(path) and not isinstance(v, (QTensor, QTensor4)):
                out[k] = quant_one(v)
            else:
                out[k] = v
        return out

    return walk(params)

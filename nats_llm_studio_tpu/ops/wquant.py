"""Weight-only int8 quantization for serving.

Decode throughput is bound by streaming the weights from HBM once per step
(SURVEY.md §7 hard part #5); storing matmul weights as int8 with a
per-output-channel scale halves that traffic vs bf16 and is what makes
Llama-3-70B fit on a v5e-8 (BASELINE.md config 3: 8 x 16 GB HBM cannot hold
140 GB of bf16). The reference gets the same capability from llama.cpp's
quantized GGUF kernels inside LM Studio (/root/reference/README.md:3-7);
here it is a first-class device representation, not a file format.

``QTensor`` is a pytree (int8 codes + broadcastable scale), so quantized
params flow through jit / lax.scan / shard_map unchanged — scan slices the
leading [L] axis off both leaves. ``mm``/``q_einsum`` dequantize on the fly:
XLA fuses convert(s8->bf16)*scale into the matmul's operand read, so HBM
moves int8 bytes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclass
class QTensor:
    """Symmetric per-output-channel int8 weight: ``w ≈ q * s``.

    q: int8, the original weight shape [..., in, out]
    s: f32, [..., 1, out] — broadcastable over the contraction axis
    """

    q: jax.Array
    s: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.s).astype(dtype)


def quantize_weight(w: np.ndarray | jax.Array, device: bool = False) -> QTensor:
    """Symmetric absmax int8 over the contraction (second-to-last) axis.

    Host-side NumPy by default so the streaming 70B loader can quantize one
    tensor at a time without touching the device; ``device=True`` runs the
    same math in jnp for already-placed arrays.
    """
    xp = jnp if device else np
    w = w if device else np.asarray(w, dtype=np.float32)
    amax = xp.max(xp.abs(w.astype(xp.float32) if device else w), axis=-2, keepdims=True)
    s = amax / 127.0
    safe = xp.where(s == 0, 1.0, s)
    q = xp.clip(xp.round(w / safe), -127, 127).astype(xp.int8)
    return QTensor(q=q, s=safe.astype(xp.float32))


def mm(x: jax.Array, w) -> jax.Array:
    """``x @ w`` for plain arrays or QTensor (dequant-in-matmul)."""
    if isinstance(w, QTensor):
        y = jnp.matmul(x, w.q.astype(x.dtype))
        return y * w.s.astype(x.dtype)
    return x @ w


def q_einsum(spec: str, x: jax.Array, w) -> jax.Array:
    """``einsum(spec, x, w)`` with QTensor support.

    Requires the weight's contraction axis to be its second-to-last (where
    the scale has extent 1). The scale is permuted/broadcast to the output
    label order, so any output layout works ("btd,edf->btef",
    "ecd,edf->ecf", ...).
    """
    if not isinstance(w, QTensor):
        return jnp.einsum(spec, x, w)
    y = jnp.einsum(spec, x, w.q.astype(x.dtype))
    ins, out = spec.split("->")
    wsub = ins.split(",")[1]
    kept = [l for l in out if l in wsub]
    # the reduced labels all have extent 1 in the scale, so this einsum is a
    # squeeze+permute into output label order
    s = jnp.einsum(f"{wsub}->{''.join(kept)}", w.s)
    shape = [s.shape[kept.index(l)] if l in kept else 1 for l in out]
    return y * s.reshape(shape).astype(x.dtype)


_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
     "w_gate_e", "w_up_e", "w_down_e", "lm_head"}
)


def quantizable(key: str) -> bool:
    """Whether a params-pytree leaf (by last path segment) should be int8.

    Norms and the router stay high precision (tiny, accuracy-critical); the
    embedding stays bf16 because it is read by gather, not matmul.
    """
    return key.rsplit(".", 1)[-1] in _QUANT_KEYS


def quantize_params(params: dict, device: bool = False) -> dict:
    """Quantize every eligible leaf of a materialized params pytree."""

    def walk(node: dict, prefix: str = "") -> dict:
        out = {}
        for k, v in node.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                out[k] = walk(v, f"{path}.")
            elif quantizable(path) and not isinstance(v, QTensor):
                out[k] = quantize_weight(
                    v if device else np.asarray(v), device=device
                )
            else:
                out[k] = v
        return out

    return walk(params)

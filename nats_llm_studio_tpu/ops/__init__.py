"""Numeric building blocks (pure JAX + Pallas TPU kernels).

The reference has no tensor code at all — every FLOP lives in the external
llama.cpp engine (/root/reference/README.md:3-7). These ops are the in-tree
replacement, written TPU-first: bf16 matmuls for the MXU, f32 accumulation
for softmax/norms, static shapes, no data-dependent control flow under jit.
"""

from .layers import apply_rope, gqa_attention, rms_norm, rope_cos_sin, swiglu

__all__ = ["rms_norm", "rope_cos_sin", "apply_rope", "gqa_attention", "swiglu"]

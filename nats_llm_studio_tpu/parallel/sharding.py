"""NamedSharding rules for the stacked-params pytree.

Megatron-style TP (BASELINE.md config 3: Llama-3-70B TP=8 on v5e-8): QKV and
FFN-in sharded on their output-features axis, attn-out and FFN-down on their
input axis — so each block does local matmuls and GSPMD inserts exactly one
all-reduce after attention and one after the MLP. Experts shard on the ep
axis (config 4: Mixtral). The KV cache shards heads on tp, batch on dp, and
the sequence axis on sp (ring attention; SURVEY.md §5).

Weights keep a leading [L] stack axis (lax.scan), so every rule below starts
with None for L.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..ops.wquant import QTensor, QTensor4
from .mesh import AXIS_DP, AXIS_EP, AXIS_PP, AXIS_SP, AXIS_TP


def _axis(mesh: Mesh, name: str) -> str | None:
    """Use an axis only if the mesh has it with size > 1."""
    return name if name in mesh.axis_names and mesh.shape[name] > 1 else None


def kv_replicated(mesh: Mesh, cfg: ModelConfig) -> bool:
    """True when the GQA replicated-KV fallback is active: tp exceeds the
    KV head count (so the cache heads axis cannot shard) but still divides
    the query heads — wq/wo and the FFN shard normally while wk/wv and the
    KV cache stay replicated. Small KV trees make this a good trade: a
    Llama-3-8B's 8 KV heads on a tp=16 pod replicate ~1/9 of the weight
    bytes to keep 16-way sharding on the other 8/9."""
    tp = mesh.shape.get(AXIS_TP, 1)
    return tp > 1 and cfg.n_kv_heads % tp != 0 and tp > cfg.n_kv_heads \
        and cfg.n_heads % tp == 0


def param_sharding_rules(mesh: Mesh, cfg: ModelConfig | None = None) -> dict[str, P]:
    """PartitionSpec per params-pytree key (blocks.* keys are the stacked
    per-layer weights). The leading [L] stack axis shards on pp (pipeline
    stages own contiguous layer slices — parallel/pipeline.py).

    With ``cfg``, GQA models whose KV head count tp cannot divide get the
    replicated-KV fallback (``kv_replicated``): wk/wv/bk/bv stay whole per
    chip so the KV cache's heads axis can too."""
    tp = _axis(mesh, AXIS_TP)
    ep = _axis(mesh, AXIS_EP)
    pp = _axis(mesh, AXIS_PP)
    kv = None if cfg is not None and kv_replicated(mesh, cfg) else tp
    return {
        "embed": P(None, None),  # replicated: read once per token, cheap
        "out_norm": P(None),
        "lm_head": P(None, tp),  # vocab-sharded logits; argmax/sample gathers
        "blocks.attn_norm": P(pp, None),
        "blocks.ffn_norm": P(pp, None),
        "blocks.wq": P(pp, None, tp),
        "blocks.wk": P(pp, None, kv),
        "blocks.wv": P(pp, None, kv),
        "blocks.wo": P(pp, tp, None),
        "blocks.bq": P(pp, tp),  # qwen2 QKV biases: output-feature sharded
        "blocks.bk": P(pp, kv),
        "blocks.bv": P(pp, kv),
        "blocks.w_gate": P(pp, None, tp),
        "blocks.w_up": P(pp, None, tp),
        "blocks.w_down": P(pp, tp, None),
        "blocks.router": P(pp, None, None),
        "blocks.w_gate_e": P(pp, ep, None, tp),
        "blocks.w_up_e": P(pp, ep, None, tp),
        "blocks.w_down_e": P(pp, ep, tp, None),
    }


def scale_spec(weight_spec: P) -> P:
    """Spec for a QTensor's per-output-channel scale [..., 1, out]: same as
    the weight's but with the contraction (second-to-last) axis unsharded —
    the scale has extent 1 there."""
    parts = list(weight_spec) + [None] * (2 - len(weight_spec))
    parts[-2] = None
    return P(*parts)


def _flatten_keys(params: dict[str, Any], prefix: str = "") -> dict[str, Any]:
    out = {}
    for k, v in params.items():
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_keys(v, f"{path}."))
        else:
            out[path] = v
    return out


def shard_params(params: dict[str, Any], mesh: Mesh,
                 cfg: ModelConfig | None = None) -> dict[str, Any]:
    """device_put every leaf with its rule (replicated if no rule matches).

    For giant checkpoints prefer loading shard-by-shard (store/loader);
    this helper is for params already materialized on host. Pass ``cfg``
    to honor the replicated-KV GQA fallback (``kv_replicated``).
    """
    rules = param_sharding_rules(mesh, cfg)

    def place(path: str, leaf):
        spec = rules.get(path, P())
        if isinstance(leaf, QTensor):
            return QTensor(
                q=jax.device_put(leaf.q, NamedSharding(mesh, spec)),
                s=jax.device_put(leaf.s, NamedSharding(mesh, scale_spec(spec))),
            )
        if isinstance(leaf, QTensor4):
            # grouped int4: the packed codes [..., in/2, out] and the
            # per-group scale/zero [..., in/group, out] all keep the
            # weight's own spec — unlike the int8 scale (extent 1 on the
            # contraction axis), the grouped axis has real extent and
            # shards exactly as the contraction axis does
            sh = NamedSharding(mesh, spec)
            return QTensor4(
                q=jax.device_put(leaf.q, sh),
                s=jax.device_put(leaf.s, sh),
                z=jax.device_put(leaf.z, sh),
                group=leaf.group,
            )
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    def walk(node: dict[str, Any], prefix: str = "") -> dict[str, Any]:
        out = {}
        for k, v in node.items():
            path = f"{prefix}{k}"
            out[k] = walk(v, f"{path}.") if isinstance(v, dict) else place(path, v)
        return out

    return walk(params)


def cache_spec(mesh: Mesh, cfg: ModelConfig | None = None) -> P:
    """KV cache [B, L, Hkv, S, D]: batch on dp, layers on pp, heads on tp,
    sequence on sp (the ring-attention axis — long prompts' cache memory
    scales down with the sp degree; SURVEY.md §5 long-context). With
    ``cfg``, the heads axis drops tp under the replicated-KV GQA fallback
    (``kv_replicated``) — the cache must mirror wk/wv's sharding or every
    write would be a resharding collective."""
    tp = _axis(mesh, AXIS_TP)
    if cfg is not None and kv_replicated(mesh, cfg):
        tp = None
    return P(
        _axis(mesh, AXIS_DP), _axis(mesh, AXIS_PP), tp,
        _axis(mesh, AXIS_SP), None,
    )


def row_cache_spec(mesh: Mesh, cfg: ModelConfig | None = None) -> P:
    """Transient prefill row caches and prefix-cache blocks
    [m, L, Hkv, S', D]: heads on tp only. The batch axis is often 1 and S'
    a prompt bucket, so dp/sp cannot apply; pp never serves the dense
    path. Same KV-head rule as ``cache_spec`` so block copy-ins between a
    row cache and the serving ring never reshard."""
    tp = _axis(mesh, AXIS_TP)
    if cfg is not None and kv_replicated(mesh, cfg):
        tp = None
    return P(None, None, tp, None, None)


def pool_spec(mesh: Mesh, cfg: ModelConfig | None = None) -> P:
    """The paged KV block pool [NB, L, Hkv, T, D]: KV heads on tp, every
    other axis replicated. The block axis stays unsharded — block ids are
    global, so a gather of any slot's table lands on the device that owns
    the same head shard, and pool<->view moves never reshard. Same
    replicated-KV fallback rule as ``cache_spec``; the axis layout matches
    ``row_cache_spec`` (heads at index 2) by construction."""
    return row_cache_spec(mesh, cfg)


def shard_cache(k_cache, v_cache, mesh: Mesh, cfg: ModelConfig | None = None,
                spec: P | None = None):
    from ..ops.kvcache import KVQ, is_quantized

    if spec is None:
        spec = cache_spec(mesh, cfg)
    sh = NamedSharding(mesh, spec)
    # quantized caches: codes take the full cache spec, scales drop the
    # trailing head_dim axis
    sh_scale = NamedSharding(mesh, P(*list(spec)[:-1]))

    def put(c):
        if is_quantized(c):
            return KVQ(q=jax.device_put(c.q, sh), s=jax.device_put(c.s, sh_scale))
        return jax.device_put(c, sh)

    return put(k_cache), put(v_cache)


def batch_spec(mesh: Mesh) -> P:
    """Token/position arrays [B, ...]: batch on dp."""
    return P(_axis(mesh, AXIS_DP))


def validate_mesh_for_config(mesh: Mesh, cfg: ModelConfig,
                             allow_pp: bool = False) -> None:
    """Fail fast on indivisible shardings instead of cryptic XLA errors.

    ``allow_pp``: only callers that actually route through
    ``parallel.pipeline.pipeline_forward`` may accept a pp axis. The dense
    ``models.llama.forward`` over pp-sharded weights would not error — GSPMD
    would silently all-gather every layer's weights per step — so the
    serving path (default) rejects pp loudly instead."""
    if not allow_pp and mesh.shape.get(AXIS_PP, 1) > 1:
        raise ValueError(
            "mesh has a pp axis but this path runs the dense forward; "
            "pipeline parallelism is served by parallel.pipeline."
            "pipeline_forward (use tp/dp/sp/ep for the serving mesh)"
        )
    tp = mesh.shape.get(AXIS_TP, 1)
    ep = mesh.shape.get(AXIS_EP, 1)
    # every message names the FULL axis factoring, not just the failing
    # axis — a multi-axis mesh ("dp=2,ep=2,tp=2") read back as bare "tp=2"
    # sends the operator hunting the wrong knob
    factoring = ",".join(f"{k}={v}" for k, v in dict(mesh.shape).items())
    where = f"unservable on this mesh ({factoring})"
    if cfg.n_heads % tp and tp > 1:
        raise ValueError(
            f"{where}: n_heads={cfg.n_heads} not divisible by tp={tp}"
        )
    if cfg.n_kv_heads % tp and tp > 1 and not kv_replicated(mesh, cfg):
        # tp > n_kv_heads with tp | n_heads is served via the replicated-KV
        # fallback (kv_replicated); anything else has no clean layout
        raise ValueError(
            f"{where}: n_kv_heads={cfg.n_kv_heads} not "
            f"divisible by tp={tp} (replicated-KV fallback needs "
            f"tp > n_kv_heads and tp | n_heads={cfg.n_heads})"
        )
    if cfg.d_ff % tp and tp > 1:
        raise ValueError(
            f"{where}: d_ff={cfg.d_ff} not divisible by tp={tp}"
        )
    if cfg.is_moe and ep > 1 and cfg.n_experts % ep:
        raise ValueError(
            f"{where}: n_experts={cfg.n_experts} not divisible by ep={ep}"
        )
    if ep > 1 and not cfg.is_moe:
        raise ValueError(
            f"{where}: mesh has an ep axis but the model is dense "
            f"(n_experts=0) — nothing shards on ep"
        )
    sp = mesh.shape.get(AXIS_SP, 1)
    if sp > 1 and cfg.max_seq_len % sp:
        raise ValueError(
            f"{where}: max_seq_len={cfg.max_seq_len} not divisible by sp={sp}"
        )
    pp = mesh.shape.get(AXIS_PP, 1)
    if pp > 1 and cfg.n_layers % pp:
        raise ValueError(
            f"{where}: n_layers={cfg.n_layers} not divisible by pp={pp}"
        )
